"""Segment plan execution: run the device kernel, finish results host-side.

Parity: the operator-tree execution in pinot-core (Plan.execute →
InstanceResponseOperator.nextBlock, SURVEY.md §3.2) collapsed into one device
call + exact host finishing (histogram·dictionary dots in f64, dictId→value
decodes, group-key mixed-radix decode).
"""
from __future__ import annotations

import time
from typing import Dict, List, Tuple

import jax
import numpy as np

from pinot_tpu.analysis.runtime import debug_transfer_guard
from pinot_tpu.obs.profiler import profiled_device_get
from pinot_tpu.ops import kernels
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.segment.loader import ImmutableSegment


def _count_filter_leaves(spec) -> int:
    if spec is None or spec[0] in ("match_all", "empty"):
        return 0
    if spec[0] in ("and", "or"):
        return sum(_count_filter_leaves(c) for c in spec[1])
    if spec[0] == "pred" and spec[1] in ("vdoc", "ivf_probe"):
        return 0      # engine-injected (upsert mask / ANN probe), not a
    return 1          # query leaf


def gather_operands_for(segment, needed_cols) -> Dict[str, object]:
    cols: Dict[str, object] = {}
    for col, kind in needed_cols:
        if kind == "vdoc":
            # upsert validDocIds: a pseudo-column liveness lane served
            # by the segment itself (version-cached device upload)
            cols[f"{col}.vdoc"] = segment.device_valid_lane()
            continue
        ds = segment.data_source(col)
        if kind == "ids":
            cols[f"{col}.ids"] = ds.device_dict_ids()
        elif kind == "vals":
            cols[f"{col}.vals"] = ds.device_dict_values()
        elif kind == "raw":
            cols[f"{col}.raw"] = ds.device_raw_values()
        elif kind == "mv":
            cols[f"{col}.mv"] = ds.device_mv_dict_ids()
        elif kind == "parts":
            cols[f"{col}.parts"] = ds.device_part_lanes()
        elif kind == "vlane":
            cols[f"{col}.vlane"] = ds.device_value_lane()
        elif kind == "vec":
            cols[f"{col}.vec"] = ds.device_vec_values()
        elif kind == "ivfa":
            cols[f"{col}.ivfa"] = ds.device_ivf_assign()
        elif kind == "ivfc":
            cols[f"{col}.ivfc"] = ds.device_ivf_centroids()
        elif kind == "ivfv":
            cols[f"{col}.ivfv"] = ds.device_ivf_valid()
        elif kind == "hllidx":
            cols[f"{col}.hllidx"] = ds.device_hll_idx()
        elif kind == "hllrank":
            cols[f"{col}.hllrank"] = ds.device_hll_rank()
    return cols


def gather_operands(plan) -> Dict[str, object]:
    return gather_operands_for(plan.segment, plan.needed_cols)


def execute_segment_plan(plan) -> IntermediateResultsBlock:
    if plan.fast_path_result is not None:
        return plan.fast_path_result
    # PINOT_TPU_DEBUG_TRANSFERS=1 turns any implicit device→host pull in
    # the dispatch/finish path below into an error at the offending call
    # site (the explicit batched jax.device_get per dispatch still works)
    with debug_transfer_guard():
        return _execute_segment_plan(plan)


def _execute_segment_plan(plan) -> IntermediateResultsBlock:
    segment = plan.segment
    t0 = time.perf_counter()
    cols = gather_operands(plan)
    from pinot_tpu.query.plan import drive_group_execution

    def run(agg_specs, group_spec, extra_params=()):
        # returns DEVICE outs; each driver batches the device→host pull
        # into one explicit jax.device_get per dispatch (tpulint
        # host-sync: never per-scalar)
        return kernels.run_segment_kernel(
            segment.padded_docs, plan.filter_spec, agg_specs,
            group_spec, plan.select_spec, cols,
            tuple(plan.params) + tuple(extra_params),
            segment.num_docs)

    blk = IntermediateResultsBlock()
    if plan.group_spec is not None:
        outs, spec_used = drive_group_execution(run, plan.group_spec,
                                                segment.padded_docs,
                                                segment.num_docs)
        if spec_used is None:
            blk.group_map = {}
        else:
            _finish_group_by(_with_group_spec(plan, spec_used), outs, blk)
    else:
        # profiled twin of jax.device_get: counts the dispatch and the
        # host-side bytes on the ambient query profile
        outs = profiled_device_get(run(plan.agg_specs, None, ()))
        if plan.agg_specs:
            _finish_aggregation(plan, outs, blk)
    matched = int(outs["stats.num_docs_matched"])
    if plan.select_spec is not None:
        if plan.select_spec[0] == "vector":
            _finish_vector(plan, outs, blk, matched)
        else:
            _finish_selection(plan, outs, blk, matched)

    n_leaves = _count_filter_leaves(plan.filter_spec)
    n_project = len({c for c, _ in plan.needed_cols})
    blk.stats = ExecutionStats(
        num_docs_scanned=matched,
        num_entries_scanned_in_filter=n_leaves * segment.num_docs,
        num_entries_scanned_post_filter=matched * max(n_project - n_leaves, 0),
        num_segments_processed=1,
        num_segments_matched=1 if matched else 0,
        total_docs=segment.num_docs,
        time_used_ms=(time.perf_counter() - t0) * 1e3)
    return blk


def execute_segment_plans_batched(plans) -> List[IntermediateResultsBlock]:
    """One device dispatch serves N plans over ONE segment.

    Callers guarantee every plan shares a batch_signature (equal
    compiled specs, same segment — query/plan.py:batch_signature): the
    column lanes are gathered once and shared across the vmap lanes,
    each member contributes its params to the stacked leading axis, and
    the outputs are sliced back per member and fed through the same
    host finishers the sequential path uses — which is why batched and
    sequential results agree bit-for-bit on every path the coalescer
    admits (pinned by the contract tier).
    """
    if len(plans) == 1:
        return [execute_segment_plan(plans[0])]
    lead = plans[0]
    segment = lead.segment
    t0 = time.perf_counter()
    with debug_transfer_guard():
        cols = gather_operands(lead)
        if lead.params:
            outs_b = profiled_device_get(kernels.run_segment_kernel_batched(
                segment.padded_docs, lead.filter_spec, lead.agg_specs,
                lead.select_spec, cols,
                [tuple(p.params) for p in plans], segment.num_docs))
            per_member = [{k: v[b] for k, v in outs_b.items()}
                          for b in range(len(plans))]
        else:
            # param-free same-signature plans are identical programs:
            # one unbatched dispatch, every member reads the same outs
            outs1 = profiled_device_get(kernels.run_segment_kernel(
                segment.padded_docs, lead.filter_spec, lead.agg_specs,
                None, lead.select_spec, cols, (), segment.num_docs))
            per_member = [outs1] * len(plans)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    n_leaves = _count_filter_leaves(lead.filter_spec)
    n_project = len({c for c, _ in lead.needed_cols})
    blocks = []
    for plan, outs in zip(plans, per_member):
        blk = IntermediateResultsBlock()
        if plan.agg_specs:
            _finish_aggregation(plan, outs, blk)
        matched = int(outs["stats.num_docs_matched"])
        if plan.select_spec is not None:
            if plan.select_spec[0] == "vector":
                _finish_vector(plan, outs, blk, matched)
            else:
                _finish_selection(plan, outs, blk, matched)
        # the dispatch was shared; each member reports the batch wall
        # time (it really waited that long) and its own scan stats
        blk.stats = ExecutionStats(
            num_docs_scanned=matched,
            num_entries_scanned_in_filter=n_leaves * segment.num_docs,
            num_entries_scanned_post_filter=matched * max(
                n_project - n_leaves, 0),
            num_segments_processed=1,
            num_segments_matched=1 if matched else 0,
            total_docs=segment.num_docs,
            time_used_ms=elapsed_ms)
        blocks.append(blk)
    return blocks


# ---------------------------------------------------------------------------


def _finish_aggregation(plan, outs, blk) -> None:
    inters: List = []
    for i, (f, spec) in enumerate(zip(plan.functions, plan.agg_specs)):
        fname, col, source, extra = spec
        base = f.info.base
        strategy = extra[0] if isinstance(extra, tuple) else None
        if fname in ("count", "countmv"):
            inters.append(int(outs[f"agg{i}"]))
        elif fname == "hll":
            # device-built sketch registers ([m] int32, already maxed
            # across shards on the sharded path) → the HyperLogLog
            # intermediate every combine/reduce layer merges by
            # register max
            from pinot_tpu.common.sketches import (DEFAULT_LOG2M,
                                                   HyperLogLog)
            regs = np.asarray(outs[f"agg{i}.hll"]).astype(np.uint8)
            inters.append(HyperLogLog(DEFAULT_LOG2M, regs))
        elif source == "sv" and fname in ("sum", "avg") and \
                strategy in ("parts", "vlane"):
            cnt = int(outs[f"agg{i}.count"])
            if strategy == "parts":
                n_parts, min_v = \
                    plan.segment.data_source(col).int_part_info()
                if f"agg{i}.parts" in outs:
                    # [..., n_parts] fully device-reduced sums
                    arr = np.asarray(outs[f"agg{i}.parts"]).astype(
                        np.int64).reshape(-1, n_parts).sum(axis=0)
                else:
                    # oversized-segment fallback: [..., n_parts, T]
                    # block partials, exact int64 combine
                    arr = np.asarray(outs[f"agg{i}.partsT"]).astype(
                        np.int64)
                    arr = arr.reshape(-1, n_parts, arr.shape[-1]).sum(
                        axis=(0, 2))
                s = float(sum(int(arr[k]) << (7 * k)
                              for k in range(n_parts)) + min_v * cnt)
            else:
                s = float(np.asarray(outs[f"agg{i}.vsum"],
                                     dtype=np.float64).sum())
            inters.append(s if fname == "sum" else (s, cnt))
        elif fname == "hist":
            # expression aggregation: transform the dictionary value table
            # (O(cardinality)) and finish from the device histogram
            from pinot_tpu.common import expression as expr_mod
            src_vals = np.asarray(
                plan.segment.data_source(col).dictionary.values)
            tv = np.asarray(expr_mod.evaluate(f.column, lambda _: src_vals))
            inters.append(f.from_histogram(np.asarray(outs[f"agg{i}"]), tv))
        elif source in ("sv", "mv") and fname in (
                "sum", "avg", "percentile", "distinctcount"):
            ds = plan.segment.data_source(col)
            dict_vals = ds.dictionary.values
            if f.info.base == "FASTHLL" and \
                    getattr(ds.metadata, "derived_metric_type",
                            None) == "HLL":
                # derived serialized-HLL column (BrokerRequestPreProcessor
                # rewrite): union the sketches of present dictionary values
                from pinot_tpu.common.sketches import union_serialized_hlls
                hist = np.asarray(outs[f"agg{i}"])[: len(dict_vals)]
                inters.append(union_serialized_hlls(
                    np.asarray(dict_vals)[np.nonzero(hist)[0]]))
            else:
                inters.append(f.from_histogram(np.asarray(outs[f"agg{i}"]),
                                               dict_vals))
        elif source in ("sv", "mv") and fname in ("min", "max", "minmaxrange"):
            dict_vals = plan.segment.data_source(col).dictionary.values
            card = len(dict_vals)
            mn = outs.get(f"agg{i}.min")
            mx = outs.get(f"agg{i}.max")
            inters.append(f.from_minmax_ids(
                None if mn is None else int(mn),
                None if mx is None else int(mx), dict_vals))
        elif source == "raw":
            if fname == "sum":
                inters.append(float(np.asarray(outs[f"agg{i}.vsum"],
                                               dtype=np.float64).sum()))
            elif fname == "avg":
                inters.append((float(np.asarray(outs[f"agg{i}.vsum"],
                                                dtype=np.float64).sum()),
                               int(outs[f"agg{i}.count"])))
            elif fname in ("min", "max", "minmaxrange"):
                mn = outs.get(f"agg{i}.min")
                mx = outs.get(f"agg{i}.max")
                mn = None if mn is None or not np.isfinite(mn) else float(mn)
                mx = None if mx is None or not np.isfinite(mx) else float(mx)
                if fname == "min":
                    inters.append(mn)
                elif fname == "max":
                    inters.append(mx)
                else:
                    inters.append((mn, mx))
            else:
                raise ValueError(f"unexpected raw agg {fname}")
        else:
            raise ValueError(f"unexpected agg spec {spec}")
    blk.agg_intermediates = inters


def _with_group_spec(plan, spec_used):
    """Plan view for finishing: plans are cached per query shape, so a
    value-dependent (adaptive-remap) group spec must not mutate them."""
    if spec_used is plan.group_spec:
        return plan
    import copy
    p = copy.copy(plan)
    p.group_spec = spec_used
    return p


def _decode_group_values(plan, nz: np.ndarray) -> List[np.ndarray]:
    """Mixed-radix decode of group keys `nz` into per-column value arrays.

    Expression group keys decode through their transformed value table
    (collisions — distinct source ids mapping to one transformed value —
    merge in the assembly loop); raw-binned keys decode as (binId + min).
    """
    gcols, strides, _g_pad, _specs, _kmax = plan.group_spec
    cards = [entry[3] for entry in gcols]
    id_cols = []
    for stride, card in zip(strides, cards):
        id_cols.append((nz // stride) % card)
    vtables = plan.group_value_tables or (None,) * len(gcols)
    value_cols = []
    for (c, gkind, off, _card), ids, tv in zip(gcols, id_cols, vtables):
        if gkind == "idoff":
            ids = ids + off              # re-base adaptive-remapped ids
        elif gkind == "idrank":
            # densifying remap: `off` carries the present-id array; only
            # nonzero-count groups reach here, so every rank is in range
            ids = np.asarray(off)[ids]
        elif gkind in ("jcode", "jraw"):
            # join group codes ARE the dim value-table indices already;
            # the value table (dim uniques) decodes them below
            pass
        if tv is not None:
            value_cols.append(tv[ids])
        elif gkind == "rawoff":
            value_cols.append(ids.astype(np.int64) + off)
        else:
            value_cols.append(
                plan.segment.data_source(c).dictionary.decode(ids))
    return value_cols


def _decode_extreme_ids(plan, spec, arr: np.ndarray, which: str
                        ) -> np.ndarray:
    """dictId-domain per-group extrema → float values (inf when empty)."""
    _fname, col, source, extra = spec
    if source == "sv" and isinstance(extra, tuple) and extra[0] == "ids":
        vals = plan.segment.data_source(col).dictionary.values
        card = len(vals)
        if which == "min":
            valid = arr < card
            sentinel = np.inf
        else:
            valid = arr >= 0
            sentinel = -np.inf
        out = np.full(len(arr), sentinel)
        safe = np.clip(arr, 0, card - 1)
        out[valid] = np.asarray(vals, dtype=np.float64)[safe][valid]
        return out
    return arr


def _assemble_group_map(plan, blk, value_cols, per_agg_arrays,
                        n_groups: int) -> None:
    group_map: Dict[Tuple, List] = {}
    for row in range(n_groups):
        key = tuple(_plain(vc[row]) for vc in value_cols)
        inters: List = []
        for kind, a, b in per_agg_arrays:
            if kind == "count":
                inters.append(int(a[row]))
            elif kind == "sum":
                inters.append(float(a[row]))
            elif kind == "avg":
                inters.append((float(a[row]), int(b[row])))
            elif kind in ("min", "max"):
                v = float(a[row])
                inters.append(None if not np.isfinite(v) else v)
            else:  # minmaxrange
                mn, mx = float(a[row]), float(b[row])
                inters.append((None if not np.isfinite(mn) else mn,
                               None if not np.isfinite(mx) else mx))
        old = group_map.get(key)
        if old is not None:
            # expression group keys can collide (non-injective transform):
            # merge with the same semantics as cross-segment combine
            inters = [f.merge(o, v) for f, o, v in
                      zip(plan.functions, old, inters)]
        group_map[key] = inters
    blk.group_map = group_map


def _finish_group_by(plan, outs, blk) -> None:
    if "group.rkeys" in outs:
        _finish_group_by_ranked(plan, outs, blk)
        return
    gcols, strides, g_pad, agg_specs, kmax = plan.group_spec
    counts = np.asarray(outs["group.count"])
    nz = np.nonzero(counts)[0]
    value_cols = _decode_group_values(plan, nz)

    def _sum_array(i, spec):
        """Exact f64 per-group sums from the device partials."""
        fname, col, source, extra = spec
        strategy = extra[0] if isinstance(extra, tuple) else None
        # all arithmetic below runs on the non-empty groups only — the
        # full [G] tables can be millions of slots with a handful occupied
        if strategy == "psums" and f"gagg{i}.cpsums.lo" in outs:
            # sharded compacted path: 16-bit halves psum'd across segments,
            # recombined exactly here in int64
            lo = np.asarray(outs[f"gagg{i}.cpsums.lo"])[:, nz]
            hi = np.asarray(outs[f"gagg{i}.cpsums.hi"])[:, nz]
            arr = (hi.astype(np.int64) << 16) + lo.astype(np.int64)
        elif strategy == "psums" and f"gagg{i}.cpsums" in outs:
            # compacted path: scatter-combined int32 [n_parts, G], or
            # [n_chunks, n_parts, G] when kmax exceeded the per-scatter
            # int32 bound — recombine chunks exactly in int64 here
            a = np.asarray(outs[f"gagg{i}.cpsums"]).astype(np.int64)
            if a.ndim == 3:
                a = a.sum(axis=0)
            arr = a[:, nz]
        elif strategy == "psums":
            arr = np.asarray(outs[f"gagg{i}.psums"])[..., nz]
            if arr.ndim == 3:                  # sharded: [S, n_parts, nz]
                arr = arr.astype(np.int64).sum(0)
            arr = arr.astype(np.int64)
        elif strategy == "csums" and f"gagg{i}.csums" in outs:
            arr = np.asarray(outs[f"gagg{i}.csums"])[..., nz]
            if arr.ndim == 2:                  # sharded: [S, nz] — combine
                arr = arr.sum(0, dtype=np.float64)   # in f64 on host
            return arr.astype(np.float64)
        else:
            return np.asarray(outs[f"gagg{i}.sum"])[nz].astype(np.float64)
        _, min_v = plan.segment.data_source(col).int_part_info()
        shifts = np.left_shift(np.int64(1),
                               7 * np.arange(arr.shape[0], dtype=np.int64))
        totals = (arr * shifts[:, None]).sum(0)
        totals = totals + np.int64(min_v) * counts[nz].astype(np.int64)
        return totals.astype(np.float64)

    def _extreme_array(i, spec, which):
        """Per-group min/max as float values (inf sentinels when empty)."""
        arr = np.asarray(outs[f"gagg{i}.{which}"])[nz]
        return _decode_extreme_ids(plan, spec, arr, which)

    per_agg_arrays = []
    for i, spec in enumerate(agg_specs):
        fname = spec[0]
        if fname == "count":
            per_agg_arrays.append(("count", counts[nz], None))
        elif fname == "sum":
            per_agg_arrays.append(("sum", _sum_array(i, spec), None))
        elif fname == "avg":
            per_agg_arrays.append(("avg", _sum_array(i, spec), counts[nz]))
        elif fname == "min":
            per_agg_arrays.append(("min", _extreme_array(i, spec, "min"),
                                   None))
        elif fname == "max":
            per_agg_arrays.append(("max", _extreme_array(i, spec, "max"),
                                   None))
        elif fname == "minmaxrange":
            per_agg_arrays.append(("minmaxrange",
                                   _extreme_array(i, spec, "min"),
                                   _extreme_array(i, spec, "max")))
        else:
            raise ValueError(fname)

    _assemble_group_map(plan, blk, value_cols, per_agg_arrays, len(nz))


def _finish_group_by_ranked(plan, outs, blk) -> None:
    """Finish the ranked compacted group-by (kernels.py: wide-key layout).

    Per-segment tables are addressed by group RANK with a parallel key
    lane, so the cross-segment combine happens here: concatenate every
    segment's valid (key, partial) entries and merge them columnar via
    np.unique + np.add.at / minimum.at / maximum.at — the
    CombineGroupByOperator merge without the g_pad-sized tables.
    """
    gcols, strides, g_pad, agg_specs, kmax = plan.group_spec
    rkeys = np.asarray(outs["group.rkeys"])
    rcount = np.asarray(outs["group.rcount"])
    single = rkeys.ndim == 1
    if single:                               # single segment → [S=1, K]
        rkeys, rcount = rkeys[None], rcount[None]
    valid = rkeys < g_pad                    # [S, K]
    nz, inverse = np.unique(rkeys[valid], return_inverse=True)
    counts_nz = np.zeros(len(nz), np.int64)
    np.add.at(counts_nz, inverse, rcount[valid].astype(np.int64))
    value_cols = _decode_group_values(plan, nz)

    def _sum_array(i, spec):
        fname, col, source, extra = spec
        strategy = extra[0] if isinstance(extra, tuple) else None
        if strategy == "psums":
            a = np.asarray(outs[f"gagg{i}.rpsums"]).astype(np.int64)
            if single:                       # [P, K] or [C, P, K] chunked
                a = (a.sum(axis=0) if a.ndim == 3 else a)[None]
            elif a.ndim == 4:                # [S, C, P, K] chunked
                a = a.sum(axis=1)
            vals = np.moveaxis(a, 1, 2)[valid]          # [M, P]
            sums = np.zeros((len(nz), vals.shape[1]), np.int64)
            np.add.at(sums, inverse, vals)
            _, min_v = plan.segment.data_source(col).int_part_info()
            shifts = np.left_shift(
                np.int64(1), 7 * np.arange(sums.shape[1], dtype=np.int64))
            totals = (sums * shifts[None, :]).sum(1)
            return (totals + np.int64(min_v) * counts_nz).astype(np.float64)
        a = np.asarray(outs[f"gagg{i}.rsum"], dtype=np.float64)
        if a.ndim == 1:
            a = a[None]
        sums = np.zeros(len(nz), np.float64)
        np.add.at(sums, inverse, a[valid])
        return sums

    def _extreme_array(i, spec, which):
        a = np.asarray(outs[f"gagg{i}.r{which}"])
        if a.ndim == 1:
            a = a[None]
        if a.dtype.kind in "iu":             # dictId domain
            _fname, col, _source, extra = spec
            sentinel = extra[1] if which == "min" else -1
            out = np.full(len(nz), sentinel, np.int64)
            red = np.minimum if which == "min" else np.maximum
            red.at(out, inverse, a[valid].astype(np.int64))
            return _decode_extreme_ids(plan, spec, out, which)
        sentinel = np.inf if which == "min" else -np.inf
        out = np.full(len(nz), sentinel, np.float64)
        red = np.minimum if which == "min" else np.maximum
        red.at(out, inverse, a[valid].astype(np.float64))
        return out

    per_agg_arrays = []
    for i, spec in enumerate(agg_specs):
        fname = spec[0]
        if fname == "count":
            per_agg_arrays.append(("count", counts_nz, None))
        elif fname == "sum":
            per_agg_arrays.append(("sum", _sum_array(i, spec), None))
        elif fname == "avg":
            per_agg_arrays.append(("avg", _sum_array(i, spec), counts_nz))
        elif fname == "min":
            per_agg_arrays.append(("min", _extreme_array(i, spec, "min"),
                                   None))
        elif fname == "max":
            per_agg_arrays.append(("max", _extreme_array(i, spec, "max"),
                                   None))
        elif fname == "minmaxrange":
            per_agg_arrays.append(("minmaxrange",
                                   _extreme_array(i, spec, "min"),
                                   _extreme_array(i, spec, "max")))
        else:
            raise ValueError(fname)

    _assemble_group_map(plan, blk, value_cols, per_agg_arrays, len(nz))


def vector_segment_identity(segment) -> Tuple[str, int]:
    """(logical segment name, doc-id base) for vector result rows.

    A consuming segment's device snapshot (`__frozen`, rows [0, start))
    and host tail (`__tail`, rows [start, n)) are ONE logical segment:
    stripping the suffix and offsetting tail docids by `start` makes
    (name, $docId) identical to what a whole-segment host pass reports —
    the bit-identical-ids contract across host/device/sharded paths.
    """
    name = getattr(segment, "segment_name", "?")
    for suffix in ("__frozen", "__tail"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return name, int(getattr(segment, "start", 0) or 0)


def _decode_gather_columns(segment, gather_cols, outs, plain=None):
    """Per-column decoded value arrays for selection/vector gather lanes."""
    plain = plain or _plain
    col_values = []
    for col, source in gather_cols:
        ds = segment.data_source(col)
        lane = np.asarray(outs[f"sel.{col}"])
        if source == "sv":
            vals = ds.dictionary.decode(np.clip(lane, 0,
                                                ds.metadata.cardinality - 1))
        elif source == "raw":
            vals = lane
        else:  # mv: [k, W] padded ids
            card = ds.metadata.cardinality
            vals = [[plain(ds.dictionary.get(i)) for i in row if i < card]
                    for row in lane]
        col_values.append(vals)
    return col_values


def vector_result_rows(decode_segment, select_spec, outs,
                       seg_name: str, doc_base: int) -> List[tuple]:
    """Rows (user cols..., $docId, $segmentName, $score) from one
    segment's kernel outputs. `decode_segment` supplies the dictionary
    decode tables (the union view on the sharded path); name/base name
    the rows' identity."""
    _kind, _k, _order, gather_cols = select_spec
    docids = np.asarray(outs["sel.docids"])
    scores = np.asarray(outs["sel.scores"])
    col_values = _decode_gather_columns(decode_segment, gather_cols, outs)
    rows = []
    for r in range(len(docids)):
        if docids[r] < 0:
            continue
        rows.append(tuple(_plain(cv[r]) for cv in col_values) +
                    (int(docids[r]) + doc_base, seg_name,
                     float(scores[r])))
    return rows


def _finish_vector(plan, outs, blk, matched: int) -> None:
    from pinot_tpu.common.request import VECTOR_RESULT_COLUMNS
    name, base = vector_segment_identity(plan.segment)
    blk.selection_rows = vector_result_rows(plan.segment, plan.select_spec,
                                            outs, name, base)
    blk.selection_columns = [c for c, _ in plan.select_spec[3]] + \
        list(VECTOR_RESULT_COLUMNS)
    blk.selection_display_cols = None
    blk.stats.num_docs_scanned = matched


def _finish_selection(plan, outs, blk, matched: int) -> None:
    kind, k, order, gather_cols = plan.select_spec
    docids = np.asarray(outs["sel.docids"])
    valid = docids >= 0
    n = int(valid.sum())
    columns = [c for c, _ in gather_cols]
    col_values = _decode_gather_columns(plan.segment, gather_cols, outs)
    rows = []
    for r in range(len(docids)):
        if not valid[r]:
            continue
        rows.append(tuple(_plain(cv[r]) for cv in col_values))
    blk.selection_rows = rows
    blk.selection_columns = columns
    blk.selection_display_cols = plan.select_display
    blk.stats.num_docs_scanned = matched


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()  # tpulint: disable=host-sync -- np.generic scalar: isinstance-guarded, host value
    return v
