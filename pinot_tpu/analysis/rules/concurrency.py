"""concurrency: cross-thread attribute races, thread-entry-point aware.

v1 of this rule flagged EVERY unguarded mutation in a lock-free class —
which buried the real races under single-writer noise (26 of the 33
grandfathered findings were exactly that). v2 reasons about who can
actually run each method:

- A class's **thread roots** come from the callgraph thread-entry map:
  methods handed to `threading.Thread(target=...)` / `Timer`,
  `Executor.submit`, `run_in_executor` are SPAWNED (other-thread)
  roots; `async def` methods and loop-callback targets (`call_soon*`,
  `add_done_callback`) share the LOOP root; public methods carry an
  EXTERNAL root (scheduler pools, HTTP handler threads, watcher
  callbacks can all call them) — in addition to a spawn root when they
  are also a thread target. `__init__`-only helpers carry the `init`
  root (construction happens-before publish). Private methods inherit
  the roots of their in-class callers (fixpoint), so a `_flush`
  reachable only from the consume-loop thread carries exactly that one
  root.

- In a class that declares NO lock, a write to `self.X` is flagged when
  X is written from **two or more distinct writing methods spanning two
  or more roots** — or from ONE method that provably runs on two
  threads (spawn root plus another) — with no common lock. The
  single-writer invariant (one consumer thread mutating, all readers on
  snapshots; all writes funneling through one sole method) is VERIFIED
  by the analyzer instead of demanded as a suppression comment.

- In a class that DOES declare a lock, the lock is the author's own
  statement that the class is shared: every non-init mutation outside
  the lock is still flagged (v1 semantics), because a half-guarded
  class is worse than an unguarded one.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from pinot_tpu.analysis import astutil, callgraph
from pinot_tpu.analysis.core import Finding, Rule, register

_INIT_METHODS = callgraph.INIT_METHODS


def _self_attr_of_target(tgt: ast.AST) -> str:
    """'X' when tgt writes self.X or self.X[...]; '' otherwise."""
    if isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
        return tgt.attr
    return ""


def _write_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, ast.AugAssign):
        return [node.target]
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [node.target]
    return []


@register
class ConcurrencyRule(Rule):
    id = "concurrency"
    description = ("attributes of server/realtime classes written on "
                   ">=2 thread paths (or outside a declared lock) "
                   "without a common lock")

    def check(self, ctx) -> Iterator[Finding]:
        if not ctx.in_prefixes(ctx.config.concurrency_prefixes):
            return
        global_locks = callgraph.module_locks(ctx.tree, ctx.aliases)
        for model in callgraph.iter_class_models(ctx.tree, ctx.aliases):
            yield from self._check_class(ctx, model, global_locks)

    def _check_class(self, ctx, model: callgraph.ClassModel,
                     global_locks: Set[str]) -> Iterator[Finding]:
        cls = model.node.name
        locks = model.lock_attrs
        # attr → [(node, method, roots, held-locks)]
        writes: Dict[str, List[Tuple[ast.AST, str, frozenset,
                                     frozenset]]] = {}

        def method_roots(mname: str) -> frozenset:
            raw = set(model.roots.get(mname, ()))
            effective = raw - {"init"}
            if not effective and not raw:
                # uncalled private method: some other module calls it —
                # conservatively its own external root
                return frozenset({f"ext:{mname}"})
            return frozenset(effective)

        for mname, m in model.methods.items():
            if mname in _INIT_METHODS:
                # direct writes are construction-time, but a closure
                # DEFINED here and handed to a thread/loop API runs
                # post-publish — scan exactly those below
                self._scan_spawned_closures(ctx, model, mname, m,
                                            global_locks, writes)
                continue
            roots = method_roots(mname)
            if not roots:
                # reachable from __init__ only: direct writes are
                # construction-time, but thread/loop-handed closures
                # still escape construction — scan those
                self._scan_spawned_closures(ctx, model, mname, m,
                                            global_locks, writes)
                continue
            for site in callgraph.walk_with_locks(m, locks, global_locks):
                for tgt in _write_targets(site.node):
                    attr = _self_attr_of_target(tgt)
                    if attr and attr not in locks:
                        writes.setdefault(attr, []).append(
                            (site.node, mname, roots,
                             frozenset(site.held)))
            self._scan_spawned_closures(ctx, model, mname, m,
                                        global_locks, writes,
                                        roots=roots)
        yield from self._judge(ctx, cls, locks, writes)

    def _scan_spawned_closures(self, ctx, model: callgraph.ClassModel,
                               mname: str, m: ast.AST,
                               global_locks: Set[str], writes,
                               roots=None) -> None:
        """Record self-writes inside closures nested in `m`.

        Closures run LATER, on whatever thread they were handed to — a
        lock held at DEF time is not held at call time, so each closure
        body starts with an empty held set; locks the closure ITSELF
        takes do count (walk_with_locks starts fresh per function).
        `roots=None` means `m` is a construction method: only closures
        handed to a thread/loop API matter (anything else runs during
        construction, happens-before publish).
        """
        locks = model.lock_attrs
        spawned_here = callgraph.thread_spawned_callables(m, ctx.aliases)
        loop_here = callgraph.loop_callback_callables(m, ctx.aliases)
        # a closure whose name is only ever used as a direct `name()`
        # call never escapes the method: it runs inline, under whatever
        # locks its call sites hold — not a deferred callback, so the
        # empty-held-set policy is wrong for it and it is skipped like
        # any other inline code. For closures that DO escape through a
        # non-spawn call (sort key=, a retry wrapper), record the lock
        # set held at every escape site: if every hand-off happens
        # under a lock, the closure's writes inherit that guard (the
        # sort runs inline inside the with-block); one unlocked escape
        # drops the inheritance (conservative).
        direct_call_funcs = {id(c.func) for c in ast.walk(m)
                             if isinstance(c, ast.Call)}
        escape_held: Dict[str, List[frozenset]] = {}
        for site in callgraph.walk_with_locks(m, locks, global_locks):
            n = site.node
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and \
                    id(n) not in direct_call_funcs:
                escape_held.setdefault(n.id, []).append(
                    frozenset(site.held))
        for nd in ast.walk(m):
            if nd is m or not isinstance(
                    nd, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            where = f"{mname}.<{nd.name}>"
            inherited: frozenset = frozenset()
            if nd.name in spawned_here:
                nroots = frozenset({f"spawn:{where}"})
            elif nd.name in loop_here:
                nroots = frozenset({"loop"})
            elif roots is not None and nd.name in escape_held:
                nroots = roots
                inherited = frozenset.intersection(
                    *escape_held[nd.name])
            else:
                continue          # init-local or inline-only closure
            for site in callgraph.walk_with_locks(nd, locks,
                                                  global_locks):
                for tgt in _write_targets(site.node):
                    attr = _self_attr_of_target(tgt)
                    if attr and attr not in locks:
                        writes.setdefault(attr, []).append(
                            (site.node, where, nroots,
                             frozenset(site.held) | inherited))

    def _judge(self, ctx, cls: str, locks: Set[str],
               writes) -> Iterator[Finding]:
        if locks:
            # lock-declaring class: v1 semantics — every unguarded
            # non-init write is a finding
            for attr, sites in sorted(writes.items()):
                for node, mname, _roots, held in sites:
                    if not held:
                        yield ctx.finding(
                            self.id, node,
                            f"`{cls}.{mname}` mutates self.{attr} "
                            f"without holding {'/'.join(sorted(locks))}")
            return
        # lock-free class: flag an attribute when EITHER (a) it is
        # written from >=2 distinct WRITE paths (methods) reachable
        # from >=2 distinct thread roots, or (b) its sole writing
        # method is itself reachable from a spawned thread AND another
        # context (a public Thread-target: the same code provably runs
        # on two threads) — in both cases with no common held lock.
        # Pure ext-to-ext fan-in through one method (append→extend, a
        # lazy cache with one filler) stays the verified single-writer
        # pattern: the sole writer carries serialization structurally.
        for attr, sites in sorted(writes.items()):
            methods = {m for _n, m, _r, _h in sites}
            all_roots = sorted(set().union(*(r for _n, _m, r, _h
                                             in sites)))
            if len(all_roots) < 2:
                continue          # verified single-writer: one root
            if len(methods) < 2 and not any(
                    r.startswith("spawn:") for r in all_roots):
                continue          # sole writing method, no proven
                #                   second thread: structural fan-in
            common = frozenset.intersection(*(h for _n, _m, _r, h
                                              in sites))
            if common:
                continue          # a shared (module-level) lock guards
            paths = ", ".join(all_roots)
            for node, mname, _roots, _held in sites:
                yield ctx.finding(
                    self.id, node,
                    f"`{cls}.{mname}` writes self.{attr}, also written "
                    f"on other thread paths ({paths}) with no common "
                    "lock — add a lock or make one path the sole "
                    "writer")
