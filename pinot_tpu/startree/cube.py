"""Star-tree analogue: pre-aggregated cubes over dictId combinations.

Parity: pinot-core/.../core/startree/v2/ — StarTreeV2BuilderConfig
(dimensionsSplitOrder, functionColumnPairs, maxLeafRecords) and the
pre-aggregation the tree encodes. The TPU-idiomatic form drops the node
tree entirely: a cube is a *columnar grouped table* — one row per distinct
dictId combination of the configured dimensions, with materialized
count/sum/min/max stats per configured metric. Queries that only touch
cube dimensions and covered metrics run over n_groups rows instead of
n_docs (OffHeapStarTree.java:35-76's O(tree) skip becomes an O(groups)
columnar scan — groups are bounded at build time, typically 1000-100000x
smaller than the segment).

The cube's dimension lanes share the parent segment's dictionaries, so
every id-domain predicate the engine can resolve against the segment
resolves identically against the cube.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Dict, List, Optional

import numpy as np

STARTREE_META = "startree.{idx}.json"
STARTREE_DATA = "startree.{idx}.npz"
DEFAULT_MAX_GROUPS = 1 << 20


@dataclasses.dataclass
class StarTreeConfig:
    dimensions: List[str]                 # split order (all materialized)
    metrics: List[str]                    # metric columns with stats lanes
    max_groups: int = DEFAULT_MAX_GROUPS  # build refused above this

    @classmethod
    def from_json(cls, d: dict) -> "StarTreeConfig":
        metrics = []
        for pair in d.get("functionColumnPairs", d.get("metrics", [])):
            # "SUM__revenue" → revenue (the cube stores the full stat set)
            col = pair.split("__", 1)[1] if "__" in pair else pair
            if col not in metrics and col != "*":
                metrics.append(col)
        # NOTE: Pinot's maxLeafRecords is a node-SPLIT threshold, not a
        # size cap — a ported config's maxLeafRecords (default 10k) must
        # not disable cube builds, so only maxGroups/maxSize cap the build
        return cls(
            dimensions=list(d.get("dimensionsSplitOrder",
                                  d.get("dimensions", []))),
            metrics=metrics,
            max_groups=int(d.get("maxGroups",
                                 d.get("maxSize", DEFAULT_MAX_GROUPS))))

    def to_json(self) -> dict:
        return {"dimensionsSplitOrder": self.dimensions,
                "metrics": self.metrics, "maxSize": self.max_groups}


class StarTreeCube:
    """One materialized cube: dim id lanes + per-metric stat lanes."""

    def __init__(self, config: StarTreeConfig, n_groups: int,
                 dim_ids: Dict[str, np.ndarray],
                 counts: np.ndarray,
                 metric_stats: Dict[str, Dict[str, np.ndarray]]):
        self.config = config
        self.n_groups = n_groups
        self.dim_ids = dim_ids                  # col → int32 [n_groups]
        self.counts = counts                    # int64 [n_groups]
        self.metric_stats = metric_stats        # col → {sum,min,max}[n_groups]

    @property
    def dimensions(self) -> List[str]:
        return self.config.dimensions

    @property
    def metrics(self) -> List[str]:
        return self.config.metrics

    def save(self, seg_dir: str, idx: int) -> None:
        arrays = {"counts": self.counts}
        for d, ids in self.dim_ids.items():
            arrays[f"dim.{d}"] = ids
        for m, stats in self.metric_stats.items():
            for k, arr in stats.items():
                arrays[f"met.{m}.{k}"] = arr
        # data first, meta last: the .json is the commit marker, so a
        # crash mid-save never leaves a json pointing at a missing npz
        np.savez(os.path.join(seg_dir, STARTREE_DATA.format(idx=idx)),
                 **arrays)
        with open(os.path.join(seg_dir, STARTREE_META.format(idx=idx)),
                  "w") as fh:
            json.dump(self.config.to_json(), fh)

    @classmethod
    def load(cls, seg_dir, idx: int) -> "StarTreeCube":
        import io

        from pinot_tpu.segment import format as fmt
        d = fmt.open_dir(seg_dir)
        config = StarTreeConfig.from_json(json.loads(
            d.read_text(STARTREE_META.format(idx=idx))))
        data = np.load(io.BytesIO(
            d.read_bytes(STARTREE_DATA.format(idx=idx))))
        dim_ids = {d: data[f"dim.{d}"] for d in config.dimensions}
        metric_stats = {
            m: {k: data[f"met.{m}.{k}"] for k in ("sum", "min", "max")}
            for m in config.metrics}
        return cls(config, len(data["counts"]), dim_ids, data["counts"],
                   metric_stats)


def build_star_trees(segment, table_config) -> List[StarTreeCube]:
    """Materialize every configured cube from a loaded segment's host
    lanes. Parity: BaseSingleTreeBuilder — but a single vectorized
    group-by pass instead of a sort+split tree walk."""
    cubes: List[StarTreeCube] = []
    for raw_cfg in table_config.indexing_config.star_tree_configs or []:
        config = StarTreeConfig.from_json(raw_cfg) \
            if isinstance(raw_cfg, dict) else raw_cfg
        cube = _build_cube(segment, config)
        if cube is not None:
            cubes.append(cube)
    return cubes


def _build_cube(segment, config: StarTreeConfig
                ) -> Optional[StarTreeCube]:
    n = segment.num_docs
    if n == 0 or not config.dimensions:
        return None
    id_lanes = []
    cards = []
    for d in config.dimensions:
        if not segment.has_column(d):
            return None
        ds = segment.data_source(d)
        cm = ds.metadata
        if not (cm.has_dictionary and cm.single_value):
            return None                     # MV/raw dims unsupported
        id_lanes.append(ds.dict_ids.astype(np.int64))
        cards.append(cm.cardinality)
    if np.prod([float(c) for c in cards]) >= 2**62:
        return None                         # packed key would overflow
    key = np.zeros(n, dtype=np.int64)
    for lane, card in zip(id_lanes, cards):
        key = key * card + lane
    uniq, inverse = np.unique(key, return_inverse=True)
    g = len(uniq)
    if g > config.max_groups:
        return None                         # cube would not pay off

    dim_ids: Dict[str, np.ndarray] = {}
    rem = uniq.copy()
    for d, card in zip(reversed(config.dimensions), reversed(cards)):
        dim_ids[d] = (rem % card).astype(np.int32)
        rem //= card
    counts = np.zeros(g, dtype=np.int64)
    np.add.at(counts, inverse, 1)

    metric_stats: Dict[str, Dict[str, np.ndarray]] = {}
    for m in config.metrics:
        if not segment.has_column(m):
            return None
        ds = segment.data_source(m)
        cm = ds.metadata
        if not cm.single_value or not cm.data_type.is_numeric:
            return None
        if cm.has_dictionary:
            vals = np.asarray(ds.dictionary.values,
                              dtype=np.float64)[ds.dict_ids]
        else:
            vals = ds.raw_values.astype(np.float64)
        sums = np.zeros(g, dtype=np.float64)
        mins = np.full(g, np.inf)
        maxs = np.full(g, -np.inf)
        np.add.at(sums, inverse, vals)
        np.minimum.at(mins, inverse, vals)
        np.maximum.at(maxs, inverse, vals)
        metric_stats[m] = {"sum": sums, "min": mins, "max": maxs}
    return StarTreeCube(config, g, dim_ids, counts, metric_stats)


def build_and_save_star_trees(seg_dir: str, table_config) -> int:
    """Post-build hook: load the sealed segment, materialize + persist
    cubes next to it. Returns the number of cubes written."""
    if not (table_config and
            table_config.indexing_config.star_tree_configs):
        return 0
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    segment = ImmutableSegmentLoader.load(seg_dir)
    cubes = build_star_trees(segment, table_config)
    for i, cube in enumerate(cubes):
        cube.save(seg_dir, i)
    return len(cubes)


def load_star_trees(seg_dir) -> List[StarTreeCube]:
    from pinot_tpu.segment import format as fmt
    d = fmt.open_dir(seg_dir)
    cubes = []
    for meta_name in d.list(prefix="startree.", suffix=".json"):
        idx = int(meta_name.split(".")[1])
        try:
            cubes.append(StarTreeCube.load(d, idx))
        except Exception:  # noqa: BLE001 — an acceleration structure must
            # never brick the segment; skip the broken cube
            import logging
            logging.getLogger(__name__).warning(
                "skipping unloadable star-tree cube %d in %s", idx,
                d.path, exc_info=True)
    return cubes
