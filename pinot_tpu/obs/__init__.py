"""pinot_tpu/obs — end-to-end observability.

The instrumentation layer every perf PR reads:

- `tracing`: hierarchical distributed tracing (trace-id/span-id spans
  with parent links, Dapper-style), propagated broker→server inside
  `InstanceRequest` and merged into one trace tree at broker reduce.
- `profiler`: per-query operator profiling (docs scanned, cube-vs-scan
  path, device transfer bytes, kernel dispatch counts) aggregated into
  rolling per-table stats at the broker.
- `prometheus`: text exposition of a `MetricsRegistry` (the
  Monarch/Prometheus pull model; bounded log-scale histograms for
  timers) served from broker, server and controller `/metrics`.
- `slowlog`: sampling JSONL slow-query log with a threshold config.

See docs/OBSERVABILITY.md for the span model, metric naming rules,
exposition endpoints and the slow-log record format.
"""
from pinot_tpu.obs.tracing import (NoopTraceContext, TraceContext,  # noqa: F401
                                   build_trace_tree, make_trace_context)
from pinot_tpu.obs.profiler import (QueryProfile,                   # noqa: F401
                                    TableStatsAggregator)
from pinot_tpu.obs.prometheus import render_prometheus              # noqa: F401
from pinot_tpu.obs.slowlog import SlowQueryLog                      # noqa: F401
