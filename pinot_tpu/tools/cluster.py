"""Cluster harnesses: embedded (one process) and multi-process.

Parity: the reference's ClusterTest harness (pinot-integration-tests/.../
ClusterTest.java:85 — real Controller/Broker/Server instances in one JVM)
and the Quickstart wiring (tools/Quickstart.java:125-144). The full
production plumbing runs: property store, state transitions, deep store,
scatter-gather (in-process or TCP), broker reduce.

Membership churn is programmable — ``add_server()`` / ``remove_server()``
/ ``drain_server()`` — so chaos suites and scale-out benchmarks can grow,
kill and drain servers mid-workload (the ClusterTest analogue of the
reference's ChaosMonkey-style integration tests).

`MultiprocCluster` is the production shape: every plane its own OS
process via the admin CLI (StartStore / StartController / StartServer /
StartBroker / StartMinion), with chaos verbs that act on REAL processes
— ``kill_server`` is SIGKILL, ``drain_server`` is SIGTERM into the
admin CLI's drain handler, ``fail_controller`` SIGKILLs the ACTIVE
lead so the standby's lease takeover is what recovery measures, and
``net_latency``/``net_drop`` arm FaultInjectingTransport windows inside
the broker processes over their /debug/faults endpoints. It implements
the `common/chaos.py` adapter surface (verbs + ``targets`` +
``clear_fault`` + ``recovery_probe``), so a ChaosCoordinator drives it
directly.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from typing import Dict, List, Optional

from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
from pinot_tpu.broker.request_handler import (BrokerRequestHandler,
                                              InProcessTransport,
                                              TcpTransport)
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.participant import ServerParticipant


class EmbeddedCluster:
    """controller + num_servers query servers + one broker."""

    def __init__(self, work_dir: str, num_servers: int = 2,
                 tcp: bool = False, mesh=None, scheduler: str = "fcfs",
                 http: bool = False, store_dir: str = None,
                 server_max_pending: int = None,
                 cache_freshness_ms: float = None):
        """`store_dir`: persist cluster state (property-store WAL +
        snapshots) under this directory — a cluster rebuilt over the
        same work_dir/store_dir recovers its tables and segments."""
        from pinot_tpu.broker.quota import QueryQuotaManager
        self.work_dir = work_dir
        self._tcp = tcp
        self._mesh = mesh
        self._scheduler = scheduler
        self._http = http
        self._server_max_pending = server_max_pending
        self.controller = Controller(os.path.join(work_dir, "deepstore"),
                                     store_dir=store_dir)
        self.servers: Dict[str, ServerInstance] = {}
        self.participants: Dict[str, ServerParticipant] = {}
        if tcp:
            self.transport = TcpTransport({})
        else:
            # InProcessTransport shares the live server dict, so
            # add_server/remove_server mutate its view too
            self.transport = InProcessTransport(self.servers)
        # ONE quota manager shared by the watcher (which converges
        # table-config quotas into it) and the broker (which enforces)
        self.quota = QueryQuotaManager()
        self.watcher = BrokerClusterWatcher(self.controller.coordinator,
                                            self.controller.manager,
                                            quota=self.quota)
        self.broker = BrokerRequestHandler(
            self.watcher.routing, self.transport,
            time_boundary=self.watcher.time_boundary,
            quota=self.quota,
            segment_pruner=self.watcher.partition_pruner,
            cache_freshness_ms=cache_freshness_ms)
        # segment lifecycle (upload/replace/drop) flushes the broker
        # result cache — the freshness bound only covers consuming-
        # ingestion staleness, not an offline backfill
        self.watcher.register_result_cache(self.broker.result_cache)
        # a deregistered server's breaker/health state drops in the
        # same watch event as its live record
        self.watcher.attach_fault_tolerance(self.broker.fault_tolerance)
        self.broker_api = None
        self.controller_api = None
        self.server_apis: Dict[str, object] = {}
        self.broker_port: Optional[int] = None
        self.controller_port: Optional[int] = None
        self.server_http_ports: Dict[str, int] = {}
        for i in range(num_servers):
            self.add_server(f"Server_{i}")
        if http:
            from pinot_tpu.broker.http_api import BrokerApiServer
            from pinot_tpu.controller.http_api import ControllerApiServer
            self.broker_api = BrokerApiServer(self.broker)
            self.broker_port = self.broker_api.start()
            self.controller_api = ControllerApiServer(self.controller)
            self.controller_port = self.controller_api.start()

    # -- membership churn ---------------------------------------------------
    def add_server(self, name: Optional[str] = None) -> str:
        """Start a new query server, join it to the cluster (live
        record + state transitions), and wire it into the broker's
        data plane. Returns its instance id."""
        if name is None:
            i = len(self.servers)
            while f"Server_{i}" in self.servers:
                i += 1
            name = f"Server_{i}"
        if name in self.servers:
            raise ValueError(f"server {name} already exists")
        server = ServerInstance(name, scheduler=self._scheduler,
                                mesh=self._mesh,
                                max_pending=self._server_max_pending)
        participant = ServerParticipant(
            server, self.controller.manager,
            completion=self.controller.realtime,
            work_dir=os.path.join(self.work_dir, "server_work", name))
        self.servers[name] = server
        self.participants[name] = participant
        if self._tcp:
            port = server.start(port=0)
            self.transport.set_endpoint(name, "127.0.0.1", port)
        # registration LAST: the reconcile it triggers may immediately
        # assign segments / consuming partitions to the new server
        self.controller.coordinator.register_participant(name, participant)
        if self._http:
            from pinot_tpu.server.http_api import ServerApiServer
            api = ServerApiServer(server)
            self.server_apis[name] = api
            self.server_http_ports[name] = api.start()
        return name

    def remove_server(self, name: str) -> None:
        """Abrupt death (the embedded analogue of kill -9 / session
        expiry): the live record and current states vanish with no
        drain and no seal — the self-healing plane must repair."""
        server = self.servers.pop(name)
        participant = self.participants.pop(name)
        # ephemeral-loss first: views, routing, broker ft state all
        # react to the membership event while the "process" disappears
        self.controller.coordinator.deregister_participant(name)
        participant.shutdown()
        server.stop()
        api = self.server_apis.pop(name, None)
        if api is not None:
            api.stop()
        self.server_http_ports.pop(name, None)

    def drain_server(self, name: str, seal_timeout_s: float = 20.0,
                     settle_s: float = 0.3) -> bool:
        """Planned departure: seal consuming segments where possible,
        deregister (brokers reroute on the watch event), let in-flight
        work finish, then stop — zero query errors by construction.
        Returns whether every sealable consumer actually sealed."""
        import time
        server = self.servers[name]
        participant = self.participants[name]
        sealed = participant.seal_consuming(seal_timeout_s)
        self.controller.coordinator.deregister_participant(name)
        # the embedded watch chain is synchronous, but the broker's
        # in-flight scatters are not: hold the FULL settle window. A
        # depth()==0 early exit raced queries already scattered but not
        # yet admitted (in transit they hold no admission slot), so the
        # stop below turned them into execution errors on a loaded box.
        deadline = time.monotonic() + max(settle_s, 0.05)
        while time.monotonic() < deadline:
            time.sleep(0.02)
        while server.admission.depth() > 0 and \
                time.monotonic() < deadline + seal_timeout_s:
            time.sleep(0.02)
        # only NOW leave the transport's server map: the seal and the
        # settle window above still serve queries, and the in-process
        # transport shares self.servers — popping first turned routed
        # dispatches into KeyErrors during the seal
        self.servers.pop(name, None)
        self.participants.pop(name, None)
        participant.shutdown()
        server.stop()
        api = self.server_apis.pop(name, None)
        if api is not None:
            api.stop()
        self.server_http_ports.pop(name, None)
        return sealed

    # -- admin facade (parity: controller REST) ----------------------------
    def add_schema(self, schema: Schema) -> None:
        self.controller.manager.add_schema(schema)

    def add_table(self, config: TableConfig, **kw) -> str:
        from pinot_tpu.common.table_config import TableType
        if config.table_type == TableType.REALTIME:
            return self.controller.realtime.setup_table(config, **kw)
        return self.controller.manager.add_table(config, **kw)

    def upload_segment(self, table: str, segment_dir: str) -> str:
        return self.controller.manager.add_segment(table, segment_dir)

    def query(self, pql: str) -> BrokerResponse:
        return self.broker.handle(pql)

    def stop(self) -> None:
        if self.broker_api is not None:
            self.broker_api.stop()
        if self.controller_api is not None:
            self.controller_api.stop()
        for api in self.server_apis.values():
            api.stop()
        self.controller.stop()
        self.watcher.close()
        self.broker.close()
        for participant in self.participants.values():
            participant.shutdown()
        for server in self.servers.values():
            server.stop()


# ---------------------------------------------------------------------------
# multi-process cluster + chaos verbs
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _http_json(method: str, url: str, body: Optional[bytes] = None,
               ctype: str = "application/json", timeout: float = 60.0):
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": ctype} if body else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class MultiprocCluster:
    """The production process shape, drivable by a ChaosCoordinator.

    Topology: one StandaloneStore (the ZK role, outliving every
    controller), a lead + optional standby controller joined to it
    (``ha=True``), ``num_servers`` query servers with admin APIs,
    ``num_brokers`` HTTP brokers, and optionally one minion. Every
    component is its own OS process spawned through the admin CLI, so
    the chaos verbs below are real signals against real pids.

    ``broker_faults=True`` starts brokers with
    PINOT_TPU_BROKER_FAULTS=1: their data plane runs through a
    FaultInjectingTransport whose arm/clear surface is the broker's
    /debug/faults endpoints — that is how ``net_latency`` / ``net_drop``
    windows reach inside a real broker process.
    """

    def __init__(self, base: str, num_brokers: int = 1,
                 num_servers: int = 2, ha: bool = False,
                 minion: bool = False, lease_s: float = 2.0,
                 broker_faults: bool = False,
                 env: Optional[dict] = None):
        self.base = base
        self.ha = ha
        self.lease_s = lease_s
        self.broker_faults = broker_faults
        self._env = dict(os.environ, PYTHONPATH=_REPO)
        if env:
            self._env.update(env)
        os.makedirs(os.path.join(base, "logs"), exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}
        self.controllers: Dict[str, dict] = {}    # id -> {httpPort}
        self.server_admin_ports: Dict[str, int] = {}
        self.broker_ports: List[int] = []
        self.minion_ids: List[str] = []
        self._store_client = None

        if ha:
            boot = self._spawn("store", "StartStore",
                               "--dir", os.path.join(base, "storehost"),
                               "--store-port", "0")
            self.store_port = boot["storePort"]
            store_addr = f"127.0.0.1:{self.store_port}"
            lead = self._spawn(
                "controller:Controller_lead", "StartController",
                "--dir", os.path.join(base, "controller"),
                "--store-addr", store_addr,
                "--instance-id", "Controller_lead",
                "--lease-s", str(lease_s))
            self.deep_store = lead["deepStore"]
            self.controllers["Controller_lead"] = \
                {"httpPort": lead["httpPort"]}
            standby = self._spawn(
                "controller:Controller_standby", "StartController",
                "--dir", os.path.join(base, "controller"),
                "--store-addr", store_addr,
                "--instance-id", "Controller_standby", "--standby",
                "--lease-s", str(lease_s))
            self.controllers["Controller_standby"] = \
                {"httpPort": standby["httpPort"]}
        else:
            ctrl = self._spawn("controller:Controller_0",
                               "StartController",
                               "--dir", os.path.join(base, "controller"),
                               "--store-port", "0")
            self.store_port = ctrl["storePort"]
            self.deep_store = ctrl["deepStore"]
            self.controllers["Controller_0"] = \
                {"httpPort": ctrl["httpPort"]}
        self._store_addr = f"127.0.0.1:{self.store_port}"

        for i in range(num_servers):
            self.start_server(f"Server_{i}")
        for _ in range(num_brokers):
            self._start_broker()
        if minion:
            self.start_minion("Minion_0")

    # -- process plumbing --------------------------------------------------
    def _spawn(self, name: str, *cmd: str) -> dict:
        log = open(os.path.join(self.base, "logs",
                                f"{name.replace(':', '_')}.log"), "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "pinot_tpu.tools.admin", *cmd],
            stdout=subprocess.PIPE, stderr=log, env=self._env,
            cwd=_REPO, text=True)
        log.close()
        self._procs[name] = p
        line = p.stdout.readline().strip()
        if not line:
            raise RuntimeError(
                f"process {name} died on boot (see "
                f"{self.base}/logs/{name.replace(':', '_')}.log)")
        return json.loads(line)

    def _reap(self, name: str, sig: Optional[int] = None,
              wait_s: float = 0.0) -> None:
        p = self._procs.get(name)
        if p is None:
            return
        if sig is not None and p.poll() is None:
            p.send_signal(sig)
        if wait_s:
            try:
                p.wait(timeout=wait_s)
            except subprocess.TimeoutExpired:
                pass

    def store(self):
        """Store client for the driver process (lazy; the standalone
        store outlives controller failovers, so one client serves the
        whole run)."""
        if self._store_client is None:
            from pinot_tpu.controller.store_client import \
                RemotePropertyStore
            self._store_client = RemotePropertyStore("127.0.0.1",
                                                     self.store_port)
        return self._store_client

    # -- admin facade ------------------------------------------------------
    def active_controller_http(self) -> Optional[str]:
        """Base URL of the ACTIVE controller. HA: the store's published
        /CONTROLLER/ENDPOINT record (written on every takeover);
        non-HA: the only controller."""
        if not self.ha:
            port = next(iter(self.controllers.values()))["httpPort"]
            return f"http://127.0.0.1:{port}"
        try:
            rec = self.store().get("/CONTROLLER/ENDPOINT")
        except Exception:  # noqa: BLE001 — store racing failover
            rec = None
        return rec["base"] if rec else None

    def active_controller_id(self) -> Optional[str]:
        base = self.active_controller_http()
        if base is None:
            return None
        port = int(base.rsplit(":", 1)[1])
        for cid, rec in self.controllers.items():
            if rec["httpPort"] == port:
                return cid
        return None

    def add_schema(self, schema) -> None:
        _http_json("POST", f"{self.active_controller_http()}/schemas",
                   json.dumps(schema.to_json()).encode())

    def add_table(self, config) -> None:
        _http_json("POST", f"{self.active_controller_http()}/tables",
                   json.dumps(config.to_json()).encode())

    def upload_segment(self, table: str, segment_dir: str) -> None:
        from pinot_tpu.common.segment_tar import pack_segment_dir
        _http_json("POST",
                   f"{self.active_controller_http()}/segments/{table}",
                   pack_segment_dir(segment_dir),
                   ctype="application/octet-stream", timeout=120)

    def query(self, pql: str, broker: int = 0, timeout: float = 30.0):
        port = self.broker_ports[broker % len(self.broker_ports)]
        return _http_json("POST", f"http://127.0.0.1:{port}/query",
                          json.dumps({"pql": pql}).encode(),
                          timeout=timeout)

    def await_ready(self, table: str, expected_rows: int,
                    timeout_s: float = 300.0) -> None:
        """Every broker serves the FULL table (views converged)."""
        deadline = time.monotonic() + timeout_s
        last = None
        pending = list(range(len(self.broker_ports)))
        while time.monotonic() < deadline and pending:
            try:
                out = self.query(f"SELECT COUNT(*) FROM {table}",
                                 broker=pending[0], timeout=10)
                last = out
                if not out.get("exceptions") and \
                        out["aggregationResults"][0]["value"] == \
                        str(expected_rows):
                    pending.pop(0)
                    continue
            except Exception as e:  # noqa: BLE001 — still booting
                last = str(e)
            time.sleep(0.3)
        if pending:
            raise RuntimeError(
                f"cluster not ready in {timeout_s}s: {last}")

    def metrics_snapshots(self) -> dict:
        out = {"brokers": {}, "servers": {}}
        for i, port in enumerate(self.broker_ports):
            try:
                out["brokers"][f"Broker_{i}"] = _http_json(
                    "GET",
                    f"http://127.0.0.1:{port}/metrics?format=json",
                    timeout=10)
            except Exception:  # noqa: BLE001 — best-effort
                pass
        for name, port in self.server_admin_ports.items():
            try:
                out["servers"][name] = _http_json(
                    "GET",
                    f"http://127.0.0.1:{port}/metrics?format=json",
                    timeout=10)
            except Exception:  # noqa: BLE001
                pass
        return out

    def health_rollups(self) -> dict:
        """GET /debug/health from every process that serves it — the
        one-scrape-per-process leak-gate poll the soak samples."""
        out: Dict[str, dict] = {}
        for i, port in enumerate(self.broker_ports):
            try:
                out[f"Broker_{i}"] = _http_json(
                    "GET", f"http://127.0.0.1:{port}/debug/health",
                    timeout=10)
            except Exception:  # noqa: BLE001
                pass
        for name, port in self.server_admin_ports.items():
            try:
                out[name] = _http_json(
                    "GET", f"http://127.0.0.1:{port}/debug/health",
                    timeout=10)
            except Exception:  # noqa: BLE001
                pass
        base = self.active_controller_http()
        if base is not None:
            try:
                out["controller"] = _http_json(
                    "GET", f"{base}/debug/health", timeout=10)
            except Exception:  # noqa: BLE001
                pass
        return out

    # -- membership / chaos verbs ------------------------------------------
    # every verb takes (target, **params) — the ChaosCoordinator calls
    # them positionally with its (possibly seeded) target choice

    def start_server(self, target: str, **params) -> str:
        boot = self._spawn(
            f"server:{target}", "StartServer",
            "--store", self._store_addr,
            "--deep-store", self.deep_store,
            "--instance-id", target,
            "--dir", os.path.join(self.base, "server_work", target),
            "--controller-http", "auto" if self.ha else
            self.active_controller_http().split("//", 1)[1],
            "--admin-port", "0")
        self.server_admin_ports[target] = boot["adminPort"]
        return target

    def kill_server(self, target: str, **params) -> str:
        """kill -9: no drain, no seal — the self-healing plane and the
        brokers' failover must mask it."""
        self._reap(f"server:{target}", signal.SIGKILL, wait_s=10)
        self._procs.pop(f"server:{target}", None)
        self.server_admin_ports.pop(target, None)
        return target

    def drain_server(self, target: str, **params) -> str:
        """SIGTERM: the admin CLI's graceful drain (seal consuming,
        deregister, bleed in-flight, exit). Returns immediately — the
        recovery probe watches the process actually exit."""
        self._reap(f"server:{target}", signal.SIGTERM)
        self.server_admin_ports.pop(target, None)
        return target

    def _start_broker(self) -> int:
        env_keys = {}
        if self.broker_faults:
            env_keys["PINOT_TPU_BROKER_FAULTS"] = "1"
        idx = len(self.broker_ports)
        old_env = self._env
        if env_keys:
            self._env = dict(self._env, **env_keys)
        try:
            boot = self._spawn(f"broker:{idx}", "StartBroker",
                               "--store", self._store_addr,
                               "--deep-store", self.deep_store)
        finally:
            self._env = old_env
        self.broker_ports.append(boot["httpPort"])
        return boot["httpPort"]

    def start_controller(self, target: str, standby: bool = True,
                         **params) -> str:
        """(Re)join a controller — chaos runs restart the failed lead
        as the NEW standby."""
        cmd = ["StartController",
               "--dir", os.path.join(self.base, "controller"),
               "--store-addr", self._store_addr,
               "--instance-id", target,
               "--lease-s", str(self.lease_s)]
        if standby:
            cmd.append("--standby")
        boot = self._spawn(f"controller:{target}", *cmd)
        self.controllers[target] = {"httpPort": boot["httpPort"]}
        return target

    def fail_controller(self, target: Optional[str] = None,
                        **params) -> str:
        """SIGKILL the ACTIVE lead controller (or a named one): the
        lease must expire on its TTL and the standby must take over —
        publishing the new /CONTROLLER/ENDPOINT — within the recovery
        deadline."""
        cid = target or self.active_controller_id()
        if cid is None:
            raise RuntimeError("no active controller resolvable")
        self._reap(f"controller:{cid}", signal.SIGKILL, wait_s=10)
        self._procs.pop(f"controller:{cid}", None)
        self.controllers.pop(cid, None)
        return cid

    def start_minion(self, target: str = "Minion_0", **params) -> str:
        self._spawn(f"minion:{target}", "StartMinion",
                    "--store", self._store_addr,
                    "--deep-store", self.deep_store,
                    "--instance-id", target,
                    "--dir", os.path.join(self.base, "minion_work",
                                          target))
        if target not in self.minion_ids:
            self.minion_ids.append(target)
        return target

    def kill_minion(self, target: str = "Minion_0", **params) -> str:
        """kill -9, possibly mid-swap: the task lease requeues and the
        intent-logged swap protocol must resume or roll back."""
        self._reap(f"minion:{target}", signal.SIGKILL, wait_s=10)
        self._procs.pop(f"minion:{target}", None)
        if target in self.minion_ids:
            self.minion_ids.remove(target)
        return target

    # transport fault windows (armed inside every broker process)
    def _broker_fault(self, method: str, path: str,
                      body: Optional[dict] = None) -> None:
        for port in self.broker_ports:
            try:
                _http_json(method,
                           f"http://127.0.0.1:{port}{path}",
                           json.dumps(body).encode() if body else None,
                           timeout=10)
            except Exception:  # noqa: BLE001 — a dead broker has no arm
                pass

    def net_latency(self, target: str, latency_s: float = 0.25,
                    probability: float = 1.0, **params) -> str:
        """Inject per-dispatch latency toward one server on EVERY
        broker's data plane (window; disarmed via clear_fault)."""
        self._broker_fault("POST", "/debug/faults",
                           {"server": target, "kind": "latency",
                            "latencyS": latency_s,
                            "probability": probability})
        return target

    def net_drop(self, target: str, probability: float = 0.5,
                 **params) -> str:
        """Probabilistically drop broker→server connections (window)."""
        self._broker_fault("POST", "/debug/faults",
                           {"server": target, "kind": "drop",
                            "probability": probability})
        return target

    def clear_fault(self, target: str, **params) -> None:
        self._broker_fault("DELETE",
                           f"/debug/faults?server={target}")

    # -- chaos adapter surface ---------------------------------------------
    def targets(self, kind: str):
        if kind in ("kill_server", "drain_server", "net_latency",
                    "net_drop"):
            return list(self.server_admin_ports)
        if kind in ("fail_controller",):
            cid = self.active_controller_id()
            return [cid] if cid else []
        if kind in ("kill_minion",):
            return list(self.minion_ids)
        return []

    def recovery_probe(self, event, target: str):
        """Callable the ChaosCoordinator polls until recovery.

        kill_server — the cluster healed: replication deficit back to
        zero AND a broker answers clean. fail_controller — a DIFFERENT
        controller published the active endpoint and answers /health.
        drain_server — the process exited (the drain path runs in its
        SIGTERM handler). Others: untracked."""
        kind = event.kind
        if kind == "kill_server":
            return self._probe_healed
        if kind == "fail_controller":
            old_http = self.active_controller_http()
            return lambda: self._probe_controller_takeover(old_http)
        if kind == "drain_server":
            name = f"server:{target}"

            def exited() -> bool:
                p = self._procs.get(name)
                if p is None or p.poll() is not None:
                    self._procs.pop(name, None)
                    return True
                return False
            return exited
        return None

    def _probe_healed(self) -> bool:
        base = self.active_controller_http()
        if base is None:
            return False
        try:
            snap = _http_json("GET", f"{base}/metrics?format=json",
                              timeout=10)
        except Exception:  # noqa: BLE001
            return False
        deficits = [v for k, v in snap.items()
                    if k.startswith("gauge.") and
                    k.endswith("clusterReplicationDeficit")]
        return bool(deficits) and all(v == 0 for v in deficits)

    def _probe_controller_takeover(self, old_http: Optional[str]) -> bool:
        base = self.active_controller_http()
        if base is None or base == old_http:
            return False
        try:
            req = urllib.request.Request(f"{base}/health")
            with urllib.request.urlopen(req, timeout=5) as resp:
                return resp.status == 200
        except Exception:  # noqa: BLE001
            return False

    def stop(self) -> None:
        if self._store_client is not None:
            try:
                self._store_client.close()
            except Exception:  # noqa: BLE001
                pass
        procs = list(self._procs.values())
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        self._procs.clear()
