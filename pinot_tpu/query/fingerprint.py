"""Canonical query fingerprint: the result-cache key.

Two requests share a fingerprint iff they MUST produce identical
results over identical data. The fingerprint therefore hashes a
canonicalized form of the compiled request:

- execution-irrelevant options are dropped (trace, timeoutMs — they
  shape metadata and deadlines, never result values;
  minConsumingFreshnessTimeMs is enforced per-query at cache-GET time
  as a max-age bound, so queries that differ only in their freshness
  bound share one entry);
- IN/NOT_IN value lists are sorted (set semantics);
- AND/OR children are sorted by their canonical encoding (conjunction
  and disjunction are commutative over result values).

Canonicalization only ever MERGES equivalent queries — a query pair
with different results always hashes differently, so a cache keyed on
the fingerprint (plus segment CRCs) is exact by construction; an
imperfect canonicalization costs hit rate, never correctness.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.common.serde import filter_to_json, request_to_json

_COMMUTATIVE = (FilterOperator.AND, FilterOperator.OR)
_SET_VALUED = (FilterOperator.IN, FilterOperator.NOT_IN)


def _canonical_filter(node: Optional[FilterQueryTree]):
    if node is None:
        return None
    d = filter_to_json(node)
    if node.operator in _COMMUTATIVE:
        children = [_canonical_filter(c) for c in node.children]
        children.sort(key=lambda c: json.dumps(c, sort_keys=True))
        d["children"] = children
    elif node.operator in _SET_VALUED:
        d["vals"] = sorted(node.values)
    return d


def canonical_request_dict(request: BrokerRequest) -> dict:
    d = request_to_json(request)
    d["filter"] = _canonical_filter(request.filter)
    opts = d.get("options") or {}
    # execution-shaping keys never change result values: "workload" is
    # a scheduling/quota tag (two tenants issuing the same query must
    # share one cache entry), trace/timeoutMs shape metadata and
    # deadlines (the parser mirrors them into options.options too)
    drop = {"workload", "trace", "timeoutMs",
            "minConsumingFreshnessTimeMs"}
    d["options"] = {"options": dict(sorted(
        (k, v) for k, v in (opts.get("options") or {}).items()
        if k not in drop))}
    return d


def query_fingerprint(request: BrokerRequest) -> str:
    """Stable hex digest of the canonicalized request (table included)."""
    payload = json.dumps(canonical_request_dict(request), sort_keys=True,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:32]


# ---------------------------------------------------------------------------
# Plan-shape key: the canonical fingerprint with literals hoisted out.
#
# Two requests share a plan-shape key iff they compile to the same
# kernel SHAPE and differ only in runtime literal operands — the
# condition under which the dispatch coalescer may stack them along a
# leading batch axis and serve both from one kernel execution. The
# compiled filter spec carries structure (operator tree, columns,
# lane sources, padded widths); literal values ride as runtime params
# (dictionary ids, member vectors, range bounds), so hoisting them
# here mirrors the spec/params split in query/plan.py exactly.
#
# The key is ADVISORY: the executor re-verifies compiled-spec equality
# before stacking (plan-time constant folds — an EQUALITY literal
# missing from a segment dictionary folds to EMPTY, an IN list whose
# resolved-id count crosses a pow2 bucket widens its lane — can make
# same-key plans diverge). A collision therefore costs batch
# occupancy, never correctness.

_VALUE_LEAVES = (FilterOperator.EQUALITY, FilterOperator.NOT,
                 FilterOperator.IN, FilterOperator.NOT_IN,
                 FilterOperator.REGEXP_LIKE)


def _shape_filter(node: Optional[FilterQueryTree]):
    """Canonical shape dict + hoisted literal list for a filter tree."""
    if node is None:
        return None, []
    d = filter_to_json(node)
    lits: list = []
    if node.operator in _COMMUTATIVE:
        pairs = [_shape_filter(c) for c in node.children]
        # sort by shape first so literal-only rewrites keep the child
        # order (and thus the key) stable; tiebreak identical-shape
        # siblings by their literal sub-vectors for determinism — a
        # swap of such siblings permutes the literal vector but the
        # shape encoding, and the key, are unchanged
        pairs.sort(key=lambda p: (json.dumps(p[0], sort_keys=True),
                                  json.dumps(p[1], default=str)))
        d["children"] = [shape for shape, _ in pairs]
        for _, sub in pairs:
            lits.extend(sub)
    elif node.operator in _SET_VALUED:
        vals = sorted(node.values)
        lits.extend(vals)
        # arity stays structural: the compiled lane width is padded
        # from the list length, so a different-arity IN is (usually) a
        # different kernel shape
        d["vals"] = ["?"] * len(vals)
    elif node.operator in _VALUE_LEAVES:
        lits.extend(node.values)
        d["vals"] = ["?"] * len(node.values)
    elif node.operator is FilterOperator.RANGE:
        lits.append(node.lower)
        lits.append(node.upper)
        d["lo"] = "?" if node.lower is not None else None
        d["hi"] = "?" if node.upper is not None else None
        # bound PRESENCE and inclusivity flags stay structural
    return d, lits


def plan_shape_key(request: BrokerRequest):
    """``(key, literal_vector)`` — the canonical fingerprint with
    literals hoisted out. Same key == batchable modulo the compiled
    spec check; the literal vector is the hoisted operands in canonical
    order (diagnostics and property tests, not an execution input —
    the stacked params come from each member's compiled plan)."""
    d = request_to_json(request)
    shape, lits = _shape_filter(request.filter)
    d["filter"] = shape
    # LIMIT and the selection window are literal knobs too: they shape
    # the host-side finish (and at most a pow2 topk bucket the spec
    # check re-verifies), not the operator tree
    lits.append(d.get("limit"))
    d["limit"] = "?"
    sel = d.get("selection")
    if sel:
        lits.append(sel.get("offset"))
        lits.append(sel.get("size"))
        sel["offset"] = "?"
        sel["size"] = "?"
    gb = d.get("groupBy")
    if gb:
        lits.append(gb.get("topN"))
        gb["topN"] = "?"
    vec = d.get("vector")
    if vec:
        # the query embedding is a runtime operand; k shapes the topk
        # lane and stays structural
        lits.extend(vec.get("q") or ())
        vec["q"] = "?"
    opts = d.get("options") or {}
    drop = {"workload", "trace", "timeoutMs",
            "minConsumingFreshnessTimeMs"}
    d["options"] = {"options": dict(sorted(
        (k, v) for k, v in (opts.get("options") or {}).items()
        if k not in drop))}
    payload = json.dumps(d, sort_keys=True, separators=(",", ":"))
    key = hashlib.sha256(payload.encode("utf-8")).hexdigest()[:32]
    return key, tuple(lits)
