"""Throughput curve: SSB queries through a real controller + broker +
2-server cluster (HTTP broker endpoint, TCP data plane), driven by the
QueryRunner perf harness in increasingQPS mode.

Parity: pinot-tools/.../perf/QueryRunner.java targetQPS/increasingQPS and
contrib/pinot-druid-benchmark PinotThroughput — the reference's benchmark
culture records p50/p99 vs offered QPS and the saturation knee, not just
single-query latency. Writes QPS_r06.json at the repo root (override the
artifact name with QPS_ARTIFACT; QPS_r05.json is the pre-mux baseline).

Two cluster shapes:

- QPS_MULTIPROC=0 (default): the single-process EmbeddedCluster — on
  small CPU hosts one interpreter beats four processes' XLA thread
  pools fighting over the same cores, so this is the shape the
  committed QPS_r*.json artifacts use (the JSON's "cluster" field
  records which shape produced it).
- QPS_MULTIPROC=1: controller, broker and each server run as their OWN
  process via the admin CLI (StartController/StartServer/StartBroker
  parity) — the reference's deployment shape; prefer it on real
  multi-core hosts where per-plane interpreters actually parallelize.

Runs on the CPU backend (the serving plane under test is broker routing +
scatter/gather + scheduler + reduce; bench.py covers the chip plane), on
purpose at a row count small enough that per-query work doesn't mask the
serving-path costs.
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# HARD override: the serving-plane benchmark must not pay the test
# harness's TPU relay RTT (~90ms/dispatch) per query — that measures the
# relay, not the broker path. bench.py owns the chip-plane numbers.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

ROWS = int(os.environ.get("QPS_ROWS", 2_000_000))
SEGMENTS = int(os.environ.get("QPS_SEGMENTS", 4))
STEP_S = float(os.environ.get("QPS_STEP_S", 3.0))
# default: single process — on small CPU hosts the one-interpreter
# embedded shape outperforms 4 processes × XLA thread pools fighting for
# the same cores; set QPS_MULTIPROC=1 on real multi-core hosts for the
# reference's one-process-per-plane deployment shape
MULTIPROC = os.environ.get("QPS_MULTIPROC", "0") != "0"
NUM_SERVERS = 2
TABLE = "lineorder_OFFLINE"


def _http(method, url, body=None, ctype="application/json", timeout=60):
    req = urllib.request.Request(
        url, data=body, method=method,
        headers={"Content-Type": ctype} if body else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class MultiprocCluster:
    """controller + NUM_SERVERS servers + broker, one process each."""

    def __init__(self, base: str, dirs, schema, table_config):
        self._procs = []
        env = dict(os.environ, PYTHONPATH=REPO)

        def spawn(*cmd):
            p = subprocess.Popen(
                [sys.executable, "-m", "pinot_tpu.tools.admin", *cmd],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                env=env, cwd=REPO, text=True)
            self._procs.append(p)
            line = p.stdout.readline().strip()
            if not line:
                raise RuntimeError(f"process {cmd[0]} died on boot")
            return json.loads(line)

        ctrl = spawn("StartController", "--dir", base, "--store-port", "0")
        store = f"127.0.0.1:{ctrl['storePort']}"
        deep = ctrl["deepStore"]
        for i in range(NUM_SERVERS):
            spawn("StartServer", "--store", store, "--deep-store", deep,
                  "--instance-id", f"Server_{i}")
        broker = spawn("StartBroker", "--store", store,
                       "--deep-store", deep)
        self.broker_port = broker["httpPort"]

        capi = f"http://127.0.0.1:{ctrl['httpPort']}"
        _http("POST", f"{capi}/schemas",
              json.dumps(schema.to_json()).encode())
        _http("POST", f"{capi}/tables",
              json.dumps(table_config.to_json()).encode())
        from pinot_tpu.controller.http_api import pack_segment_dir
        for d in dirs:
            _http("POST", f"{capi}/segments/{TABLE}", pack_segment_dir(d),
                  ctype="application/octet-stream")

    def metrics_snapshots(self):
        """Phase-timer snapshots for attribution (multiproc shape: the
        broker JSON view only — servers are separate processes without
        admin ports here; the embedded shape attributes server-side
        phases too)."""
        bapi = f"http://127.0.0.1:{self.broker_port}"
        try:
            broker = _http("GET", f"{bapi}/metrics?format=json",
                           timeout=10)
        except Exception:  # noqa: BLE001 — profile note is best-effort
            broker = {}
        return {"broker": broker, "servers": {}}

    def await_ready(self, expected_rows: int, timeout_s: float = 60.0):
        """Poll until the broker serves the FULL table (external view
        converged on every server)."""
        bapi = f"http://127.0.0.1:{self.broker_port}"
        deadline = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < deadline:
            try:
                out = _http("POST", f"{bapi}/query", json.dumps(
                    {"pql": "SELECT COUNT(*) FROM lineorder"}).encode(),
                    timeout=10)
                last = out
                if not out.get("exceptions") and \
                        out["aggregationResults"][0]["value"] == \
                        str(expected_rows):
                    return
            except Exception:  # noqa: BLE001 — still booting
                pass
            time.sleep(0.3)
        raise RuntimeError(f"cluster not ready in {timeout_s}s: {last}")

    def stop(self):
        for p in self._procs:
            p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# phase attribution (VERDICT.md #1: "where does the time go") — broker
# pipeline stages + server-side stages summed across server registries
BROKER_PHASES = ("requestCompilation", "authorization", "queryRouting",
                 "scatterGather", "reduce", "queryTotal")
SERVER_PHASES = ("requestDeserialization", "schedulerWait",
                 "queryProcessing", "responseSerialization")


def _phase_means(prev, cur):
    """Mean per-query milliseconds per phase over one rung window
    (delta of the cumulative timers between two snapshots)."""

    def mean(prev_reg, cur_reg, phase):
        dc = cur_reg.get(f"timer.{phase}.count", 0) - \
            prev_reg.get(f"timer.{phase}.count", 0)
        dt = cur_reg.get(f"timer.{phase}.totalMs", 0.0) - \
            prev_reg.get(f"timer.{phase}.totalMs", 0.0)
        return round(dt / dc, 3) if dc > 0 else None

    out = {}
    for phase in BROKER_PHASES:
        out[f"broker.{phase}"] = mean(prev["broker"], cur["broker"],
                                      phase)
    for phase in SERVER_PHASES:
        dc = dt = 0.0
        for name, cur_reg in cur["servers"].items():
            prev_reg = prev["servers"].get(name, {})
            dc += cur_reg.get(f"timer.{phase}.count", 0) - \
                prev_reg.get(f"timer.{phase}.count", 0)
            dt += cur_reg.get(f"timer.{phase}.totalMs", 0.0) - \
                prev_reg.get(f"timer.{phase}.totalMs", 0.0)
        out[f"server.{phase}"] = round(dt / dc, 3) if dc > 0 else None
    return out


def _attribution_profile(phase_rungs, rungs, knee):
    """The per-phase attribution note: what dominates at the knee."""
    knee_idx = next((i for i, r in enumerate(rungs)
                     if knee is not None and r["target_qps"] == knee),
                    len(rungs) - 1)
    at_knee = phase_rungs[knee_idx] if phase_rungs else {}
    total = at_knee.get("broker.queryTotal")
    breakdown = {k: v for k, v in at_knee.items()
                 if k != "broker.queryTotal" and v is not None}
    dominant = max((k for k in breakdown if k.startswith("broker.")),
                   key=lambda k: breakdown[k], default=None)
    # scatterGather CONTAINS the server-side time: subtract the server
    # queryProcessing mean to split network+queueing from compute
    sg = breakdown.get("broker.scatterGather")
    qp = breakdown.get("server.queryProcessing")
    note = None
    if dominant is not None:
        note = (f"at the {rungs[knee_idx]['target_qps']:g}-QPS rung "
                f"(knee={knee}), mean per-query queryTotal="
                f"{total}ms; dominant broker phase: {dominant} "
                f"({breakdown[dominant]}ms)")
        if sg is not None and qp is not None:
            note += (f" — of scatterGather {sg}ms, server "
                     f"queryProcessing accounts for {qp}ms, leaving "
                     f"{round(sg - qp, 3)}ms for transport+serde+queue")
    return {
        "artifact": "phase_attribution_profile",
        "kneeQps": knee,
        "kneeRungOfferedQps": rungs[knee_idx]["target_qps"],
        "phaseMeansMsAtKnee": at_knee,
        "dominantBrokerPhase": dominant,
        "note": note,
        "rungs": [{"offered_qps": r["target_qps"],
                   "phaseMeansMs": pm}
                  for r, pm in zip(rungs, phase_rungs)],
    }


def main() -> None:
    from bench import SSB_PQLS
    from pinot_tpu.tools.datagen import (build_ssb_segment_dirs,
                                         ssb_schema, ssb_table_config)
    from pinot_tpu.tools.perf import QueryRunner, http_query_fn

    t0 = time.time()
    base = tempfile.mkdtemp()
    print(f"building {ROWS} rows / {SEGMENTS} segments...",
          file=sys.stderr, flush=True)
    dirs, _ids, _sc = build_ssb_segment_dirs(
        os.path.join(base, "segs"), ROWS, SEGMENTS, seed=7, star_tree=True)

    if MULTIPROC:
        cluster = MultiprocCluster(os.path.join(base, "cluster"), dirs,
                                   ssb_schema(),
                                   ssb_table_config(star_tree=True))
        shape = (f"controller + broker(http) + {NUM_SERVERS} servers "
                 "over TCP, one process each")
    else:
        from pinot_tpu.tools.cluster import EmbeddedCluster

        class _Embedded:
            def __init__(self):
                self.c = EmbeddedCluster(os.path.join(base, "cluster"),
                                         num_servers=NUM_SERVERS,
                                         tcp=True, http=True)
                self.c.add_schema(ssb_schema())
                self.c.add_table(ssb_table_config(star_tree=True))
                for d in dirs:
                    self.c.upload_segment(TABLE, d)
                self.broker_port = self.c.broker_port

            def await_ready(self, *_a, **_k):
                pass

            def metrics_snapshots(self):
                return {
                    "broker": self.c.broker.metrics.snapshot(),
                    "servers": {name: s.metrics.snapshot()
                                for name, s in self.c.servers.items()}}

            def stop(self):
                self.c.stop()

        cluster = _Embedded()
        shape = (f"controller + broker(http) + {NUM_SERVERS} servers "
                 "over TCP, single process")
    try:
        cluster.await_ready(ROWS)
        queries = list(SSB_PQLS.values())
        fn = http_query_fn(f"127.0.0.1:{cluster.broker_port}")
        runner = QueryRunner(fn, queries)

        # warm every query's plan/kernel caches
        warm = runner.single_thread(num_times=2)
        print(f"warm: {warm}", file=sys.stderr, flush=True)

        rungs = []
        phase_rungs = []
        qps = 25.0
        knee = None
        snap = cluster.metrics_snapshots()
        while qps <= 800:
            r = runner.target_qps(qps=qps, duration_s=STEP_S,
                                  num_threads=16)
            print(str(r), file=sys.stderr, flush=True)
            rungs.append(r.to_json())
            # per-rung phase attribution from the cumulative timers
            next_snap = cluster.metrics_snapshots()
            phase_rungs.append(_phase_means(snap, next_snap))
            snap = next_snap
            achieved = r.qps
            if knee is None and (achieved < 0.9 * qps or
                                 r.missed_slots > r.num_queries // 2):
                knee = qps
            qps *= 2
        out = {
            "artifact": "ssb13_throughput_curve",
            "rows": ROWS, "segments": SEGMENTS,
            "cluster": shape,
            "backend": "cpu (serving-plane benchmark; chip plane is "
                       "bench.py)",
            "mode": "increasingQPS (QueryRunner.java parity)",
            "step_duration_s": STEP_S,
            "warmup": warm.to_json(),
            "rungs": rungs,
            "saturation_knee_qps": knee,
            "wall_s": round(time.time() - t0, 1),
        }
        path = os.path.join(REPO,
                            os.environ.get("QPS_ARTIFACT", "QPS_r06.json"))
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        # the phase-attribution profile note (obs subsystem): which
        # pipeline stage the per-query time actually goes to at the knee
        profile = _attribution_profile(phase_rungs, rungs, knee)
        profile.update({"rows": ROWS, "segments": SEGMENTS,
                        "cluster": shape,
                        "qps_artifact": os.path.basename(path)})
        ppath = os.path.join(REPO, os.environ.get("PROFILE_ARTIFACT",
                                                  "PROFILE_r06.json"))
        with open(ppath, "w") as f:
            json.dump(profile, f, indent=1)
        print(f"profile: {profile['note']}", file=sys.stderr, flush=True)
        print(json.dumps({"artifact": path,
                          "profile_artifact": ppath,
                          "saturation_knee_qps": knee,
                          "dominant_phase_at_knee":
                              profile["dominantBrokerPhase"],
                          "max_achieved_qps": max(r["qps"]
                                                  for r in rungs)}))
    finally:
        cluster.stop()


if __name__ == "__main__":
    main()
