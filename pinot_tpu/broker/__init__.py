from pinot_tpu.broker.fault_tolerance import (CircuitBreaker,
                                              FaultToleranceManager)
from pinot_tpu.broker.quota import HitCounter, QueryQuotaManager
from pinot_tpu.broker.request_handler import (BrokerRequestHandler,
                                              InProcessTransport,
                                              QueryRouter, TcpTransport)
from pinot_tpu.broker.routing import (BalancedRandomRoutingTableBuilder,
                                      LargeClusterRoutingTableBuilder,
                                      ReplicaGroupRoutingTableBuilder,
                                      RoutingManager)
from pinot_tpu.broker.time_boundary import (TimeBoundaryService,
                                            attach_time_boundary)

__all__ = ["CircuitBreaker", "FaultToleranceManager",
           "HitCounter", "QueryQuotaManager", "BrokerRequestHandler",
           "InProcessTransport", "QueryRouter", "TcpTransport",
           "BalancedRandomRoutingTableBuilder",
           "LargeClusterRoutingTableBuilder",
           "ReplicaGroupRoutingTableBuilder", "RoutingManager",
           "TimeBoundaryService", "attach_time_boundary"]
