"""Linear-time sorted factorize shared by the dictionary and cube builders.

np.unique is an O(n log n) argsort; a hash factorize is O(n) plus a sort of
the (tiny) unique set. pandas provides the hash table; without it the
np.unique fallback keeps behavior identical.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def int_lut_factorize(arr: np.ndarray
                      ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """bincount-LUT ladder: bounded-span integers factorize with two
    O(n) passes and NO hashing (presence scatter + LUT gather) — the
    dominant SSB dictionary-build case (dims are small-range ints,
    metrics like revenue span < 2M). None when the span is too wide."""
    a = np.asarray(arr)
    if a.dtype.kind not in "iu" or not len(a):
        return None
    mn, mx = int(a.min()), int(a.max())
    span = mx - mn + 1
    if span > max(4 * len(a), 1 << 22):
        return None
    off = (a.astype(np.int64) - mn)
    presence = np.zeros(span, bool)
    presence[off] = True
    uniq_off = np.flatnonzero(presence)
    # int32 LUT: ranks are < n < 2^31; halves the peak allocation of
    # this hot build path (a 400M-slot span is 1.6GB, not 3.2GB)
    lut = np.zeros(span, np.int32)
    lut[uniq_off] = np.arange(len(uniq_off), dtype=np.int32)
    return (uniq_off + mn).astype(a.dtype), lut[off]


def sorted_factorize(arr: np.ndarray
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(sorted unique values, inverse codes) for arr, or None when the
    linear path can't run (pandas missing, or NaN-like values that
    factorize maps to the -1 sentinel — callers fall back to np.unique)."""
    fast = int_lut_factorize(arr)
    if fast is not None:
        return fast
    try:
        import pandas as pd
    except ImportError:
        return None
    codes, uniq = pd.factorize(arr)
    if len(codes) and codes.min() < 0:          # -1 = NaN sentinel
        return None
    uniq = np.asarray(uniq)
    order = np.argsort(uniq, kind="stable")      # unique set: tiny vs n
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order), dtype=np.int64)
    return uniq[order], rank[codes]


def sorted_factorize_or_unique(arr: np.ndarray
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """sorted_factorize with the canonical np.unique fallback — callers
    that don't need a custom fallback (e.g. a pre-cast step) use this so
    the fallback semantics live in one place."""
    fact = sorted_factorize(arr)
    if fact is None:
        return np.unique(arr, return_inverse=True)
    return fact
