"""Cluster self-healing: liveness-driven replica repair and rebalance.

Parity: the slice of Helix the reference leans on for node death —
a dead participant's ephemeral session drops it from LIVEINSTANCES, the
controller recomputes assignments, replicas are re-created on healthy
servers and consuming partitions re-consumed elsewhere (SURVEY §:
cluster management via Helix + ZooKeeper; PinotHelixResourceManager's
rebalance + ensureAllPartitionsConsuming). Two cooperating pieces:

- ``SegmentRebalancer`` — computes and applies **minimal** replica
  moves against the ideal state: replica-count repair for committed
  segments whose holders died (new replicas assigned through the
  table's existing assignment strategy onto healthy tenant servers,
  capped at live capacity), pruning of dead holders, and a throttled
  make-before-break spread onto newly joined servers. Every write goes
  through the property store, so brokers' routing views converge via
  the existing external-view watch chain — the rebalancer never talks
  to a broker.
- ``ClusterHealthMonitor`` — a lead-gated periodic task that watches
  live-instance membership, declares a server dead only after a
  configurable grace window (a restart must not trigger a rebalance
  storm), then drives the rebalancer for committed replicas and the
  realtime manager's partition-takeover path for CONSUMING ones.

Crash points (tests kill the controller at each and restart over the
same durable store; every step is idempotent so recovery is re-running
the monitor):

- ``rebalance.move_staged``  — after a repair plan is computed, before
  any ideal-state write for the batch.
- ``rebalance.pre_commit``   — after new replicas were added to the
  ideal state, before dead holders are pruned.
- ``takeover.pre_resume``    — in realtime_manager, after a consuming
  partition's dead owners were bounced OFFLINE, before the new owners'
  CONSUMING assignment is written.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Set

from pinot_tpu.common.cluster_state import CONSUMING, ONLINE
from pinot_tpu.common.faults import crash_points
from pinot_tpu.common.metrics import ControllerMeter
from pinot_tpu.controller.assignment import make_assignment
from pinot_tpu.controller.periodic import PeriodicTask

log = logging.getLogger(__name__)


def replication_deficit(manager) -> int:
    """Σ over committed segments of (configured replicas, capped at the
    table's live tenant capacity) − (live ideal-state holders). 0 when
    fully repaired; the `clusterReplicationDeficit` gauge."""
    deficit = 0
    for table in manager.table_names():
        config = manager.get_table_config(table)
        if config is None:
            continue
        live = set(manager.server_instances_for(config))
        wanted = config.segments_config.replication
        capacity = min(wanted, len(live))
        for seg, states in manager.coordinator.ideal_state(table).items():
            if CONSUMING in states.values():
                continue        # the realtime repair path owns these
            alive = sum(1 for inst in states if inst in live)
            deficit += max(0, capacity - alive)
    return deficit


class SegmentRebalancer:
    """Minimal-move replica repair + bounded rebalance-on-join."""

    def __init__(self, manager, metrics=None,
                 max_moves_per_cycle: int = 16,
                 join_converge_timeout_s: float = 20.0):
        self.manager = manager
        self.metrics = metrics
        self.max_moves_per_cycle = max_moves_per_cycle
        self.join_converge_timeout_s = join_converge_timeout_s

    def _mark_moves(self, n: int) -> None:
        if n and self.metrics is not None:
            self.metrics.meter(ControllerMeter.REBALANCE_MOVES).mark(n)

    def _strategy(self, table: str):
        return self.manager._assignments.setdefault(
            table, make_assignment("balanced"))

    # -- replica-count repair ----------------------------------------------
    def compute_repair(self, table: str) -> Dict[str, Dict[str, List[str]]]:
        """The repair plan for one table: per segment, replicas to add
        (on healthy live servers, via the table's assignment strategy)
        and dead holders to prune. Empty when converged — the no-op
        cycle costs only store reads."""
        config = self.manager.get_table_config(table)
        if config is None:
            return {}
        live = set(self.manager.server_instances_for(config))
        replicas = config.segments_config.replication
        strategy = self._strategy(table)
        ideal = self.manager.coordinator.ideal_state(table)
        plan: Dict[str, Dict[str, List[str]]] = {}
        for seg in sorted(ideal):
            states = ideal[seg]
            if CONSUMING in states.values():
                continue        # realtime takeover path, not ours
            survivors = sorted(i for i in states if i in live)
            dead = sorted(i for i in states if i not in live)
            need = min(replicas, len(live)) - len(survivors)
            adds: List[str] = []
            if need > 0:
                candidates = sorted(live - set(survivors))
                if candidates:
                    # honor the table's strategy for the NEW replicas:
                    # ask it for a full assignment over the candidates
                    # and take the first `need` it ranks
                    pm = (self.manager.segment_metadata(table, seg) or {}
                          ).get("partitionMetadata") or {}
                    pids = {p for info in pm.values()
                            for p in info.get("partitions") or ()}
                    ranked = strategy.assign(seg, candidates,
                                             min(need, len(candidates)),
                                             ideal,
                                             partition_ids=pids or None)
                    adds = [i for i in ranked if i not in survivors][:need]
            if adds or dead:
                plan[seg] = {"add": adds, "dead": dead}
        return plan

    def repair_table(self, table: str,
                     budget: Optional[int] = None) -> Dict:
        """Apply up to `budget` (default max_moves_per_cycle) repair
        moves: add replacement replicas first (make), then prune dead
        holders (break). Both writes are idempotent fold functions over
        the CURRENT ideal state, so a crash between them — or a re-run
        after one — converges without double-owned or orphaned
        replicas."""
        plan = self.compute_repair(table)
        if not plan:
            return {"added": {}, "pruned": {}, "remaining": 0}
        budget = self.max_moves_per_cycle if budget is None else budget
        batch: Dict[str, Dict[str, List[str]]] = {}
        moves = 0
        for seg in sorted(plan):
            cost = len(plan[seg]["add"]) or 1
            if moves + cost > budget and batch:
                break
            batch[seg] = plan[seg]
            moves += cost
        # seeded crash point: plan computed, nothing written — restart
        # must recompute the identical plan from the durable state
        crash_points.hit("rebalance.move_staged")

        added = {s: m["add"] for s, m in batch.items() if m["add"]}
        if added:
            def add_new(segments, added=added):
                for seg, insts in added.items():
                    entry = dict(segments.get(seg, {}))
                    for inst in insts:
                        entry.setdefault(inst, ONLINE)
                    segments[seg] = entry
                return segments

            self.manager.coordinator.update_ideal_state(table, add_new)
        # seeded crash point: replacements staged in the ideal state but
        # dead holders not yet pruned — harmless duplicates (a dead
        # holder serves nothing); the next cycle prunes them
        crash_points.hit("rebalance.pre_commit")

        pruned = {s: m["dead"] for s, m in batch.items() if m["dead"]}
        if pruned:
            config = self.manager.get_table_config(table)
            live = set(self.manager.server_instances_for(config)) \
                if config else set()

            def drop_dead(segments, pruned=pruned, live=live):
                for seg, insts in pruned.items():
                    entry = dict(segments.get(seg, {}))
                    for inst in insts:
                        # re-check against the CURRENT ideal: the holder
                        # may have reincarnated since the plan was built
                        if inst not in live:
                            entry.pop(inst, None)
                    segments[seg] = entry
                return segments

            self.manager.coordinator.update_ideal_state(table, drop_dead)
        self._mark_moves(sum(len(v) for v in added.values()))
        remaining = len(plan) - len(batch)
        if added or pruned:
            log.warning("rebalance: %s repaired %d segment(s) "
                        "(+%d replicas, -%d dead holders), %d deferred",
                        table, len(batch),
                        sum(len(v) for v in added.values()),
                        sum(len(v) for v in pruned.values()), remaining)
        return {"added": added, "pruned": pruned, "remaining": remaining}

    def repair_all(self) -> Dict[str, Dict]:
        out = {}
        for table in self.manager.table_names():
            report = self.repair_table(table)
            if report["added"] or report["pruned"] or report["remaining"]:
                out[table] = report
        return out

    # -- rebalance-on-join --------------------------------------------------
    def rebalance_onto(self, joined: str,
                       budget: Optional[int] = None) -> Dict[str, List[str]]:
        """Spread load onto a newly joined server, make-before-break:
        for up to `budget` segments whose strategy target includes the
        joiner, add a replica there, await it serving in the external
        view, then drop the most-loaded old holder. A convergence
        timeout leaves the extra replica in place (over-replication is
        safe; the next cycle retries the drop via compute_repair's
        no-op). Throttled by design — one bounded pass per join event."""
        budget = self.max_moves_per_cycle if budget is None else budget
        moved: Dict[str, List[str]] = {}
        for table in self.manager.table_names():
            config = self.manager.get_table_config(table)
            if config is None:
                continue
            servers = self.manager.server_instances_for(config)
            if joined not in servers or len(servers) < 2:
                continue
            replicas = config.segments_config.replication
            strategy = self._strategy(table)
            ideal = self.manager.coordinator.ideal_state(table)
            load = {inst: 0 for inst in servers}
            for states in ideal.values():
                for inst in states:
                    if inst in load:
                        load[inst] += 1
            for seg in sorted(ideal):
                if len(moved.get(table, ())) >= budget:
                    break
                states = ideal[seg]
                if CONSUMING in states.values() or joined in states:
                    continue
                if len(states) < replicas:
                    continue    # deficit: repair path owns it
                pm = (self.manager.segment_metadata(table, seg) or {}
                      ).get("partitionMetadata") or {}
                pids = {p for info in pm.values()
                        for p in info.get("partitions") or ()}
                target = strategy.assign(seg, servers, replicas, ideal,
                                         partition_ids=pids or None)
                if joined not in target:
                    continue
                victim = max(states, key=lambda i: (load.get(i, 0), i))
                if load.get(victim, 0) <= load.get(joined, 0) + 1:
                    continue    # already balanced enough: don't churn

                def add(segments, seg=seg):
                    entry = dict(segments.get(seg, {}))
                    entry.setdefault(joined, ONLINE)
                    segments[seg] = entry
                    return segments

                self.manager.coordinator.update_ideal_state(table, add)
                try:
                    self.manager._await_converged(
                        table, {seg: {joined: ONLINE}}, 1,
                        self.join_converge_timeout_s, require_all=True)
                except TimeoutError:
                    log.warning("rebalance-on-join: %s/%s never served "
                                "on %s; leaving the extra replica",
                                table, seg, joined)
                    continue

                def drop(segments, seg=seg, victim=victim):
                    entry = dict(segments.get(seg, {}))
                    if joined in entry and len(entry) > 1:
                        entry.pop(victim, None)
                    segments[seg] = entry
                    return segments

                self.manager.coordinator.update_ideal_state(table, drop)
                load[victim] = load.get(victim, 1) - 1
                load[joined] = load.get(joined, 0) + 1
                moved.setdefault(table, []).append(f"{seg}:{victim}->"
                                                   f"{joined}")
                self._mark_moves(1)
        if moved:
            log.info("rebalance-on-join: moved %s onto %s",
                     {t: len(m) for t, m in moved.items()}, joined)
        return moved


class ClusterHealthMonitor(PeriodicTask):
    """Lead-gated liveness watcher: declares servers dead after a grace
    window, then drives replica repair + consuming-partition takeover;
    newly joined servers trigger a throttled rebalance-on-join.

    Parity: the Helix controller reacting to LIVEINSTANCES session
    expiry — here liveness is polled from the same ephemeral records
    (PR 4 excludes them from the WAL, so a restarted controller starts
    from an empty membership view and re-learns it, never resurrecting
    dead peers). The clock is injectable so the grace window is testable
    without wall-clock sleeps.
    """

    name = "ClusterHealthMonitor"
    interval_s = 1.0

    def __init__(self, rebalancer: Optional[SegmentRebalancer] = None,
                 realtime_manager=None, grace_s: float = 5.0,
                 clock=time.monotonic, metrics=None):
        self.rebalancer = rebalancer
        self.realtime_manager = realtime_manager
        self.grace_s = grace_s
        self._clock = clock
        self.metrics = metrics
        #: instances ever observed live (baseline seeded on first run so
        #: booting against an established cluster fires no join events)
        self._ever_seen: Optional[Set[str]] = None
        self._missing_since: Dict[str, float] = {}
        self.last_report: Dict = {}

    def _rebalancer(self, manager) -> SegmentRebalancer:
        if self.rebalancer is None:
            self.rebalancer = SegmentRebalancer(manager,
                                                metrics=self.metrics)
        return self.rebalancer

    def run(self, manager) -> None:
        now = self._clock()
        live = set(manager.coordinator.live_instances())
        report: Dict = {"dead": [], "joined": [], "repaired": {},
                        "joinMoves": {}}
        if self._ever_seen is None:
            self._ever_seen = set(live)
        # a join is a NEW instance — or a known one RETURNING from a
        # missing spell (same-id restart): if its replicas were already
        # pruned by a repair, only the join path re-adds them
        joined = sorted((live - self._ever_seen) |
                        (live & set(self._missing_since)))
        self._ever_seen |= live
        for inst in live:
            # back (or never left): reset the death clock — a server
            # that returned within grace was a restart, not a death
            self._missing_since.pop(inst, None)
        for inst in self._ever_seen - live:
            self._missing_since.setdefault(inst, now)
        # holders recorded in the DURABLE ideal state but not live and
        # never observed by this controller incarnation: a restarted
        # controller has no memory of the instance ever being alive
        # (live records are session state the WAL excludes), yet its
        # replicas persist — start their death clock now, grace intact
        for table in manager.coordinator.tables():
            for states in manager.coordinator.ideal_state(table).values():
                for inst in states:
                    if inst not in live and inst not in self._ever_seen:
                        self._ever_seen.add(inst)
                        self._missing_since.setdefault(inst, now)
        dead = sorted(i for i, t in self._missing_since.items()
                      if now - t >= self.grace_s)

        if dead:
            report["dead"] = dead
            rb = self._rebalancer(manager)
            report["repaired"] = rb.repair_all()
            if self.realtime_manager is not None:
                # consuming partitions whose owners died: reassign and
                # resume from the last committed offset (the takeover
                # path is ensure_all_partitions_consuming's repair arm,
                # crash-pointed at takeover.pre_resume)
                self.realtime_manager.ensure_all_partitions_consuming()
            # forget instances that no longer appear anywhere in any
            # ideal state: fully healed — a later reincarnation under
            # the same id is a fresh join, not a resurrection
            for inst in dead:
                if not self._holds_anything(manager, inst):
                    self._missing_since.pop(inst, None)
                    self._ever_seen.discard(inst)

        for inst in joined:
            report["joined"].append(inst)
            moves = self._rebalancer(manager).rebalance_onto(inst)
            if moves:
                report["joinMoves"][inst] = moves
        if joined and not dead:
            # a join raises live CAPACITY: segments the last repair
            # could only restore to fewer replicas than configured
            # (capped at the then-live capacity) top back up now
            repaired = self._rebalancer(manager).repair_all()
            if repaired:
                report["repaired"] = repaired
        self.last_report = report

    @staticmethod
    def _holds_anything(manager, inst: str) -> bool:
        for table in manager.coordinator.tables():
            for states in manager.coordinator.ideal_state(table).values():
                if inst in states:
                    return True
        return False
