"""DataTable: the server→broker result wire format.

Parity: pinot-common/.../utils/DataTable.java + DataTableImplV2.java:40-263 —
version, metadata map, exceptions, schema (column names/types), row payload.

Two wire versions, negotiated by the leading version tag (decode handles
both; encode defaults to the newest):

- v1: per-row tagged object serde (one `_w_obj` per row tuple) — the
  original format, kept decodable so payloads from version-skewed servers
  still reduce.
- v2: COLUMNAR — the row payload is split into per-column blocks, like
  DataTableImplV2's fixed-size/variable-size regions. Homogeneous int64 /
  float64 / string columns serialize as fixed-width numpy buffers (plus a
  var-width utf-8 region for strings); anything else (pairs, sketches,
  sets, mixed types) falls back to one tagged object list per column.
  Group-by and selection payloads are dominated by exactly those
  homogeneous columns, so the per-row tag/tuple churn of v1 disappears
  from the serving hot path.

Three logical layouts mirror IntermediateResultsBlock's payloads:
- aggregation-only: one row, one object cell per aggregation function
- group-by: one row per group, key columns + intermediate object columns
- selection: one row per selected doc
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List

import numpy as np

from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.common.serde import obj_from_bytes, obj_to_bytes
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock

_U32 = struct.Struct(">I")
VERSION = 2
_LEGACY_VERSION = 1

KIND_EMPTY = 0
KIND_AGGREGATION = 1
KIND_GROUP_BY = 2
KIND_SELECTION = 3

# v2 column-block tags
_COL_I64 = b"L"      # big-endian int64 fixed-width block
_COL_F64 = b"F"      # big-endian float64 fixed-width block
_COL_STR = b"S"      # u32 offsets (fixed region) + utf-8 blob (var region)
_COL_OBJ = b"O"      # tagged object list fallback

# Structured metadata key carrying the JSON list of segments a server was
# asked for but does not host; the broker keys its one-shot re-dispatch off
# this (not off parsing exception strings, which can drift independently).
MISSING_SEGMENTS_KEY = "missingSegments"
# Human-facing exception prefix for the same condition — shared so the
# server format and the broker's partial-response surface stay in sync.
SEGMENT_MISSING_EXC_PREFIX = "SegmentMissingError:"
# Structured metadata keys for server admission control: a shed request
# answers with SERVER_BUSY_KEY = the shed cause ("overload" | "hedge" |
# "tenantOverQuota" | "deadline" | "capacity") and RETRY_AFTER_MS_KEY =
# an estimate of when the queue will have drained. The router treats a
# busy reply as non-retriable on the SAME server (failover only).
SERVER_BUSY_KEY = "serverBusy"
RETRY_AFTER_MS_KEY = "retryAfterMs"
SERVER_BUSY_EXC_PREFIX = "ServerBusyError:"
# Metadata marker on replies served from the server result cache.
RESULT_CACHE_HIT_KEY = "resultCacheHit"


@dataclasses.dataclass
class DataTable:
    kind: int = KIND_EMPTY
    columns: List[str] = dataclasses.field(default_factory=list)
    rows: List[tuple] = dataclasses.field(default_factory=list)
    num_group_cols: int = 0
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    exceptions: List[str] = dataclasses.field(default_factory=list)

    # -- wire format -------------------------------------------------------
    def to_bytes(self, version: int = VERSION) -> bytes:
        out = bytearray()
        out += _U32.pack(version)
        out += bytes([self.kind])
        out += _U32.pack(self.num_group_cols)
        _w_obj(out, self.metadata)
        _w_obj(out, list(self.exceptions))
        _w_obj(out, list(self.columns))
        if version == _LEGACY_VERSION:
            out += _U32.pack(len(self.rows))
            for row in self.rows:
                _w_obj(out, tuple(row))
        elif version == VERSION:
            _write_columnar(out, self.rows)
        else:
            raise ValueError(f"unsupported DataTable version {version}")
        return bytes(out)

    @classmethod
    def from_bytes(cls, b: bytes) -> "DataTable":
        off = 0
        version = _U32.unpack_from(b, off)[0]
        off += 4
        if version not in (_LEGACY_VERSION, VERSION):
            raise ValueError(f"unsupported DataTable version {version}")
        kind = b[off]
        off += 1
        num_group_cols = _U32.unpack_from(b, off)[0]
        off += 4
        metadata, off = _r_obj(b, off)
        exceptions, off = _r_obj(b, off)
        columns, off = _r_obj(b, off)
        if version == _LEGACY_VERSION:
            n_rows = _U32.unpack_from(b, off)[0]
            off += 4
            rows = []
            for _ in range(n_rows):
                row, off = _r_obj(b, off)
                rows.append(row)
        else:
            rows, off = _read_columnar(b, off)
        return cls(kind=kind, columns=list(columns), rows=rows,
                   num_group_cols=num_group_cols,
                   metadata=dict(metadata), exceptions=list(exceptions))

    # -- block conversion --------------------------------------------------
    @classmethod
    def from_block(cls, request: BrokerRequest,
                   block: IntermediateResultsBlock) -> "DataTable":
        dt = cls(metadata=block.stats.to_metadata(),
                 exceptions=list(block.exceptions))
        dt.metadata["timeUsedMs"] = f"{block.stats.time_used_ms:.3f}"
        if block.execution_path is not None:
            dt.metadata["executionPath"] = block.execution_path
        # numpy-scalar normalization happens inside serde._write_obj (and
        # the columnar writer), so rows can carry intermediates as-is
        if block.group_map is not None:
            dt.kind = KIND_GROUP_BY
            gcols = request.group_by.columns if request.group_by else []
            dt.num_group_cols = len(gcols)
            dt.columns = list(gcols) + [a.call for a in request.aggregations]
            dt.rows = [key + tuple(inters)
                       for key, inters in block.group_map.items()]
        elif block.agg_intermediates is not None:
            dt.kind = KIND_AGGREGATION
            dt.columns = [a.call for a in request.aggregations]
            dt.rows = [tuple(block.agg_intermediates)]
        elif block.selection_rows is not None:
            dt.kind = KIND_SELECTION
            dt.columns = list(block.selection_columns or [])
            # selection rows are already tuples on the execution path —
            # re-tupling every row was pure churn at scale
            dt.rows = [r if type(r) is tuple else tuple(r)
                       for r in block.selection_rows]
            if block.selection_display_cols is not None:
                # trailing ORDER-BY-only columns: the broker needs the
                # display split to trim after its cross-server merge
                dt.metadata["selectionDisplayCols"] = str(
                    block.selection_display_cols)
        return dt

    def to_block(self) -> IntermediateResultsBlock:
        blk = IntermediateResultsBlock(exceptions=list(self.exceptions))
        blk.stats = _stats_from_metadata(self.metadata)
        if self.kind == KIND_GROUP_BY:
            g = self.num_group_cols
            # rows are tuples on every decode path, so tuple() here is a
            # no-op identity check, not a copy (it only materializes for
            # hand-built list rows)
            blk.group_map = {tuple(row[:g]): list(row[g:])
                             for row in self.rows}
        elif self.kind == KIND_AGGREGATION:
            blk.agg_intermediates = list(self.rows[0]) if self.rows else None
        elif self.kind == KIND_SELECTION:
            blk.selection_rows = [r if type(r) is tuple else tuple(r)
                                  for r in self.rows]
            blk.selection_columns = list(self.columns)
            n = self.metadata.get("selectionDisplayCols")
            if n is not None:
                blk.selection_display_cols = int(n)
        return blk


def _stats_from_metadata(md: Dict[str, str]) -> ExecutionStats:
    def gi(k):
        return int(md.get(k, "0"))

    return ExecutionStats(
        num_docs_scanned=gi("numDocsScanned"),
        num_entries_scanned_in_filter=gi("numEntriesScannedInFilter"),
        num_entries_scanned_post_filter=gi("numEntriesScannedPostFilter"),
        num_segments_processed=gi("numSegmentsProcessed"),
        num_segments_matched=gi("numSegmentsMatched"),
        total_docs=gi("totalDocs"),
        num_groups_limit_reached=md.get("numGroupsLimitReached") == "true",
        num_consuming_segments_processed=gi("numConsumingSegmentsProcessed"),
        min_consuming_freshness_ms=gi("minConsumingFreshnessTimeMs"),
        time_used_ms=float(md.get("timeUsedMs", "0")))


# ---------------------------------------------------------------------------
# v2 columnar payload
# ---------------------------------------------------------------------------

_I64_MIN, _I64_MAX = -(2 ** 63), 2 ** 63 - 1


def _is_i64(v) -> bool:
    if type(v) is int:                      # excludes bool
        return _I64_MIN <= v <= _I64_MAX
    return isinstance(v, np.integer)


def _is_f64(v) -> bool:
    return type(v) is float or isinstance(v, np.floating)


def _write_columnar(out: bytearray, rows: List[tuple]) -> None:
    n_rows = len(rows)
    n_cols = len(rows[0]) if rows else 0
    out += _U32.pack(n_rows)
    out += _U32.pack(n_cols)
    if not n_rows or not n_cols:
        return
    for col in zip(*rows):
        _write_column(out, col)


def _write_column(out: bytearray, col: tuple) -> None:
    if all(_is_i64(v) for v in col):
        out += _COL_I64
        out += np.asarray(col, dtype=">i8").tobytes()
    elif all(_is_f64(v) for v in col):
        out += _COL_F64
        out += np.asarray(col, dtype=">f8").tobytes()
    elif all(type(v) is str for v in col):
        encoded = [v.encode("utf-8") for v in col]
        offsets = np.zeros(len(col) + 1, dtype=">u4")
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        out += _COL_STR
        out += _U32.pack(len(blob))
        out += offsets.tobytes()
        out += blob
    else:
        # heterogeneous / complex cells (pairs, sketches, None, bool,
        # bigint, bytes): one tagged object list for the whole column —
        # still no per-ROW tuple headers
        out += _COL_OBJ
        _w_obj(out, list(col))


def _read_columnar(b: bytes, off: int):
    n_rows = _U32.unpack_from(b, off)[0]
    off += 4
    n_cols = _U32.unpack_from(b, off)[0]
    off += 4
    if not n_rows or not n_cols:
        return [() for _ in range(n_rows)], off
    cols = []
    for _ in range(n_cols):
        col, off = _read_column(b, off, n_rows)
        cols.append(col)
    return list(zip(*cols)), off


def _read_column(b: bytes, off: int, n: int):
    tag = b[off:off + 1]
    off += 1
    if tag == _COL_I64:
        end = off + n * 8
        return np.frombuffer(b, dtype=">i8", count=n,
                             offset=off).tolist(), end
    if tag == _COL_F64:
        end = off + n * 8
        return np.frombuffer(b, dtype=">f8", count=n,
                             offset=off).tolist(), end
    if tag == _COL_STR:
        blob_len = _U32.unpack_from(b, off)[0]
        off += 4
        offsets = np.frombuffer(b, dtype=">u4", count=n + 1, offset=off)
        off += (n + 1) * 4
        blob = b[off:off + blob_len]
        off += blob_len
        return [blob[offsets[i]:offsets[i + 1]].decode("utf-8")
                for i in range(n)], off
    if tag == _COL_OBJ:
        col, off = _r_obj(b, off)
        return col, off
    raise ValueError(f"bad DataTable column tag {tag!r} at {off - 1}")


def amend_metadata_bytes(b: bytes, updates: Dict[str, str]) -> bytes:
    """Rewrite ONLY the metadata map of a serialized DataTable.

    The server result-cache hit path stamps per-request keys
    (requestId, resultCacheHit) onto cached payloads; a full
    from_bytes/to_bytes round-trip there decodes and re-encodes every
    row — burning, on multi-MB selection results, exactly the CPU the
    cache exists to save under overload. The metadata map sits at a
    fixed offset right after the 9-byte header, so it can be spliced
    at memcpy cost without touching exceptions/schema/rows."""
    version = _U32.unpack_from(b, 0)[0]
    if version not in (_LEGACY_VERSION, VERSION):
        raise ValueError(f"unsupported DataTable version {version}")
    off = 9                   # version(4) + kind(1) + numGroupCols(4)
    metadata, end = _r_obj(b, off)
    md = dict(metadata)
    md.update(updates)
    out = bytearray(b[:off])
    _w_obj(out, md)
    out += b[end:]
    return bytes(out)


def _w_obj(out: bytearray, v) -> None:
    b = obj_to_bytes(v)
    out += _U32.pack(len(b))
    out += b


def _r_obj(b: bytes, off: int):
    n = _U32.unpack_from(b, off)[0]
    off += 4
    return obj_from_bytes(b[off:off + n]), off + n
