"""Minion worker: claims tasks, converts segments, re-uploads.

Parity: pinot-minion/.../MinionStarter.java + TaskFactory — a Helix
participant that runs task-framework jobs. Here the worker polls the
property-store task queue (atomic claim), downloads the segment from the
deep store, runs the registered executor, uploads the converted segment
through the controller manager (a refresh bounce re-loads it on
servers), and marks the task COMPLETED/ERROR.
"""
from __future__ import annotations

import os
import shutil
import tempfile
import threading
import traceback
from typing import List, Optional

from pinot_tpu.minion.executors import (MinionContext, TaskExecutorRegistry)
from pinot_tpu.minion.tasks import (COMPLETED, ERROR, SEGMENT_NAME_KEY,
                                    TABLE_NAME_KEY, PinotTaskConfig,
                                    TaskQueue)


class MinionEventObserver:
    """Task lifecycle callbacks (parity: pinot-minion's
    MinionEventObserver SPI + MinionEventObserverFactory — observers are
    notified at task start / success / error, e.g. for metrics or
    progress reporting). Default methods are no-ops so observers
    override only what they need."""

    def notify_task_start(self, task: PinotTaskConfig) -> None:
        pass

    def notify_task_success(self, task: PinotTaskConfig) -> None:
        pass

    def notify_task_error(self, task: PinotTaskConfig,
                          error: BaseException) -> None:
        pass


class MinionWorker:
    def __init__(self, manager, instance_id: str = "Minion_0",
                 work_dir: Optional[str] = None,
                 registry: Optional[TaskExecutorRegistry] = None,
                 context: Optional[MinionContext] = None,
                 observers: Optional[List[MinionEventObserver]] = None):
        self.manager = manager                      # ControllerManager
        self.instance_id = instance_id
        self.queue = TaskQueue(manager.store)
        self.registry = registry or TaskExecutorRegistry()
        self.observers: List[MinionEventObserver] = list(observers or ())
        self.context = context or MinionContext()
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="minion_")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- single task ------------------------------------------------------

    def run_one(self) -> Optional[str]:
        """Claim and execute one task; returns its id or None when idle."""
        task = self.queue.claim(self.instance_id,
                                self.registry.task_types())
        if task is None:
            return None
        self._notify(lambda o: o.notify_task_start(task))
        try:
            self._execute(task)
            self.queue.finish(task, COMPLETED)
            self._notify(lambda o: o.notify_task_success(task))
        except Exception as e:  # noqa: BLE001 — task isolation boundary
            self.queue.finish(task, ERROR,
                              f"{type(e).__name__}: {e}\n"
                              f"{traceback.format_exc(limit=5)}")
            self._notify(lambda o: o.notify_task_error(task, e))
        return task.task_id

    def _notify(self, fn) -> None:
        for obs in self.observers:
            try:
                fn(obs)
            except Exception:  # noqa: BLE001 — observers never break tasks
                pass

    def _execute(self, task: PinotTaskConfig) -> None:
        table = task.configs[TABLE_NAME_KEY]
        segments = [s for s in
                    task.configs.get(SEGMENT_NAME_KEY, "").split(",") if s]
        executor = self.registry.get(task.task_type)
        if executor is None:
            raise ValueError(f"no executor for task type {task.task_type}")
        from pinot_tpu.common.table_name import raw_table
        schema = self.manager.get_schema(raw_table(table)) or \
            self.manager.get_schema(table)
        config = self.manager.get_table_config(table)
        if schema is None or config is None:
            raise ValueError(f"missing schema/config for {table}")
        # download from the deep store (local-FS copy here; the PinotFS
        # SPI covers remote stores)
        inputs = []
        task_dir = os.path.join(self.work_dir, task.task_id)
        os.makedirs(task_dir, exist_ok=True)
        for seg in segments:
            meta = self.manager.segment_metadata(table, seg)
            if meta is None:
                raise ValueError(f"segment {seg} not found in {table}")
            local = os.path.join(task_dir, "in", seg)
            os.makedirs(os.path.dirname(local), exist_ok=True)
            # resolve by scheme: an HTTP-advertised downloadPath fetches
            # through the deep-store client (re-based onto the current
            # controller endpoint), local paths copy directly
            from pinot_tpu.common.filesystem import get_fs
            src = self.manager.resolve_download_path(meta["downloadPath"])
            src_fs = get_fs(src) if "://" in src else self.manager.fs
            src_fs.copy(src, local)
            # minions verify inputs like servers do — a corrupt artifact
            # must not be silently merged/purged into a new segment
            from pinot_tpu.segment.integrity import verify_segment
            verify_segment(local, meta.get("crc"))
            inputs.append(local)
        out_dir = os.path.join(task_dir, "out")
        os.makedirs(out_dir, exist_ok=True)
        result = executor.execute(task, schema, config, inputs, out_dir,
                                  self.context)
        self.manager.add_segment(table, result.out_dir)
        shutil.rmtree(task_dir, ignore_errors=True)

    # -- background loop --------------------------------------------------

    def start(self, poll_interval_s: float = 0.2) -> None:
        def loop():
            while not self._stop.is_set():
                if self.run_one() is None:
                    self._stop.wait(poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=self.instance_id)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def drain(self) -> List[str]:
        """Run queued tasks to completion (test/batch convenience)."""
        done = []
        while True:
            tid = self.run_one()
            if tid is None:
                return done
            done.append(tid)
