"""Version-portability shims over the JAX surface pinot_tpu depends on.

The engine is written against the modern JAX API; installed versions
skew in both directions (the seed shipped `jax.shard_map` call sites
onto jax 0.4.37, where the symbol lives at
`jax.experimental.shard_map.shard_map` — 33 tier-1 failures from one
name). Every version-sensitive symbol is resolved HERE, once, by
probing the installed jax with getattr — which also keeps call sites
clean under tpulint's api-compat rule: `pinot_tpu.compat.shard_map`
always resolves, whatever jax is underneath.
"""
from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp

_shard_map_impl = getattr(jax, "shard_map", None)
if _shard_map_impl is None:
    # jax < 0.6: experimental spelling, `check_rep` instead of `check_vma`
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` resolved by availability.

    Accepts the modern keyword surface and translates `check_vma` to
    the pre-0.6 `check_rep` when running on the experimental impl.
    """
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if "check_vma" in _SHARD_MAP_PARAMS:
        kwargs["check_vma"] = check_vma
    else:
        kwargs["check_rep"] = check_vma
    return _shard_map_impl(f, **kwargs)


def wide_i64(value):
    """A genuinely-64-bit int constant for math on int64/float64 lanes.

    A bare ``jnp.int64(x)`` is a lie when x64 is disabled: it silently
    builds an int32, and any mask/shift arithmetic written for 64-bit
    lanes truncates without a whisper (tpulint's dtype-drift rule exists
    for exactly this). This helper asserts the intent instead: the
    caller is operating on a lane whose dtype IS 64-bit, which can only
    happen with x64 enabled — calling it in 32-bit mode is a programmer
    error surfaced at trace time, not a silent truncation at query time.
    """
    if not jax.config.jax_enable_x64:
        raise AssertionError(
            "wide_i64 used while x64 is disabled — a 64-bit lane cannot "
            "exist here; the surrounding dtype dispatch is wrong")
    return jnp.int64(value)  # tpulint: disable=dtype-drift -- the one sanctioned 64-bit constructor: guarded by the x64 assertion above
