"""Broker-side cluster spectator: external views → routing + time boundary.

Parity: HelixBrokerStarter's spectator role —
HelixExternalViewBasedRouting.processExternalViewChange (:418) rebuilds
routing tables, and HelixExternalViewBasedTimeBoundaryService recomputes
hybrid boundaries from offline segment metadata.
"""
from __future__ import annotations

from typing import Optional

from pinot_tpu.broker.routing import RoutingManager
from pinot_tpu.broker.time_boundary import TimeBoundaryService
from pinot_tpu.common.cluster_state import ONLINE, TableView
from pinot_tpu.common.table_name import raw_table, table_type
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.state_machine import ClusterCoordinator


class BrokerClusterWatcher:
    def __init__(self, coordinator: ClusterCoordinator,
                 manager: ResourceManager,
                 routing: Optional[RoutingManager] = None,
                 time_boundary: Optional[TimeBoundaryService] = None):
        self.coordinator = coordinator
        self.manager = manager
        self.routing = routing or RoutingManager()
        self.time_boundary = time_boundary or TimeBoundaryService()
        self.partition_pruner = PartitionZKMetadataPruner(manager)
        coordinator.watch_external_views(self._on_view)
        for table in coordinator.tables():
            self._on_view(coordinator.external_view(table))

    def _on_view(self, view: TableView) -> None:
        self.partition_pruner.invalidate(view.table_name)
        if not view.segment_states:
            self.routing.remove_table(view.table_name)
            return
        self._apply_routing_config(view.table_name)
        self.routing.update_view(view)
        if table_type(view.table_name) == "OFFLINE":
            self._update_time_boundary(view)

    def _apply_routing_config(self, table: str) -> None:
        """Honor the table's routingTableBuilderName (parity:
        HelixExternalViewBasedRouting reading RoutingConfig)."""
        from pinot_tpu.broker.routing import make_routing_builder
        config = self.manager.get_table_config(table)
        if config is None:
            return
        rc = config.routing_config

        def partition_lookup(segment: str, _t=table):
            """Segment -> recorded partition-id union across partitioned
            columns (the PartitionAware builder's grouping key)."""
            return self.partition_pruner.segment_partitions(_t, segment)

        builder = make_routing_builder(rc.builder_name, rc.options,
                                       partition_lookup=partition_lookup)
        target = builder if builder is not None else self.routing.builder
        # builder-kind comparison: re-applying the same kind would only
        # churn (option-only changes take effect on broker restart)
        if type(target) is not type(self.routing.table_builder(table)):
            # the caller pushes the fresh view right after: no rebuild
            self.routing.set_table_builder(table, builder, rebuild=False)

    def _update_time_boundary(self, view: TableView) -> None:
        offline_table = view.table_name
        schema = self.manager.get_schema(raw_table(offline_table))
        if schema is None:
            return
        tc = schema.time_column
        if tc is None:
            return
        # Only segments actually served (at least one ONLINE replica in the
        # external view — matching what RoutingManager will route to) may
        # advance the boundary, and non-positive end times are skipped —
        # parity: HelixExternalViewBasedTimeBoundaryService filters to the EV
        # and ignores endTime <= 0. With an async coordinator the property
        # store can hold segments no server serves yet; advancing past them
        # would silently drop rows from hybrid results.
        served = {seg for seg, states in view.segment_states.items()
                  if ONLINE in states.values()}
        ends, unit = [], None
        for seg in self.manager.segment_names(offline_table):
            if seg not in served:
                continue
            meta = self.manager.segment_metadata(offline_table, seg) or {}
            end = meta.get("endTime")
            if end is not None and end > 0:
                ends.append(end)
                unit = meta.get("timeUnit") or unit
        if ends:
            self.time_boundary.update_from_segments(
                offline_table, tc.name, unit or "DAYS", ends)


class PartitionZKMetadataPruner:
    """Broker-side partition pruning from segment ZK records.

    Parity: pinot-broker/.../pruner/PartitionZKMetadataPruner — before
    scatter, EQ predicates on partitioned columns eliminate segments
    whose recorded partition-id sets cannot match, cutting server
    fan-out (the functional outcome of the reference's partition-aware
    routing builders). Partition metadata and schemas are cached per
    table; BrokerClusterWatcher invalidates the cache on external-view
    changes, keeping the query hot path free of property-store reads.
    Any malformed metadata fails OPEN (segment kept, never dropped).
    """

    def __init__(self, manager: ResourceManager):
        self.manager = manager
        self._meta: dict = {}      # table → {segment: partitionMetadata}
        self._schemas: dict = {}   # table → Schema | None

    def invalidate(self, table: str) -> None:
        self._meta.pop(table, None)
        self._schemas.pop(table, None)

    def _table_meta(self, table: str) -> dict:
        cached = self._meta.get(table)
        if cached is None:
            cached = {}
            for seg in self.manager.segment_names(table):
                rec = self.manager.segment_metadata(table, seg) or {}
                pm = rec.get("partitionMetadata") or {}
                if pm:
                    cached[seg] = pm
            self._meta[table] = cached
        return cached

    def _schema(self, table: str):
        if table not in self._schemas:
            self._schemas[table] = self.manager.get_schema(
                raw_table(table))
        return self._schemas[table]

    def segment_partitions(self, table: str, segment: str):
        """Recorded partition-id union across a segment's partitioned
        columns, or None — the public lookup the partition-aware routing
        builder groups by (same cache the pruner reads)."""
        pm = self._table_meta(table).get(segment)
        if not pm:
            return None
        ids = set()
        for info in pm.values():
            ids.update(info.get("partitions") or ())
        return ids or None

    def prune(self, request, table: str, segments):
        try:
            meta = self._table_meta(table)
            if not meta:
                return list(segments)
            schema = self._schema(table)
            memo: dict = {}
            kept = []
            for seg in segments:
                pm = meta.get(seg)
                if pm and self._pruned(request.filter, pm, schema, memo):
                    continue
                kept.append(seg)
            return kept
        except Exception:  # noqa: BLE001 — pruning is an optimization:
            return list(segments)      # fail open on any metadata issue

    def _pruned(self, node, pm, schema, memo) -> bool:
        from pinot_tpu.common.request import FilterOperator
        if node is None:
            return False
        if node.operator == FilterOperator.AND:
            return any(self._pruned(c, pm, schema, memo)
                       for c in node.children)
        if node.operator == FilterOperator.OR:
            return all(self._pruned(c, pm, schema, memo)
                       for c in node.children)
        if node.operator != FilterOperator.EQUALITY:
            return False
        info = pm.get(node.column)
        if not info or not info.get("partitions"):
            return False
        from pinot_tpu.common.partition import partition_of_value
        key = (node.column, info["functionName"],
               int(info["numPartitions"]), node.values[0])
        p = memo.get(key)
        if p is None:
            dt = None
            if schema is not None and schema.has_column(node.column):
                dt = schema.field(node.column).data_type.np_dtype
            try:
                p = partition_of_value(info["functionName"],
                                       int(info["numPartitions"]),
                                       dt, node.values[0])
            except Exception:  # noqa: BLE001 — unknown function: keep
                p = -1
            memo[key] = p
        return p >= 0 and p not in set(info["partitions"])
