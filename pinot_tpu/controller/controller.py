"""Controller process wiring.

Parity: pinot-controller/.../ControllerStarter.java:77-444 — connects the
cluster coordinator, resource manager and periodic tasks. (The reference
additionally hosts the Helix controller and a Jersey REST API; the REST
admin surface here lives in pinot_tpu/tools and the coordinator is
in-process.)

HA shape (``ha=True``): the controller runs against a SHARED store (its
own or a remote one also serving a peer controller), holds a renewable
leader lease with a fencing token, and routes every cluster mutation
through a FencedStore so a deposed leader's in-flight writes are
rejected. Periodic tasks stay lead-gated as before; the leadership
heartbeat renews the lease at lease/3.
"""
from __future__ import annotations

from typing import List, Optional

from pinot_tpu.common.metrics import (ControllerGauge, ControllerMeter,
                                      MetricsRegistry)
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.periodic import (PeriodicTask,
                                           PeriodicTaskScheduler,
                                           RealtimeSegmentValidationManager)
from pinot_tpu.controller.leadership import (ControllerLeadershipManager,
                                             FencedStore)
from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.controller.realtime_manager import RealtimeSegmentManager
from pinot_tpu.controller.rebalance import (ClusterHealthMonitor,
                                            SegmentRebalancer,
                                            replication_deficit)
from pinot_tpu.controller.state_machine import ClusterCoordinator


class Controller:
    def __init__(self, deep_store_dir: str,
                 store: Optional[PropertyStore] = None,
                 periodic_tasks: Optional[List[PeriodicTask]] = None,
                 instance_id: str = "Controller_0",
                 store_dir: Optional[str] = None,
                 ha: bool = False,
                 lease_s: Optional[float] = None):
        """`store_dir`: when the controller constructs its own store,
        persist cluster state (WAL + snapshots) under this directory so
        a restarted controller recovers tables, ideal states, segment
        records and the realtime FSM's durable inputs.
        `ha`: multi-controller deployment — mutations go through a
        FencedStore bound to this instance's leader lease (fencing
        token), and start()/stop() run the lease heartbeat. `lease_s`
        overrides the leader-lease TTL (HA failover happens within one
        lease period)."""
        self._owns_store = store is None
        self.store = store or PropertyStore(data_dir=store_dir)
        self.metrics = MetricsRegistry("controller")
        from pinot_tpu.obs import residency
        residency.bind_registry(self.metrics)
        # leadership elects on the RAW store (the election CAS is the
        # fence's ground truth and must never be fenced itself)
        self.leadership = ControllerLeadershipManager(
            self.store, instance_id, metrics=self.metrics,
            **({"lease_s": lease_s} if lease_s is not None else {}))
        self.ha = ha
        mutation_store = FencedStore(self.store, self.leadership) \
            if ha else self.store
        self.coordinator = ClusterCoordinator(mutation_store)
        self.manager = ResourceManager(self.coordinator, deep_store_dir)
        self.realtime = RealtimeSegmentManager(self.manager,
                                               metrics=self.metrics)
        self.rebalancer = SegmentRebalancer(self.manager,
                                            metrics=self.metrics)
        # minion maintenance plane: swap protocol driver + task queue
        from pinot_tpu.controller.compaction import SegmentSwapManager
        from pinot_tpu.minion.task_manager import PinotTaskManager
        self.swaps = SegmentSwapManager(self.manager,
                                        metrics=self.metrics)
        self.task_manager = PinotTaskManager(self.manager,
                                             metrics=self.metrics)
        # always-present cluster gauges (parity: ControllerMetrics'
        # tableCount/segmentCount-style validation gauges) — /metrics is
        # never empty, even before any periodic task ran
        self.metrics.gauge(ControllerGauge.TABLE_COUNT).set_callable(
            lambda: len(self.manager.table_names()))
        self.metrics.gauge(ControllerGauge.SCHEMA_COUNT).set_callable(
            lambda: len(self.manager.store.children("/CONFIGS/SCHEMA")))
        self.metrics.gauge(
            ControllerGauge.CLUSTER_REPLICATION_DEFICIT).set_callable(
                lambda: replication_deficit(self.manager))
        # self-healing + maintenance meters exist at 0 from boot so
        # /metrics exposition always carries them
        for name in (ControllerMeter.REBALANCE_MOVES,
                     ControllerMeter.PARTITION_TAKEOVERS,
                     ControllerMeter.LEADER_FAILOVERS,
                     ControllerMeter.SEGMENTS_COMPACTED,
                     ControllerMeter.SEGMENTS_MERGED,
                     ControllerMeter.RETENTION_SEGMENTS_DELETED,
                     ControllerMeter.SWAPS_RESUMED,
                     ControllerMeter.TOMBSTONES_DELETED):
            self.metrics.meter(name)
        self.periodic = PeriodicTaskScheduler(self.manager, periodic_tasks,
                                              leadership=self.leadership,
                                              metrics=self.metrics)
        if periodic_tasks is None:
            # scheduler owns the defaults; the controller appends the
            # tasks that need its realtime manager / rebalancer /
            # minion task manager / swap driver
            from pinot_tpu.controller.compaction import SwapJanitor
            from pinot_tpu.controller.periodic import MinionTaskScheduler
            self.health_monitor = ClusterHealthMonitor(
                rebalancer=self.rebalancer,
                realtime_manager=self.realtime,
                metrics=self.metrics)
            self.periodic.tasks.append(self.health_monitor)
            self.periodic.tasks.append(
                RealtimeSegmentValidationManager(self.realtime))
            self.periodic.tasks.append(
                MinionTaskScheduler(self.task_manager))
            self.periodic.tasks.append(
                SwapJanitor(self.swaps, metrics=self.metrics))
            for task in self.periodic.tasks:
                if getattr(task, "rebalancer", "missing") is None:
                    task.rebalancer = self.rebalancer
        else:
            self.health_monitor = None

    def start(self) -> None:
        if self.ha:
            # claim (or queue behind) the lease NOW so a lead
            # controller's admin writes pass the fence immediately,
            # then renew at lease/3 — a dead leader is succeeded within
            # one lease period
            self.leadership.try_acquire()
            self.leadership.start()
        self.periodic.start()

    def stop(self) -> None:
        self.periodic.stop()
        if self.ha:
            self.leadership.stop()      # graceful: resign the lease
        self.manager.close()
        if self._owns_store:
            self.store.close()
