"""Stage-2 join context: exchanged dim blocks → probe/gather tables.

The JoinContext is built once per server query from the fetched stage-1
dim blocks (already dim-filtered, already upsert-masked by the normal
scan path) and attached to the server-local request copy as
``request._join_ctx``; the planner (query/plan.py `_resolve_join_pred` /
`_plan_group_by`) and the host oracle (query/host_exec.py `_join_probe`)
both read it, so every execution path probes the SAME dim arrays.

Join-key contract: single-value INTEGER columns on both sides, and dim
keys UNIQUE (star-schema PK semantics — each fact row matches at most
one dim row). Violations raise StageCompileError → typed 4xx at the
broker, never a crash.

Co-partitioned dispatch: when both tables are partitioned on their join
keys by the same function, each published dim block carries the
partition ids of the segments it scanned, and `filter_sources` drops
sources disjoint from the fact server's own partitions. This is purely
a transfer optimization — fetching a superset of the needed dim rows
never changes the probe result (a dim row of another partition can
match no local fact key by the shared-partition-function premise), so
the mode is safe to decide per-server from segment metadata alone.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.request import JoinSpec
from pinot_tpu.query.stages import exchange
from pinot_tpu.query.stages.errors import StageCompileError

#: dim-side row cap for a broadcast join — one device selection window
#: (plan.MAX_SELECTION_K); the stage-1 publish fails loudly past it
DIM_CAP = 1 << 16


def columns_of(dt: DataTable) -> Dict[str, object]:
    """name → column (numpy array or list) from a selection DataTable,
    preferring the zero-copy v3 column blocks."""
    if dt.col_data is not None and dt._rows is None:
        return dict(zip(dt.columns, dt.col_data))
    cols = list(zip(*dt.rows)) if dt.rows else \
        [() for _ in dt.columns]
    return {name: list(col) for name, col in zip(dt.columns, cols)}


class JoinContext:
    """Probe/gather tables over the assembled dim side."""

    def __init__(self, spec: JoinSpec, keys: np.ndarray,
                 columns: Dict[str, object]):
        self.spec = spec
        self.fact_key = spec.fact_key
        self.dim_table = spec.dim_table
        if len(keys) and (not isinstance(keys, np.ndarray) or
                          keys.dtype.kind not in "iu"):
            raise StageCompileError(
                f"join keys must be INTEGER columns; dim key "
                f"'{spec.dim_key}' decoded as "
                f"{getattr(keys, 'dtype', type(keys).__name__)}")
        self.keys = np.asarray(keys, dtype=np.int64)
        if len(np.unique(self.keys)) != len(self.keys):
            raise StageCompileError(
                f"dim join key '{spec.dim_key}' values are not unique — "
                "inner joins require star-schema PK semantics on the "
                "dim side")
        self._columns = columns
        self.order = np.argsort(self.keys, kind="stable").astype(np.int64)
        self.skeys = self.keys[self.order]
        self._lock = threading.Lock()
        self._member_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}  # tpulint: disable=cache-bound -- keyed by id(dictionary): bounded by the query's segment count; the context dies with the query
        self._codings: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}  # tpulint: disable=cache-bound -- one coding per projected dim column: bounded by the join's column list
        # residency: the probe tables become jitted-kernel operands (one
        # implicit upload per dispatch); account them for the context's
        # lifetime — a query holds at most its own dim side, and the
        # finalizer releases when the stage's plan drops the context
        import weakref
        from pinot_tpu.obs import residency
        nbytes = (self.keys.nbytes + self.order.nbytes +
                  self.skeys.nbytes +
                  sum(c.nbytes for c in columns.values()
                      if isinstance(c, np.ndarray)))
        owner = f"join:{id(self)}"
        residency.LEDGER.register(owner, table=spec.dim_table or "",
                                  segment="", kind="join", nbytes=nbytes)
        weakref.finalize(self, residency.LEDGER.release, owner)

    @property
    def empty(self) -> bool:
        return len(self.keys) == 0

    # -- probe -------------------------------------------------------------
    def _translate(self, values: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """(hit bool, dim row int64) per entry of `values` (any integer
        array — a dictionary's value table or a raw per-row lane).
        Cached per dictionary object for the per-segment planning path."""
        key = id(values)
        with self._lock:
            cached = self._member_cache.get(key)
        if cached is not None:
            return cached
        v = np.asarray(values, dtype=np.int64)
        if len(self.skeys):
            pos = np.clip(np.searchsorted(self.skeys, v), 0,
                          len(self.skeys) - 1)
            hit = self.skeys[pos] == v
            dimrow = self.order[pos]
        else:
            hit = np.zeros(len(v), dtype=bool)
            dimrow = np.zeros(len(v), dtype=np.int64)
        with self._lock:
            return self._member_cache.setdefault(key, (hit, dimrow))

    def member_for(self, dict_values: np.ndarray) -> np.ndarray:
        """bool [cardinality]: which fact dictIds join (the member-vector
        predicate of the dict-keyed probe)."""
        return self._translate(dict_values)[0]

    def probe_values(self, values: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Row-domain probe (host oracle path): (hit, dimrow) —
        uncached, values are per-query row lanes."""
        v = np.asarray(values, dtype=np.int64)
        if not len(self.skeys):
            return np.zeros(len(v), dtype=bool), \
                np.zeros(len(v), dtype=np.int64)
        pos = np.clip(np.searchsorted(self.skeys, v), 0,
                      len(self.skeys) - 1)
        hit = self.skeys[pos] == v
        return hit, self.order[pos]

    # -- dim columns -------------------------------------------------------
    def dim_values(self, dcol: str) -> np.ndarray:
        col = self._columns.get(dcol)
        if col is None:
            raise StageCompileError(
                f"dim column '{dcol}' was not shipped by the stage-1 "
                "scan")
        return col if isinstance(col, np.ndarray) else \
            np.asarray(col, dtype=object)

    def group_coding(self, dcol: str) -> Tuple[np.ndarray, np.ndarray]:
        """(codes int32 [D], uniques): the dim column factorized — codes
        are the group-key domain the kernels aggregate in, uniques the
        decode table."""
        with self._lock:
            cached = self._codings.get(dcol)
        if cached is not None:
            return cached
        vals = self.dim_values(dcol)
        uniq, inv = np.unique(vals, return_inverse=True)
        coding = (inv.astype(np.int32), uniq)
        with self._lock:
            return self._codings.setdefault(dcol, coding)

    def code_table_for(self, dict_values: np.ndarray, dcol: str,
                       card_pad: int) -> np.ndarray:
        """int32 [card_pad] fact-dictId → dim group code (0 on misses —
        masked by the join predicate everywhere)."""
        hit, dimrow = self._translate(dict_values)
        codes, _uniq = self.group_coding(dcol)
        table = np.zeros(card_pad, dtype=np.int32)
        table[: len(hit)][hit] = codes[dimrow[hit]]
        return table

    # -- raw-key device operands -------------------------------------------
    def _dtype_mask(self, np_dtype) -> np.ndarray:
        """Dim keys representable in the fact key dtype (others can match
        no fact value and are dropped — a cast that WRAPPED them would
        fabricate matches)."""
        info = np.iinfo(np_dtype)
        return (self.keys >= info.min) & (self.keys <= info.max)

    def padded_keys(self, np_dtype) -> Optional[np.ndarray]:
        """Device probe operand: dim keys in the fact dtype, pow2-padded
        by REPEATING the max key (duplicates of a real key can neither
        create nor destroy a match). None when no key is representable."""
        from pinot_tpu.ops.kernels import pow2_bucket
        keys = self.keys[self._dtype_mask(np_dtype)].astype(np_dtype)
        if not len(keys):
            return None
        d_pad = pow2_bucket(len(keys), floor=8)
        out = np.full(d_pad, keys.max(), dtype=np_dtype)
        out[: len(keys)] = keys
        return out

    def padded_key_codes(self, dcol: str, np_dtype
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """(keys [Dp], group codes [Dp] int32) for the jraw device
        probe; padding repeats (max key, its code) so padding-run probe
        hits resolve to the right code."""
        from pinot_tpu.ops.kernels import pow2_bucket
        codes, _uniq = self.group_coding(dcol)
        mask = self._dtype_mask(np_dtype)
        keys = self.keys[mask].astype(np_dtype)
        kcodes = codes[mask]
        if not len(keys):
            return (np.zeros(8, dtype=np_dtype),
                    np.zeros(8, dtype=np.int32))
        d_pad = pow2_bucket(len(keys), floor=8)
        mx = int(np.argmax(keys))
        out_k = np.full(d_pad, keys[mx], dtype=np_dtype)
        out_c = np.full(d_pad, kcodes[mx], dtype=np.int32)
        out_k[: len(keys)] = keys
        out_c[: len(keys)] = kcodes
        return out_k, out_c


# ---------------------------------------------------------------------------
# Context assembly (stage-2 entry on the fact server)
# ---------------------------------------------------------------------------


def filter_sources(sources: List[dict],
                   fact_parts: Optional[Tuple[str, int, set]]
                   ) -> Tuple[List[dict], int]:
    """Co-partitioned dispatch: drop sources whose partition tags are
    provably disjoint from this server's fact partitions. `fact_parts`:
    (function name, num partitions, partition-id set) or None (unknown
    → fetch everything: a superset is always correct)."""
    if fact_parts is None:
        return list(sources), 0
    fn, n, pids = fact_parts
    kept: List[dict] = []
    skipped = 0
    for s in sources:
        parts = s.get("partitions")
        if parts is None or s.get("partitionFunction") != fn or \
                s.get("numPartitions") != n:
            kept.append(s)
            continue
        if set(parts) & pids:
            kept.append(s)
        else:
            skipped += 1
    return kept, skipped


def fact_partition_info(segments, fact_key: str
                        ) -> Optional[Tuple[str, int, set]]:
    """(function, N, partition ids) of the fact key column across the
    query's segments — None unless EVERY segment is consistently tagged
    (the only condition under which skipping a source is provably safe)."""
    fn = None
    n = 0
    pids: set = set()
    for seg in segments:
        if not seg.has_column(fact_key):
            return None
        cm = seg.data_source(fact_key).metadata
        if not cm.partition_function or not cm.partitions:
            return None
        if fn is None:
            fn, n = cm.partition_function, cm.num_partitions
        elif (cm.partition_function, cm.num_partitions) != (fn, n):
            return None
        pids.update(cm.partitions)
    return None if fn is None else (fn, n, pids)


def build_context(spec: JoinSpec, sources: List[dict],
                  fact_parts: Optional[Tuple[str, int, set]],
                  deadline_s: Optional[float] = None) -> JoinContext:
    """Fetch the (partition-filtered) dim blocks and assemble the
    probe context. Deterministic assembly order: sources sorted by
    (server, id) so every replica builds identical arrays."""
    chosen, skipped = filter_sources(sources, fact_parts)
    chosen = sorted(chosen, key=lambda s: (str(s.get("server")),
                                           str(s.get("id"))))
    blocks = exchange.fetch_blocks(chosen, deadline_s)
    key_parts: List[np.ndarray] = []
    col_parts: Dict[str, list] = {c: [] for c in spec.dim_columns}
    for dt in blocks:
        cols = columns_of(dt)
        if spec.dim_key not in cols:
            raise StageCompileError(
                f"stage-1 dim block is missing the join key column "
                f"'{spec.dim_key}'")
        key_col = cols[spec.dim_key]
        if not isinstance(key_col, np.ndarray):
            key_col = np.asarray(key_col)
        key_parts.append(key_col)
        for c in spec.dim_columns:
            col = cols.get(c)
            if col is None:
                raise StageCompileError(
                    f"stage-1 dim block is missing column '{c}'")
            col_parts[c].append(col)
    if key_parts:
        kp = [np.asarray(k) for k in key_parts]
        if any(k.dtype.kind not in "iu" for k in kp if len(k)):
            raise StageCompileError(
                f"join keys must be INTEGER columns; dim key "
                f"'{spec.dim_key}' decoded as "
                f"{[str(k.dtype) for k in kp]}")
        keys = np.concatenate([k.astype(np.int64) for k in kp]) \
            if kp else np.zeros(0, np.int64)
    else:
        keys = np.zeros(0, np.int64)
    columns: Dict[str, object] = {}
    for c, parts in col_parts.items():
        if all(isinstance(p, np.ndarray) for p in parts) and parts:
            columns[c] = np.concatenate(parts)
        else:
            merged: list = []
            for p in parts:
                merged.extend(list(p))
            columns[c] = np.asarray(merged, dtype=object)
    ctx = JoinContext(spec, keys, columns)
    ctx.sources_skipped = skipped
    return ctx
