"""Resource-lifecycle tier (``--lifecycle``): HBM residency rules.

Two per-file rule families guard the invariants ROADMAP item 1's
residency manager will budget against:

- ``device-ledger``: every host→device materialization on the serving
  path must route through ``obs/residency.py`` (``ledgered_put`` /
  ``ledgered_asarray``) so the bytes land in the process ledger. A raw
  ``jax.device_put`` / ``jnp.asarray`` at dispatch scope is an upload
  the ledger cannot see — exactly how "what is holding HBM" questions
  become unanswerable. Calls INSIDE jitted functions are trace-time ops
  (no host→device transfer of their own) and are exempt, mirroring the
  host-sync rule's jit-scope reasoning.

- ``cache-bound``: every memoization-shaped container on the query path
  (a dict/list/set attr or module global that is both membership-read
  and inserted into) must carry a STRUCTURAL bound the AST can see —
  eviction (``pop``/``popitem``/``del``/``clear``), whole-container
  reassignment outside ``__init__`` (generation swap), a ``len()``
  guard (size cap), or ``deque(maxlen=...)``. Growth with none of these
  is how the soak's flat-RSS gate regresses one innocent-looking cache
  at a time. Genuinely extrinsic bounds (a cache keyed by cluster
  membership, a per-query context) state their invariant in a
  ``# tpulint: disable=cache-bound -- <why bounded>`` suppression, per
  the PR 7 "by analysis, not suppression" bar for everything else.

Both rules are per-file ``check(ctx)`` rules (tier "lifecycle"), so
line suppressions, fixtures and the baseline behave exactly like the
fast tier.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

#: the serving path: modules whose device uploads serve queries (and
#: must therefore be accounted). tools/, tests/, benchmarks stay out —
#: a datagen upload is not resident serving state.
SERVING_PREFIXES = (
    "pinot_tpu/segment/", "pinot_tpu/parallel/", "pinot_tpu/query/",
    "pinot_tpu/realtime/", "pinot_tpu/server/", "pinot_tpu/broker/",
    "pinot_tpu/startree/",
)

#: resolved call targets that materialize a device array from host data
UPLOAD_CALLS = {"jax.device_put", "jax.numpy.asarray", "jax.numpy.array"}

#: the accountable choke points (and the module that owns the ledger)
LEDGER_CALLS = {"pinot_tpu.obs.residency.ledgered_put",
                "pinot_tpu.obs.residency.ledgered_asarray",
                "residency.ledgered_put", "residency.ledgered_asarray"}


def _jit_scope_nodes(tree: ast.AST, aliases: Dict[str, str]) -> Set[int]:
    """ids of every node inside a jit boundary: decorated-jitted
    functions, plus functions wrapped by name in a `jax.jit(...)` /
    `shard_map(...)` call anywhere in the file (the sharded executor's
    `jax.jit(shard_map(fn, ...))` idiom)."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = astutil.resolve(node.func, aliases) or ""
        if astutil.is_jit_expr(node.func, aliases) or \
                f.endswith("shard_map") or f.endswith("pmap") or \
                f.endswith("vmap"):
            for arg in node.args[:1]:
                inner = arg
                # unwrap nested wrappers: jit(shard_map(fn, mesh...))
                while isinstance(inner, ast.Call) and inner.args:
                    inner = inner.args[0]
                if isinstance(inner, ast.Name):
                    wrapped.add(inner.id)
    out: Set[int] = set()
    for fn in astutil.iter_functions(tree):
        if astutil.is_jitted(fn, aliases) or fn.name in wrapped:
            out.update(id(n) for n in ast.walk(fn))
    return out


@register
class DeviceLedgerRule(Rule):
    id = "device-ledger"
    description = ("serving-path device uploads must route through the "
                   "residency ledger (obs/residency.py)")
    tier = "lifecycle"

    def check(self, ctx) -> Iterator[Finding]:
        if not ctx.in_prefixes(SERVING_PREFIXES):
            return
        jit_nodes = _jit_scope_nodes(ctx.tree, ctx.aliases)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in jit_nodes:
                continue
            f = astutil.resolve(node.func, ctx.aliases)
            if f not in UPLOAD_CALLS:
                continue
            short = f.rsplit(".", 1)[-1]
            yield ctx.finding(
                self.id, node,
                f"unledgered device upload: {short}() materializes a "
                f"device array outside obs/residency.py — use "
                f"residency.ledgered_"
                f"{'put' if short == 'device_put' else 'asarray'}() so "
                f"the bytes are accounted")


# ---------------------------------------------------------------------------
# cache-bound
# ---------------------------------------------------------------------------

#: constructors that build a growable container
_CONTAINER_CTORS = {"dict", "list", "set", "collections.OrderedDict",
                    "collections.defaultdict", "collections.Counter",
                    "OrderedDict", "defaultdict", "Counter"}

_GROW_METHODS = {"setdefault", "append", "add", "appendleft"}
_EVICT_METHODS = {"pop", "popitem", "clear", "remove", "discard",
                  "popleft"}
_READ_METHODS = {"get"}


def _container_init(value: ast.AST, aliases: Dict[str, str]
                    ) -> Optional[str]:
    """"unbounded" / "bounded" when `value` constructs a container,
    None otherwise (deque(maxlen=...) is born bounded)."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return "unbounded"
    if isinstance(value, ast.Call):
        f = astutil.resolve(value.func, aliases) or ""
        if f in _CONTAINER_CTORS:
            return "unbounded"
        if f in ("collections.deque", "deque"):
            for kw in value.keywords:
                if kw.arg == "maxlen" and not (
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is None):
                    return "bounded"
            return "unbounded"
    return None


class _Usage:
    __slots__ = ("grown", "read", "bounded", "node")

    def __init__(self, node: ast.AST):
        self.grown = False
        self.read = False
        self.bounded = False
        self.node = node


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _scan_usage(body_nodes, usages: Dict[str, _Usage], key,
                init_scope: bool) -> None:
    """Fold growth/read/bound evidence for the tracked containers into
    `usages`. `key(node)` maps an expression to a tracked container
    name (attr name or global name) or None."""
    for node in body_nodes:
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            name = key(node.target)
            if name in usages and not init_scope:
                usages[name].bounded = True
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                # whole-container reassignment outside init: a
                # generation swap bounds the old contents
                name = key(tgt)
                if name in usages and not init_scope:
                    usages[name].bounded = True
                if isinstance(tgt, ast.Subscript):
                    name = key(tgt.value)
                    if name in usages:
                        usages[name].grown = True
        elif isinstance(node, ast.AugAssign):
            name = key(node.target)
            if name in usages:
                usages[name].grown = True
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                t = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                name = key(t)
                if name in usages:
                    usages[name].bounded = True
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute):
                name = key(node.func.value)
                if name in usages:
                    m = node.func.attr
                    if m in _GROW_METHODS:
                        usages[name].grown = True
                        if m == "setdefault":
                            usages[name].read = True
                    elif m in _EVICT_METHODS:
                        usages[name].bounded = True
                    elif m in _READ_METHODS:
                        usages[name].read = True
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == "len" and node.args:
                name = key(node.args[0])
                if name in usages:
                    usages[name].bounded = True    # a size guard/cap
        elif isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn))
                   for op in node.ops):
                for cand in node.comparators:
                    name = key(cand)
                    if name in usages:
                        usages[name].read = True


@register
class CacheBoundRule(Rule):
    id = "cache-bound"
    description = ("memoization-shaped containers on the query path "
                   "must carry a structural bound (eviction, swap, "
                   "size cap, or maxlen)")
    tier = "lifecycle"

    def check(self, ctx) -> Iterator[Finding]:
        if not ctx.in_prefixes(SERVING_PREFIXES):
            return
        yield from self._check_classes(ctx)
        yield from self._check_globals(ctx)

    def _check_classes(self, ctx) -> Iterator[Finding]:
        from pinot_tpu.analysis.callgraph import INIT_METHODS
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            usages: Dict[str, _Usage] = {}
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) or \
                        fn.name not in INIT_METHODS:
                    continue
                for node in astutil.walk_shallow(fn):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                        value = node.value
                    elif isinstance(node, ast.AnnAssign) and \
                            node.value is not None:
                        targets = [node.target]
                        value = node.value
                    else:
                        continue
                    if _container_init(value,
                                       ctx.aliases) != "unbounded":
                        continue
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr is not None and attr not in usages:
                            usages[attr] = _Usage(node)
            if not usages:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                _scan_usage(astutil.walk_shallow(fn), usages,
                            _self_attr,
                            init_scope=fn.name in INIT_METHODS)
            for attr, u in sorted(usages.items()):
                if u.grown and u.read and not u.bounded:
                    yield ctx.finding(
                        self.id, u.node,
                        f"cache '{cls.name}.{attr}' is read-guarded and "
                        f"inserted into but never evicted, swapped, or "
                        f"size-capped — an unbounded query-path cache")

    def _check_globals(self, ctx) -> Iterator[Finding]:
        usages: Dict[str, _Usage] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if _container_init(value, ctx.aliases) != "unbounded":
                continue
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id not in usages:
                    usages[tgt.id] = _Usage(node)
        if not usages:
            return

        def gkey(node: ast.AST) -> Optional[str]:
            return node.id if isinstance(node, ast.Name) else None

        for fn in astutil.iter_functions(ctx.tree):
            _scan_usage(astutil.walk_shallow(fn), usages, gkey,
                        init_scope=False)
        for name, u in sorted(usages.items()):
            if u.grown and u.read and not u.bounded:
                yield ctx.finding(
                    self.id, u.node,
                    f"module-global cache '{name}' is read-guarded and "
                    f"inserted into but never evicted, swapped, or "
                    f"size-capped — an unbounded query-path cache")
