#!/usr/bin/env python
"""Multi-stage join smoke gate (SSB-style dim × fact, embedded cluster).

Drives the whole stage plane end to end over the real TCP data plane:

- BROADCAST join: ``SELECT SUM(...) FROM fact JOIN part ON ...`` with a
  dim-side WHERE and dim+fact GROUP BY must match an independent numpy
  oracle EXACTLY (values per group, not approximately);
- CO-PARTITIONED join: the same query over partition-aligned tables
  stays exact, and the per-segment partition metadata provably lets a
  single-partition server skip disjoint dim sources;
- EXCHANGE over TCP: a stage-1 block published on one server is fetched
  over the XCHG data-plane frame (forced remote path) byte-identically;
- WINDOW functions: ROW_NUMBER + SUM OVER rows satisfy the per-partition
  rank/telescoping invariants and are run-to-run deterministic;
- HLL: DISTINCTCOUNTHLL equals the host HyperLogLog oracle's estimate
  exactly (register-identical sketches ⇒ identical estimates);
- UPSERT freshness: a REALTIME upsert fact table joins against the dim
  table; re-publishing a key with a NEW join key converges the join
  result to the latest-rows oracle — the superseded row never joins.

Artifact mode (the committed JOIN_r12.json): JOIN_SMOKE_ROWS=1000000
JOIN_SMOKE_ARTIFACT=JOIN_r12.json adds a host/device/sharded parity
sweep over the 1M-row fact and records wall times per query class.

Exit code 0 on success, 1 otherwise. Env knobs:
  JOIN_SMOKE_ROWS      fact rows             (default 30000)
  JOIN_SMOKE_DIM_ROWS  dim rows              (default 600)
  JOIN_SMOKE_ARTIFACT  write a JSON artifact (default off)
  JOIN_SMOKE_WINDOW_S  upsert convergence    (default 60)
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

ROWS = int(os.environ.get("JOIN_SMOKE_ROWS", "30000"))
DIM_ROWS = int(os.environ.get("JOIN_SMOKE_DIM_ROWS", "600"))
ARTIFACT = os.environ.get("JOIN_SMOKE_ARTIFACT", "")
WINDOW_S = float(os.environ.get("JOIN_SMOKE_WINDOW_S", "60"))

FACT = "lineorderj"
DIM = "part"


def log(msg):
    print(f"join_smoke: {msg}")


def group_dict(resp, fi=0):
    return {tuple(g["group"]): float(g["value"])
            for g in resp.aggregation_results[fi].group_by_result}


def expect_exact(name, resp, oracle_groups):
    if resp.exceptions:
        print(f"FAIL: {name}: {resp.exceptions}", file=sys.stderr)
        return False
    got = group_dict(resp)
    exp = {k: float(v[0]) for k, v in oracle_groups.items()}
    if got != exp:
        diff = {k: (got.get(k), exp.get(k))
                for k in set(got) | set(exp) if got.get(k) != exp.get(k)}
        print(f"FAIL: {name}: {len(diff)} group(s) differ, e.g. "
              f"{list(diff.items())[:3]}", file=sys.stderr)
        return False
    log(f"{name}: exact over {len(exp)} groups")
    return True


def run_cluster_suite(report):
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import (build_join_table_dirs,
                                         fact_join_schema, join_oracle,
                                         join_table_configs,
                                         part_dim_schema)

    base = tempfile.mkdtemp(prefix="join_smoke_")
    t0 = time.perf_counter()
    fact_dirs, dim_dirs, dim, fact = build_join_table_dirs(
        os.path.join(base, "b"), fact_rows=ROWS, num_fact_segments=4,
        dim_rows=DIM_ROWS, seed=12)
    cp_fact_dirs, cp_dim_dirs, cp_dim, cp_fact = build_join_table_dirs(
        os.path.join(base, "cp"), fact_rows=min(ROWS, 60000),
        num_fact_segments=4, dim_rows=DIM_ROWS, seed=13,
        num_partitions=4)
    report["datagenS"] = round(time.perf_counter() - t0, 2)

    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2,
                              tcp=True)
    ok = True
    try:
        cluster.add_schema(fact_join_schema())
        cluster.add_schema(part_dim_schema())
        fc, dc = join_table_configs()
        cluster.add_table(fc)
        cluster.add_table(dc)
        for d in fact_dirs:
            cluster.upload_segment(f"{FACT}_OFFLINE", d)
        for d in dim_dirs:
            cluster.upload_segment(f"{DIM}_OFFLINE", d)

        # -- broadcast join, dim WHERE + dim/fact GROUP BY ----------------
        q = (f"SELECT SUM({FACT}.lo_revenue) FROM {FACT} JOIN {DIM} "
             f"ON {FACT}.lo_partkey = {DIM}.p_partkey "
             f"WHERE {DIM}.p_mfgr = 'MFGR#2' AND {FACT}.lo_quantity < 30 "
             f"GROUP BY {DIM}.p_brand1, {FACT}.d_year TOP 100000")
        t = time.perf_counter()
        resp = cluster.query(q)
        report["broadcastJoinMs"] = round(
            (time.perf_counter() - t) * 1e3, 1)
        fq = fact["lo_quantity"] < 30
        o = join_oracle(dim, {k: (v[fq] if isinstance(v, np.ndarray)
                                  else v) for k, v in fact.items()},
                        dim_filter=lambda d: d["p_mfgr"] == "MFGR#2",
                        group_cols=["part.p_brand1", "f.d_year"])
        exp = {(k[0], int(k[1])): v for k, v in o["groups"].items()}
        ok &= expect_exact("broadcast join", resp,
                           {k: v for k, v in exp.items()})
        report["broadcastJoinGroups"] = len(exp)

        # -- forced-TCP exchange fetch ------------------------------------
        servers = sorted(cluster.servers)
        s0 = cluster.servers[servers[0]]
        s0.exchange.put("smoke.x", b"\x00\x01payload\x7f" * 100)
        from pinot_tpu.query.stages import exchange as xmod
        host, port = cluster.transport.endpoints[servers[0]]
        import asyncio
        from pinot_tpu.transport.tcp import ServerConnection
        loop = asyncio.new_event_loop()
        try:
            conn = ServerConnection(host, port)
            raw = loop.run_until_complete(
                conn.request(xmod.fetch_frame("smoke.x"), 5.0))
            loop.run_until_complete(conn.close())
        finally:
            loop.close()
        if bytes(raw) != b"\x00\x01payload\x7f" * 100:
            print("FAIL: TCP exchange fetch not byte-identical",
                  file=sys.stderr)
            ok = False
        else:
            log("exchange: stage-1 block fetched over the TCP data "
                "plane byte-identically")

        # -- window functions (SUM OVER the bounded metric: the int32
        # running-sum contract — lo_revenue at 1M-row scale would
        # rightly be rejected by the overflow guard) -----------------------
        qw = (f"SELECT d_year, lo_quantity, ROW_NUMBER() OVER "
              f"(PARTITION BY d_year ORDER BY lo_revenue DESC), "
              f"SUM(lo_quantity) OVER (PARTITION BY d_year ORDER BY "
              f"lo_revenue DESC) FROM {FACT} WHERE lo_quantity = 1 "
              f"LIMIT 65536")
        t = time.perf_counter()
        r1 = cluster.query(qw)
        report["windowMs"] = round((time.perf_counter() - t) * 1e3, 1)
        r2 = cluster.query(qw)
        if r1.exceptions or r1.selection_results is None or \
                not r1.selection_results.results:
            print(f"FAIL: window query: {r1.exceptions}", file=sys.stderr)
            ok = False
        elif r1.selection_results.results != r2.selection_results.results:
            print("FAIL: window query not deterministic", file=sys.stderr)
            ok = False
        else:
            rows = r1.selection_results.results
            seen = {}
            w_ok = True
            for year, qty, rn, run in rows:
                prev = seen.get(year)
                if prev is None:
                    w_ok &= rn == 1 and run == qty
                else:
                    w_ok &= (rn == prev[0] + 1 and run == prev[1] + qty)
                seen[year] = (rn, run)
            n_scan = int((fact["lo_quantity"] == 1).sum())
            w_ok &= sum(s[0] for s in seen.values()) == n_scan
            if not w_ok:
                print("FAIL: window invariants violated", file=sys.stderr)
                ok = False
            else:
                log(f"window: {len(rows)} rows (of {n_scan} scanned), "
                    "rank/telescoping invariants hold, deterministic")
            report["windowRows"] = n_scan

        # -- HLL ----------------------------------------------------------
        from pinot_tpu.common.sketches import HyperLogLog
        t = time.perf_counter()
        rh = cluster.query(
            f"SELECT DISTINCTCOUNTHLL(lo_partkey) FROM {FACT}")
        report["hllMs"] = round((time.perf_counter() - t) * 1e3, 1)
        oracle_est = int(round(HyperLogLog.from_values(
            np.unique(fact["lo_partkey"])).cardinality()))
        got_est = int(float(rh.aggregation_results[0].value))
        if rh.exceptions or got_est != oracle_est:
            print(f"FAIL: HLL estimate {got_est} != oracle {oracle_est} "
                  f"(register-identity broken) {rh.exceptions}",
                  file=sys.stderr)
            ok = False
        else:
            log(f"HLL: estimate {got_est} == host-sketch oracle "
                f"(true distinct {len(np.unique(fact['lo_partkey']))})")
        report["hllEstimate"] = got_est

        # -- co-partitioned join ------------------------------------------
        cluster2 = EmbeddedCluster(os.path.join(base, "c2"),
                                   num_servers=2, tcp=True)
        try:
            cluster2.add_schema(fact_join_schema())
            cluster2.add_schema(part_dim_schema())
            fc2, dc2 = join_table_configs(num_partitions=4)
            cluster2.add_table(fc2)
            cluster2.add_table(dc2)
            for d in cp_fact_dirs:
                cluster2.upload_segment(f"{FACT}_OFFLINE", d)
            for d in cp_dim_dirs:
                cluster2.upload_segment(f"{DIM}_OFFLINE", d)
            t = time.perf_counter()
            rc = cluster2.query(
                f"SELECT SUM({FACT}.lo_revenue) FROM {FACT} JOIN {DIM} "
                f"ON {FACT}.lo_partkey = {DIM}.p_partkey "
                f"GROUP BY {DIM}.p_mfgr TOP 100")
            report["copartJoinMs"] = round(
                (time.perf_counter() - t) * 1e3, 1)
            oc = join_oracle(cp_dim, cp_fact,
                             group_cols=["part.p_mfgr"])
            ok &= expect_exact(
                "co-partitioned join", rc,
                {(k[0],): v for k, v in oc["groups"].items()})
            # partition metadata is discriminating per segment
            from pinot_tpu.query.stages.join import (fact_partition_info,
                                                     filter_sources)
            from pinot_tpu.segment.loader import ImmutableSegmentLoader
            seg0 = ImmutableSegmentLoader.load(cp_fact_dirs[0])
            fp = fact_partition_info([seg0], "lo_partkey")
            sources = [{"server": "s", "id": f"x{p}", "partitions": [p],
                        "partitionFunction": "Modulo",
                        "numPartitions": 4} for p in range(4)]
            _kept, skipped = filter_sources(sources, fp)
            if fp is None or skipped != 4 - len(fp[2]):
                print("FAIL: co-partitioned source filtering inert",
                      file=sys.stderr)
                ok = False
            else:
                log(f"co-partitioned dispatch: single-partition server "
                    f"skips {skipped}/4 dim sources")
            report["copartSkippedSources"] = skipped
        finally:
            cluster2.stop()
    finally:
        cluster.stop()
    return ok


def run_upsert_suite(report):
    """REALTIME upsert fact table joining an OFFLINE dim table: the
    join must track the LATEST row per key — a mid-run upsert moving a
    key to a different dim category converges, the superseded row never
    joins again."""
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.schema import (Schema, TimeUnit, dimension,
                                         metric, time_field)
    from pinot_tpu.common.table_config import (IndexingConfig,
                                               SegmentsConfig, TableConfig,
                                               TableType, UpsertConfig)
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import (join_table_configs, make_join_rows,
                                         part_dim_schema)
    from pinot_tpu.segment.creator import SegmentCreator

    topic = "join_smoke_topic"
    rt = "ordersrt"
    keys = 120
    rows_n = 400
    dim, _fact = make_join_rows(10, dim_rows=200, seed=21)
    schema = Schema(rt, [
        dimension("okey", DataType.STRING),
        dimension("lo_partkey", DataType.INT),
        metric("lo_revenue", DataType.LONG),
        time_field("ts", DataType.INT, TimeUnit.DAYS),
    ])
    stream = MemoryStream(topic, num_partitions=1)
    registry.register_stream_factory(
        f"mem_{topic}", MemoryStreamConsumerFactory(stream, batch_size=50))
    cfg = TableConfig(
        rt, table_type=TableType.REALTIME,
        indexing_config=IndexingConfig(stream_configs={
            "stream.factory.name": f"mem_{topic}",
            "stream.topic.name": topic,
            "realtime.segment.flush.threshold.size": "1000000",
            "realtime.segment.flush.threshold.time.ms": "600000000",
        }),
        segments_config=SegmentsConfig(replication=1,
                                       time_column_name="ts"))
    cfg.upsert_config = UpsertConfig(mode="FULL",
                                     primary_key_columns=["okey"])

    rng = np.random.default_rng(31)
    dim_keys = dim["p_partkey"].astype(np.int64)
    rows = []
    for i in range(rows_n):
        rows.append({"okey": f"o{i % keys}",
                     "lo_partkey": int(dim_keys[rng.integers(
                         0, len(dim_keys))]),
                     "lo_revenue": int(rng.integers(100, 10_000) * 100),
                     "ts": 1 + (i % 30)})

    def latest(rs):
        by = {}
        for r in rs:
            by[r["okey"]] = r
        return list(by.values())

    def oracle(rs, mfgr):
        order = np.argsort(dim_keys, kind="stable")
        skeys = dim_keys[order]
        total = cnt = 0
        for r in latest(rs):
            p = int(np.searchsorted(skeys, r["lo_partkey"]))
            if p < len(skeys) and skeys[p] == r["lo_partkey"]:
                if dim["p_mfgr"][order[p]] == mfgr:
                    total += r["lo_revenue"]
                    cnt += 1
        return total, cnt

    base = tempfile.mkdtemp(prefix="join_smoke_rt_")
    ddir = os.path.join(base, "d0")
    _fc, dc = join_table_configs()
    SegmentCreator(part_dim_schema(), dc,
                   segment_name="partd_0").build(dim, ddir)
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=1)
    ok = False
    try:
        cluster.add_schema(schema)
        cluster.add_schema(part_dim_schema())
        cluster.add_table(dc)
        cluster.upload_segment(f"{DIM}_OFFLINE", ddir)
        cluster.add_table(cfg)
        for r in rows:
            stream.publish(r, partition=0)

        q = (f"SELECT SUM({rt}.lo_revenue), COUNT(*) FROM {rt} "
             f"JOIN {DIM} ON {rt}.lo_partkey = {DIM}.p_partkey "
             f"WHERE {DIM}.p_mfgr = 'MFGR#1'")

        def result():
            resp = cluster.query(q)
            if resp.exceptions:
                return None
            return (int(float(resp.aggregation_results[0].value or 0)),
                    int(float(resp.aggregation_results[1].value)))

        deadline = time.monotonic() + WINDOW_S
        exp = oracle(rows, "MFGR#1")
        while time.monotonic() < deadline and result() != exp:
            time.sleep(0.1)
        if result() != exp:
            print(f"FAIL: upsert join initial parity: {result()} != "
                  f"{exp}", file=sys.stderr)
            return False
        log(f"upsert join: initial SUM/COUNT match latest-rows oracle "
            f"{exp}")

        # move one joined key to a DIFFERENT manufacturer's part: the
        # old row's contribution must vanish, the new one appear
        m1 = dim["p_mfgr"] == "MFGR#1"
        m3 = dim["p_mfgr"] == "MFGR#3"
        new_row = {"okey": "o7",
                   "lo_partkey": int(dim_keys[np.nonzero(m3)[0][0]]),
                   "lo_revenue": 123_400, "ts": 31}
        rows.append(new_row)
        stream.publish(new_row, partition=0)
        exp2 = oracle(rows, "MFGR#1")
        deadline = time.monotonic() + WINDOW_S
        while time.monotonic() < deadline and result() != exp2:
            time.sleep(0.1)
        if result() != exp2:
            print(f"FAIL: upsert join freshness: {result()} != {exp2}",
                  file=sys.stderr)
            return False
        exp3 = oracle(rows, "MFGR#3")
        r3 = cluster.query(
            f"SELECT SUM({rt}.lo_revenue), COUNT(*) FROM {rt} "
            f"JOIN {DIM} ON {rt}.lo_partkey = {DIM}.p_partkey "
            f"WHERE {DIM}.p_mfgr = 'MFGR#3'")
        got3 = (int(float(r3.aggregation_results[0].value or 0)),
                int(float(r3.aggregation_results[1].value)))
        if got3 != exp3:
            print(f"FAIL: upserted row not joined on new side: {got3} "
                  f"!= {exp3}", file=sys.stderr)
            return False
        log("upsert join: mid-run upsert moved key o7 between dim "
            "categories — superseded row never joins, new row joins on "
            "the next converged query")
        report["upsertJoin"] = {"initial": list(exp), "after": list(exp2),
                                "movedTo": list(exp3)}
        ok = True
    finally:
        cluster.stop()
    return ok


def run_parity_sweep(report):
    """Host/device/sharded bit-parity over the generated fact (the
    artifact's oracle-parity suite; also run at smoke scale)."""
    import copy
    from pinot_tpu.parallel.sharded import ShardedQueryExecutor, make_mesh
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.reduce import BrokerReduceService
    from pinot_tpu.query.stages import join as jmod
    from pinot_tpu.query.stages import window as wmod
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    from pinot_tpu.tools.datagen import build_join_table_dirs

    base = tempfile.mkdtemp(prefix="join_parity_")
    t0 = time.perf_counter()
    fact_dirs, _dim_dirs, dim, fact = build_join_table_dirs(
        os.path.join(base, "b"), fact_rows=ROWS, num_fact_segments=4,
        dim_rows=DIM_ROWS, seed=12)
    segs = [ImmutableSegmentLoader.load(d) for d in fact_dirs]
    report["paritySetupS"] = round(time.perf_counter() - t0, 2)
    red = BrokerReduceService()

    request = compile_pql(
        f"SELECT SUM({FACT}.lo_revenue), COUNT(*) FROM {FACT} JOIN "
        f"{DIM} ON {FACT}.lo_partkey = {DIM}.p_partkey "
        f"WHERE {DIM}.p_category = 'MFGR#23' "
        f"GROUP BY {DIM}.p_brand1 TOP 100000")
    dmask = dim["p_category"] == "MFGR#23"
    ctx = jmod.JoinContext(
        request.join, dim["p_partkey"][dmask].astype(np.int64),
        {c: dim[c][dmask] for c in request.join.dim_columns})
    req = copy.copy(request)
    req._join_ctx = ctx

    def gd(resp, fi):
        return {tuple(g["group"]): g["value"] for g in
                resp.to_json()["aggregationResults"][fi]["groupByResult"]}

    times = {}
    outs = {}
    for name, ex in [("host", ServerQueryExecutor(use_device=False)),
                     ("device", ServerQueryExecutor(use_device=True)),
                     ("sharded", ShardedQueryExecutor(mesh=make_mesh()))]:
        t = time.perf_counter()
        outs[name] = red.reduce(request, [ex.execute(req, segs)])
        times[name] = round((time.perf_counter() - t) * 1e3, 1)
    join_parity = all(
        gd(outs["host"], fi) == gd(outs["device"], fi) ==
        gd(outs["sharded"], fi) for fi in range(2))
    report["joinParity"] = {"bitIdentical": join_parity, "ms": times}
    if not join_parity:
        print("FAIL: join host/device/sharded parity", file=sys.stderr)
        return False
    log(f"parity: join host/device/sharded bit-identical over "
        f"{len(gd(outs['host'], 0))} groups "
        f"(host {times['host']}ms, device {times['device']}ms, "
        f"sharded {times['sharded']}ms)")

    # window host-vs-device bit parity on the scan input
    wreq = compile_pql(
        "SELECT d_year, lo_revenue, ROW_NUMBER() OVER (PARTITION BY "
        "d_year ORDER BY lo_revenue), SUM(lo_quantity) OVER "
        "(PARTITION BY d_year ORDER BY lo_revenue) FROM t LIMIT 100000")
    sel = fact["lo_quantity"] <= 2
    cols = {c: fact[c][sel] for c in
            ("d_year", "lo_revenue", "lo_quantity")}
    n = int(sel.sum())
    t = time.perf_counter()
    dev = wmod.execute_window(wreq, dict(cols), n, use_device=True)
    t_dev = round((time.perf_counter() - t) * 1e3, 1)
    host = wmod.execute_window(wreq, dict(cols), n, use_device=False)
    win_parity = all(np.array_equal(np.asarray(a), np.asarray(b))
                     for a, b in zip(dev.selection_cols,
                                     host.selection_cols))
    report["windowParity"] = {"bitIdentical": win_parity, "rows": n,
                              "deviceMs": t_dev}
    if not win_parity:
        print("FAIL: window host/device parity", file=sys.stderr)
        return False
    log(f"parity: window host/device bit-identical over {n} rows "
        f"({t_dev}ms device)")

    # HLL registers host/device/sharded identical
    from pinot_tpu.engine import QueryEngine
    hq = f"SELECT DISTINCTCOUNTHLL(lo_partkey) FROM {FACT}"
    t = time.perf_counter()
    vals = [QueryEngine(segs, use_device=True).query(hq),
            QueryEngine(segs, use_device=False).query(hq),
            QueryEngine(segs, use_device=True,
                        mesh=make_mesh()).query(hq)]
    t_hll = round((time.perf_counter() - t) * 1e3, 1)
    ests = [v.aggregation_results[0].value for v in vals]
    hll_parity = len(set(ests)) == 1
    report["hllParity"] = {"registerIdentical": hll_parity,
                           "estimate": ests[0], "sweepMs": t_hll}
    if not hll_parity:
        print(f"FAIL: HLL parity {ests}", file=sys.stderr)
        return False
    log(f"parity: HLL device/host/sharded estimates identical "
        f"({ests[0]})")
    return True


def main() -> int:
    report = {"artifact": "JOIN_r12", "rows": ROWS, "dimRows": DIM_ROWS,
              "backend": os.environ.get("JAX_PLATFORMS", "cpu")}
    ok = run_parity_sweep(report)
    ok = run_cluster_suite(report) and ok
    ok = run_upsert_suite(report) and ok
    report["pass"] = bool(ok)
    if ARTIFACT:
        with open(ARTIFACT, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
        log(f"wrote {ARTIFACT}")
    print("join_smoke: " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
