"""Python client: broker connection + result sets + controller admin.

Parity: pinot-api (org.apache.pinot.client) — Connection.java (execute via
a BrokerSelector over the broker list), ResultSetGroup.java,
AggregationResultSet / GroupByResultSet / SelectionResultSet, and
PinotClientException. The admin half mirrors what the reference's
quickstarts drive against the controller REST API (schema/table create,
segment upload).
"""
from __future__ import annotations

import http.client
import itertools
import json
import random
import urllib.parse
from typing import Dict, List, Optional, Sequence, Tuple


class PinotClientError(Exception):
    pass


class ResultSet:
    """One result table: aggregation value, group-by rows, or selection."""

    def __init__(self, column_names: List[str], rows: List[list],
                 group_key_columns: Optional[List[str]] = None,
                 group_keys: Optional[List[list]] = None):
        self._columns = column_names
        self._rows = rows
        self._group_key_columns = group_key_columns or []
        self._group_keys = group_keys or []

    @property
    def row_count(self) -> int:
        return len(self._rows)

    @property
    def column_count(self) -> int:
        return len(self._columns)

    def column_name(self, i: int) -> str:
        return self._columns[i]

    def get(self, row: int, col: int = 0):
        return self._rows[row][col]

    @property
    def group_key_columns(self) -> List[str]:
        return list(self._group_key_columns)

    def group_key(self, row: int) -> list:
        return self._group_keys[row]

    def rows(self) -> List[list]:
        return [list(r) for r in self._rows]


class ResultSetGroup:
    """All result tables of one query + the response stats."""

    def __init__(self, response: dict):
        self.response = response
        self.exceptions = response.get("exceptions", [])
        self._sets: List[ResultSet] = []
        for agg in response.get("aggregationResults", []):
            if "groupByResult" in agg:
                self._sets.append(ResultSet(
                    column_names=[agg["function"]],
                    rows=[[g["value"]] for g in agg["groupByResult"]],
                    group_key_columns=agg.get("groupByColumns", []),
                    group_keys=[g["group"] for g in agg["groupByResult"]]))
            else:
                self._sets.append(ResultSet(
                    column_names=[agg["function"]],
                    rows=[[agg["value"]]]))
        sel = response.get("selectionResults")
        if sel is not None:
            self._sets.append(ResultSet(column_names=sel["columns"],
                                        rows=sel["results"]))

    @property
    def result_set_count(self) -> int:
        return len(self._sets)

    def result_set(self, i: int = 0) -> ResultSet:
        return self._sets[i]

    @property
    def num_docs_scanned(self) -> int:
        return self.response.get("numDocsScanned", 0)

    @property
    def time_used_ms(self) -> float:
        return self.response.get("timeUsedMs", 0.0)

    @property
    def trace_info(self) -> Optional[dict]:
        return self.response.get("traceInfo")


class _HttpEndpoint:
    """One host:port with persistent keep-alive connections.

    Connections are PER-THREAD (thread-local): `http.client` connections
    are not thread-safe, and a single shared socket would serialize
    every concurrent caller — at high offered rates the client itself
    became the bottleneck, so a perf driver's `missed_slots` measured
    client serialization, not server saturation. Each worker thread now
    keeps its own keep-alive socket (TCP_NODELAY set, so the two-write
    request never hits Nagle + delayed-ACK stalls)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0,
                 tls_config=None):
        import threading
        import weakref
        self.host, self.port, self.timeout = host, port, timeout
        # TlsConfig → https with the configured CA/verification
        # (parity: the reference client's ClientSSLContextGenerator)
        self._ssl_ctx = tls_config.client_context() \
            if tls_config is not None else None
        self._local = threading.local()
        self._lock = threading.Lock()
        # WEAK set: each live connection is strongly held only by its
        # owning thread's local slot, so a dying worker thread releases
        # its socket to GC instead of pinning it here forever (close()
        # still reaches every connection that is actually alive)
        self._all_conns = weakref.WeakSet()

    def _connect(self) -> http.client.HTTPConnection:
        import socket
        if self._ssl_ctx is not None:
            conn = http.client.HTTPSConnection(
                self.host, self.port, timeout=self.timeout,
                context=self._ssl_ctx)
        else:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        conn.connect()
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
        except OSError:
            pass
        with self._lock:
            self._all_conns.add(conn)
        return conn

    def _drop(self, conn) -> None:
        try:
            conn.close()
        finally:
            with self._lock:
                self._all_conns.discard(conn)
            self._local.conn = None

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                idempotent: Optional[bool] = None) -> Tuple[int, bytes]:
        """One retry on a stale kept-alive connection — but only for
        requests that are safe to re-send (the server may already have
        processed a POST whose response was lost)."""
        headers = dict(headers or {})
        if idempotent is None:
            idempotent = method in ("GET", "HEAD", "PUT", "DELETE")
        for attempt in (0, 1):
            conn = getattr(self._local, "conn", None)
            if conn is None:
                conn = self._local.conn = self._connect()
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                self._drop(conn)
                if attempt or not idempotent:
                    raise
        raise PinotClientError("unreachable")  # pragma: no cover

    def close(self) -> None:
        with self._lock:
            conns = list(self._all_conns)
            self._all_conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._local.conn = None


class SimpleBrokerSelector:
    """Round-robin over the broker list (parity: SimpleBrokerSelector)."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]],
                 tls_config=None):
        if not endpoints:
            raise PinotClientError("empty broker list")
        shuffled = list(endpoints)
        random.shuffle(shuffled)
        self._endpoints = [_HttpEndpoint(h, p, tls_config=tls_config)
                           for h, p in shuffled]
        self._cycle = itertools.cycle(range(len(self._endpoints)))

    def select(self, table: Optional[str] = None) -> _HttpEndpoint:
        return self._endpoints[next(self._cycle)]

    def close(self) -> None:
        for e in self._endpoints:
            e.close()


class Connection:
    """Queries one Pinot cluster through its broker(s)."""

    def __init__(self, selector: SimpleBrokerSelector,
                 token: Optional[str] = None):
        self._selector = selector
        self._token = token

    def prepare(self, pql: str) -> "PreparedStatement":
        """`?`-placeholder statement (parity: Connection.prepareStatement)."""
        return PreparedStatement(self, pql)

    def execute(self, pql: str, trace: bool = False) -> ResultSetGroup:
        body = json.dumps({"pql": pql, "trace": trace}).encode("utf-8")
        headers = {"Content-Type": "application/json"}
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        endpoint = self._selector.select(table_of(pql))
        try:
            # queries are read-only: safe to retry on a stale connection
            status, payload = endpoint.request("POST", "/query", body,
                                               headers, idempotent=True)
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            raise PinotClientError(f"broker unreachable: {e}") from e
        if status != 200:
            raise PinotClientError(f"broker returned HTTP {status}: "
                                   f"{payload[:200]!r}")
        group = ResultSetGroup(json.loads(payload))
        for exc in group.exceptions:
            msg = exc.get("message", "")
            if "AccessDenied" in msg:
                raise PinotClientError(msg)
        return group

    def close(self) -> None:
        self._selector.close()


def connect(brokers, token: Optional[str] = None,
            tls_config=None) -> Connection:
    """connect("host:port") / connect([("h", p), ...]) → Connection.
    `tls_config`: a common.tls.TlsConfig — the brokers serve https."""
    if isinstance(brokers, str):
        brokers = [brokers]
    endpoints = []
    for b in brokers:
        if isinstance(b, str):
            host, _, port = b.partition(":")
            endpoints.append((host, int(port)))
        else:
            endpoints.append(tuple(b))
    return Connection(SimpleBrokerSelector(endpoints,
                                           tls_config=tls_config),
                      token=token)


def connect_dynamic(store_host: str, store_port: int,
                    token: Optional[str] = None,
                    tls_config=None) -> Connection:
    """Connection that discovers brokers from the cluster's property
    store and follows membership changes (parity: ConnectionFactory
    .fromZookeeper → DynamicBrokerSelector)."""
    return Connection(DynamicBrokerSelector(store_host, store_port,
                                            tls_config=tls_config),
                      token=token)


class ControllerClient:
    """Admin client for the controller REST API."""

    def __init__(self, host: str, port: int, tls_config=None):
        self._endpoint = _HttpEndpoint(host, port, tls_config=tls_config)

    def _json(self, method: str, path: str, body: Optional[bytes] = None,
              content_type: str = "application/json",
              idempotent: Optional[bool] = None) -> dict:
        status, payload = self._endpoint.request(
            method, path, body,
            {"Content-Type": content_type} if body else None,
            idempotent=idempotent)
        data = json.loads(payload) if payload else {}
        if status >= 400:
            raise PinotClientError(
                f"HTTP {status}: {data.get('error', payload[:200])}")
        return data

    def add_schema(self, schema_json: dict) -> dict:
        # schema/table adds are store upserts: retry-safe
        return self._json("POST", "/schemas",
                          json.dumps(schema_json).encode(), idempotent=True)

    def get_schema(self, name: str) -> dict:
        return self._json("GET", f"/schemas/{urllib.parse.quote(name)}")

    def add_table(self, config_json: dict) -> dict:
        return self._json("POST", "/tables",
                          json.dumps(config_json).encode())

    def list_tables(self) -> List[str]:
        return self._json("GET", "/tables")["tables"]

    def get_table(self, name: str) -> dict:
        return self._json("GET", f"/tables/{urllib.parse.quote(name)}")

    def delete_table(self, name: str) -> dict:
        return self._json("DELETE", f"/tables/{urllib.parse.quote(name)}")

    def external_view(self, table: str) -> dict:
        return self._json(
            "GET", f"/tables/{urllib.parse.quote(table)}/externalview")

    def rebalance(self, table: str, dry_run: bool = False) -> dict:
        return self._json(
            "POST", f"/tables/{urllib.parse.quote(table)}/rebalance"
            f"?dryRun={'true' if dry_run else 'false'}")

    def list_segments(self, table: str) -> List[str]:
        return self._json(
            "GET", f"/tables/{urllib.parse.quote(table)}/segments")

    def upload_segment_dir(self, table: str, segment_dir: str) -> dict:
        from pinot_tpu.controller.http_api import pack_segment_dir
        data = pack_segment_dir(segment_dir)
        return self._json(
            "POST", f"/segments/{urllib.parse.quote(table)}", data,
            content_type="application/gzip", idempotent=False)

    def delete_segment(self, table: str, segment: str) -> dict:
        return self._json(
            "DELETE", f"/segments/{urllib.parse.quote(table)}/"
            f"{urllib.parse.quote(segment)}")

    def segment_metadata(self, table: str, segment: str) -> dict:
        return self._json(
            "GET", f"/segments/{urllib.parse.quote(table)}/"
            f"{urllib.parse.quote(segment)}/metadata")

    def close(self) -> None:
        self._endpoint.close()


# ---------------------------------------------------------------------------
# Dynamic broker selection + prepared statements
# ---------------------------------------------------------------------------

import re as _re
import threading as _threading

_FROM_RE = _re.compile(r"\bFROM\s+([A-Za-z_][A-Za-z0-9_.]*)", _re.IGNORECASE)


def table_of(pql: str) -> Optional[str]:
    """Raw table name a query addresses (parity: the reference client's
    query→table extraction feeding BrokerSelector.selectBroker)."""
    m = _FROM_RE.search(pql)
    return m.group(1) if m else None


class DynamicBrokerSelector:
    """Property-store-watching broker selector.

    Parity: DynamicBrokerSelector.java:41 — the reference client watches
    the ZK external view of the broker resource to learn, per table,
    which brokers are live; here the same contract runs over the
    cluster's property store (controller/store_client.py is the ZK
    client analogue): live-instance records carry broker host:port +
    tenant tags, /BROKERRESOURCE/<table> carries the table→broker
    mapping, and both are watched, so broker restarts/kills never
    require client reconfiguration.
    """

    LIVE = "/LIVEINSTANCES"
    BROKER_RESOURCE = "/BROKERRESOURCE"

    def __init__(self, store_host: str, store_port: int,
                 tls_config=None):
        from pinot_tpu.controller.store_client import RemotePropertyStore
        self._tls_config = tls_config
        self._store = RemotePropertyStore(store_host, store_port)
        self._lock = _threading.Lock()
        self._brokers: Dict[str, Tuple[str, int]] = {}   # inst -> endpoint
        self._tables: Dict[str, List[str]] = {}          # table -> insts
        self._endpoints: Dict[Tuple[str, int], _HttpEndpoint] = {}
        self._rng = random.Random()
        self._watcher = self._on_change
        self._store.watch(self.LIVE + "/", self._watcher)
        self._store.watch(self.BROKER_RESOURCE + "/", self._watcher)
        for inst in self._store.children(self.LIVE):
            self._on_change(f"{self.LIVE}/{inst}",
                            self._store.get(f"{self.LIVE}/{inst}"))
        for table in self._store.children(self.BROKER_RESOURCE):
            self._on_change(f"{self.BROKER_RESOURCE}/{table}",
                            self._store.get(
                                f"{self.BROKER_RESOURCE}/{table}"))

    def _on_change(self, path: str, record: Optional[dict]) -> None:
        with self._lock:
            if path.startswith(self.LIVE + "/"):
                inst = path[len(self.LIVE) + 1:]
                # explicit _BROKER tags only: broker processes always
                # self-register with the suffix; a server's bare legacy
                # tag must not make its QUERY port look like a broker
                is_broker = record is not None and any(
                    t.endswith("_BROKER")
                    for t in record.get("tags", []))
                if record is None or "host" not in record or \
                        not is_broker:
                    gone = self._brokers.pop(inst, None)
                    # evict the endpoint (and its keep-alive socket)
                    # unless another live broker shares the address
                    if gone is not None and gone not in \
                            self._brokers.values():
                        ep = self._endpoints.pop(gone, None)
                        if ep is not None:
                            ep.close()
                else:
                    self._brokers[inst] = (record["host"],
                                           int(record["port"]))
            else:
                table = path[len(self.BROKER_RESOURCE) + 1:]
                if record is None:
                    self._tables.pop(table, None)
                else:
                    self._tables[table] = list(record.get("instances", []))

    def _endpoint(self, addr: Tuple[str, int]) -> _HttpEndpoint:
        ep = self._endpoints.get(addr)
        if ep is None:
            ep = self._endpoints[addr] = _HttpEndpoint(
                *addr, tls_config=self._tls_config)
        return ep

    def select(self, table: Optional[str] = None) -> _HttpEndpoint:
        with self._lock:
            candidates: List[Tuple[str, int]] = []
            if table is not None:
                insts: List[str] = []
                for t in (table, f"{table}_OFFLINE", f"{table}_REALTIME"):
                    insts.extend(self._tables.get(t, ()))
                candidates = [self._brokers[i] for i in insts
                              if i in self._brokers]
            if not candidates:
                candidates = list(self._brokers.values())
            if not candidates:
                raise PinotClientError("no live brokers in the cluster")
            return self._endpoint(self._rng.choice(candidates))

    def live_brokers(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return dict(self._brokers)

    def close(self) -> None:
        for ep in self._endpoints.values():
            ep.close()
        self._store.close()


class PreparedStatement:
    """`?`-placeholder statement with value escaping.

    Parity: PreparedStatement.java:27 — the reference fills placeholders
    client-side with single-quote escaping before sending the final PQL.
    """

    def __init__(self, connection: "Connection", pql: str):
        self._connection = connection
        self._template = pql.split("?")
        self._values: List[Optional[str]] = \
            [None] * (len(self._template) - 1)

    def _set(self, i: int, literal: str) -> "PreparedStatement":
        if not 0 <= i < len(self._values):
            raise PinotClientError(
                f"placeholder index {i} out of range "
                f"(statement has {len(self._values)})")
        self._values[i] = literal
        return self

    def set_string(self, i: int, value: str) -> "PreparedStatement":
        escaped = str(value).replace("'", "''")
        return self._set(i, f"'{escaped}'")

    def set_int(self, i: int, value: int) -> "PreparedStatement":
        return self._set(i, str(int(value)))

    def set_long(self, i: int, value: int) -> "PreparedStatement":
        return self._set(i, str(int(value)))

    def set_float(self, i: int, value: float) -> "PreparedStatement":
        return self._set(i, repr(float(value)))

    def set_double(self, i: int, value: float) -> "PreparedStatement":
        return self._set(i, repr(float(value)))

    def fill(self) -> str:
        if any(v is None for v in self._values):
            missing = [i for i, v in enumerate(self._values) if v is None]
            raise PinotClientError(f"unset placeholders: {missing}")
        out = []
        for i, part in enumerate(self._template):
            out.append(part)
            if i < len(self._values):
                out.append(self._values[i])
        return "".join(out)

    def execute(self, trace: bool = False) -> ResultSetGroup:
        return self._connection.execute(self.fill(), trace=trace)
