"""Segment metadata model.

Parity: pinot-core/.../segment/index/SegmentMetadataImpl.java +
metadata.properties — total docs, time range, per-column cardinality /
bits-per-element / sorted flag / min-max / index presence / partitions.
Stored as JSON instead of java properties.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment import format as fmt


@dataclasses.dataclass
class ColumnMetadata:
    name: str
    data_type: DataType
    cardinality: int
    bits_per_element: int
    single_value: bool = True
    sorted: bool = False
    has_dictionary: bool = True
    has_inverted_index: bool = False
    has_bloom_filter: bool = False
    min_value: Optional[object] = None
    max_value: Optional[object] = None
    max_number_of_multi_values: int = 0
    total_number_of_entries: int = 0
    partition_function: Optional[str] = None
    num_partitions: int = 0
    partitions: List[int] = dataclasses.field(default_factory=list)
    default_null_value: Optional[object] = None
    # derived-metric columns (parity: MetricFieldSpec.DerivedMetricType —
    # e.g. an HLL column holding per-row serialized sketches of
    # `derived_from`, targeted by the FASTHLL broker-request rewrite)
    derived_metric_type: Optional[str] = None
    derived_from: Optional[str] = None
    # VECTOR columns: fixed embedding dimension of the packed [n, dim]
    # float32 forward block
    vector_dimension: int = 0

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["data_type"] = self.data_type.value
        if isinstance(self.min_value, bytes):
            d["min_value"] = self.min_value.hex()
            d["max_value"] = self.max_value.hex()
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ColumnMetadata":
        d = dict(d)
        d["data_type"] = DataType(d["data_type"])
        obj = cls(**d)
        if obj.data_type == DataType.BYTES and isinstance(obj.min_value, str):
            obj.min_value = bytes.fromhex(obj.min_value)
            obj.max_value = bytes.fromhex(obj.max_value)
        return obj


@dataclasses.dataclass
class SegmentMetadata:
    segment_name: str
    table_name: str
    total_docs: int
    columns: Dict[str, ColumnMetadata]
    time_column: Optional[str] = None
    time_unit: Optional[str] = None
    start_time: Optional[int] = None
    end_time: Optional[int] = None
    segment_version: str = fmt.SEGMENT_VERSION
    creation_time_ms: int = 0
    crc: Optional[str] = None
    custom: Dict[str, str] = dataclasses.field(default_factory=dict)

    def column(self, name: str) -> ColumnMetadata:
        return self.columns[name]

    def get_derived_column(self, origin: str,
                           metric_type: str = "HLL") -> Optional[str]:
        """Derived-column lookup (parity: SegmentMetadataImpl
        .getDerivedColumn — the FASTHLL rewrite's metadata source)."""
        for cm in self.columns.values():
            if cm.derived_from == origin and \
                    cm.derived_metric_type == metric_type:
                return cm.name
        return None

    def to_json(self) -> dict:
        return {
            "segmentName": self.segment_name,
            "tableName": self.table_name,
            "totalDocs": self.total_docs,
            "timeColumn": self.time_column,
            "timeUnit": self.time_unit,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "segmentVersion": self.segment_version,
            "creationTimeMs": self.creation_time_ms,
            "crc": self.crc,
            "custom": self.custom,
            "columns": {k: v.to_json() for k, v in self.columns.items()},
        }

    def save(self, seg_dir: str) -> None:
        with open(os.path.join(seg_dir, fmt.METADATA_FILE), "w") as f:
            json.dump(self.to_json(), f, indent=1, default=str)

    @classmethod
    def load(cls, seg_dir) -> "SegmentMetadata":
        d = json.loads(fmt.open_dir(seg_dir).read_text(fmt.METADATA_FILE))
        return cls(
            segment_name=d["segmentName"],
            table_name=d["tableName"],
            total_docs=d["totalDocs"],
            time_column=d.get("timeColumn"),
            time_unit=d.get("timeUnit"),
            start_time=d.get("startTime"),
            end_time=d.get("endTime"),
            segment_version=d.get("segmentVersion", fmt.SEGMENT_VERSION),
            creation_time_ms=d.get("creationTimeMs", 0),
            crc=d.get("crc"),
            custom=d.get("custom", {}),
            columns={k: ColumnMetadata.from_json(v)
                     for k, v in d["columns"].items()},
        )
