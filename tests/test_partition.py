"""Partition functions + partition-aware pruning tests.

Parity targets: core/data/partition/ (Java-compatible hashes — golden
vectors from Kafka's UtilsTest for murmur2 and Java String.hashCode),
PartitionSegmentPruner (server), PartitionZKMetadataPruner (broker
pre-scatter pruning).
"""
import os
import tempfile

import numpy as np
import pytest

from fixtures import make_schema, make_table_config, make_shared_columns

from pinot_tpu.common.partition import (ModuloPartitionFunction,
                                        MurmurPartitionFunction,
                                        java_string_hash,
                                        make_partition_function, murmur2)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.tools.cluster import EmbeddedCluster


def test_murmur2_kafka_golden_vectors():
    # org.apache.kafka.common.utils.UtilsTest#testMurmur2
    assert murmur2(b"21") == -973932308
    assert murmur2(b"foobar") == -790332482
    assert murmur2(b"a-little-bit-long-string") == -985981536
    assert murmur2(b"a-little-bit-longer-string") == -1486304829
    assert murmur2(
        b"lkjh234lh9fiuh90y23oiuhsafujhadof229phr9h19h89h8") == -58897971


def test_java_string_hash_golden():
    assert java_string_hash("") == 0
    assert java_string_hash("abc") == 96354
    assert java_string_hash("hello") == 99162322


def test_partition_function_factory_and_ranges():
    for name in ("Murmur", "HashCode", "ByteArray"):
        fn = make_partition_function(name, 7)
        assert fn.num_partitions == 7
        for v in ("x", "yy", 123, 0):
            assert 0 <= fn.get_partition(v) < 7
    mod = make_partition_function("Modulo", 7)
    for v in (123, 0, "42"):           # Modulo is numeric-only (parity)
        assert -7 < mod.get_partition(v) < 7
    assert ModuloPartitionFunction(4).get_partition(10) == 2
    assert MurmurPartitionFunction(8).get_partition("foobar") == \
        ((-790332482) & 0x7FFFFFFF) % 8
    with pytest.raises(ValueError):
        make_partition_function("nope", 3)


def _partitioned_table_config(num_partitions=4):
    cfg = make_table_config()
    cfg.indexing_config.segment_partition_config = {
        "teamID": {"functionName": "Murmur",
                   "numPartitions": num_partitions}}
    return cfg


def _team_partition(team, n=4):
    return MurmurPartitionFunction(n).get_partition(team)


def test_creator_records_partition_metadata():
    base = tempfile.mkdtemp()
    cols = make_shared_columns(1024, seed=3)
    SegmentCreator(make_schema(), _partitioned_table_config(),
                   segment_name="p0").build(cols, base)
    seg = ImmutableSegmentLoader.load(base)
    cm = seg.data_source("teamID").metadata
    assert cm.partition_function == "Murmur" and cm.num_partitions == 4
    expected = sorted({_team_partition(t) for t in set(cols["teamID"])})
    assert cm.partitions == expected
    # round-trips through metadata save/load
    assert cm.partitions and all(0 <= p < 4 for p in cm.partitions)


def test_partition_segment_pruner():
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.query.pruner import PartitionSegmentPruner
    base = tempfile.mkdtemp()
    # one-team segment: only that team's partition present
    n = 1024
    cols = make_shared_columns(n, seed=5)
    cols["teamID"] = np.array(["BOS"] * n, dtype=object)
    d = os.path.join(base, "s0")
    SegmentCreator(make_schema(), _partitioned_table_config(),
                   segment_name="s0").build(cols, d)
    seg = ImmutableSegmentLoader.load(d)
    pruner = PartitionSegmentPruner()
    same = compile_pql("SELECT COUNT(*) FROM baseballStats "
                       "WHERE teamID = 'BOS'")
    assert pruner.prune(seg, same) is False
    # a team hashing to a DIFFERENT partition must prune
    other = next(t for t in ("NYA", "CHc", "DET", "SFN", "CLE")
                 if _team_partition(t) != _team_partition("BOS"))
    diff = compile_pql("SELECT COUNT(*) FROM baseballStats "
                       f"WHERE teamID = '{other}'")
    assert pruner.prune(seg, diff) is True
    # OR with a non-partitioned predicate must NOT prune
    mixed = compile_pql("SELECT COUNT(*) FROM baseballStats WHERE "
                        f"teamID = '{other}' OR league = 'AL'")
    assert pruner.prune(seg, mixed) is False


def test_broker_partition_pruning_end_to_end():
    """Per-partition segments: an EQ query only scatters to segments
    (and servers) whose partition can match."""
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cfg = _partitioned_table_config()
        cluster.add_table(cfg)
        teams = ["BOS", "NYA", "DET", "SFN", "CLE", "CHc"]
        by_part = {}
        for t in teams:
            by_part.setdefault(_team_partition(t), []).append(t)
        assert len(by_part) >= 2, by_part
        totals = {}
        for i, (p, ts) in enumerate(sorted(by_part.items())):
            n = 1024
            cols = make_shared_columns(n, seed=i)
            team_col = np.array([ts[j % len(ts)] for j in range(n)],
                                dtype=object)
            cols["teamID"] = team_col
            d = os.path.join(base, f"part_{p}")
            SegmentCreator(make_schema(), cfg,
                           segment_name=f"part_{p}").build(cols, d)
            cluster.upload_segment("baseballStats_OFFLINE", d)
            totals[p] = {t: int((team_col == t).sum()) for t in ts}
        # correctness: the pruned scatter returns the right counts
        for p, ts in by_part.items():
            for t in ts:
                r = cluster.query("SELECT COUNT(*) FROM baseballStats "
                                  f"WHERE teamID = '{t}'")
                assert int(r.aggregation_results[0].value) == totals[p][t]
                # pruning evidence: only the matching partition's segment
                # was processed
                assert r.num_segments_processed <= 1
    finally:
        cluster.stop()


def test_partition_aware_routing_reduces_server_fanout():
    """PartitionAwareOfflineRoutingTableBuilder parity: with multiple
    segments PER PARTITION spread over several servers, the partition-
    aware builder lands each partition's segments on few servers, so a
    partition-pruned EQ query contacts exactly ONE server — while an
    unfiltered query still fans out to all of them."""
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=3)
    try:
        cluster.add_schema(make_schema())
        cfg = _partitioned_table_config()
        cfg.routing_config.builder_name = "PartitionAwareOffline"
        cluster.add_table(cfg)
        teams = ["BOS", "NYA", "DET", "SFN", "CLE", "CHc"]
        by_part = {}
        for t in teams:
            by_part.setdefault(_team_partition(t), []).append(t)
        assert len(by_part) >= 2
        # TWO segments per partition: segment pruning alone would leave
        # them wherever balanced routing spread them; the partition-aware
        # builder must co-locate them
        expected = {}
        for i, (p, ts) in enumerate(sorted(by_part.items())):
            for half in range(2):
                n = 1024
                cols = make_shared_columns(n, seed=10 * i + half)
                cols["teamID"] = np.array(
                    [ts[j % len(ts)] for j in range(n)], dtype=object)
                d = os.path.join(base, f"part_{p}_{half}")
                SegmentCreator(make_schema(), cfg,
                               segment_name=f"part_{p}_{half}").build(
                    cols, d)
                cluster.upload_segment("baseballStats_OFFLINE", d)
                for t in ts:
                    expected[t] = expected.get(t, 0) + int(
                        (cols["teamID"] == t).sum())
        from pinot_tpu.broker.routing import \
            PartitionAwareRoutingTableBuilder
        assert isinstance(
            cluster.broker.routing.table_builder("baseballStats_OFFLINE"),
            PartitionAwareRoutingTableBuilder)
        for p, ts in sorted(by_part.items()):
            for t in ts:
                r = cluster.query("SELECT COUNT(*) FROM baseballStats "
                                  f"WHERE teamID = '{t}'")
                assert int(r.aggregation_results[0].value) == expected[t]
                assert r.num_segments_processed <= 2
                # the routing-time win: one server holds the partition
                assert r.num_servers_queried == 1, \
                    f"team {t} (partition {p}) fanned out to " \
                    f"{r.num_servers_queried} servers"
        # full scan still covers every segment
        r = cluster.query("SELECT COUNT(*) FROM baseballStats")
        assert int(r.aggregation_results[0].value) == sum(expected.values())
    finally:
        cluster.stop()
