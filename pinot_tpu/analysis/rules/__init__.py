"""Rule modules — importing this package registers every rule."""
from pinot_tpu.analysis.rules import (api_compat, concurrency, dtype_drift,
                                      host_sync, retrace)

__all__ = ["api_compat", "concurrency", "dtype_drift", "host_sync",
           "retrace"]
