"""HTTP surfaces + Python client tests.

Mirrors the reference's ClusterIntegrationTestUtils flow driven entirely
over REST: schema POST, table POST, segment upload (tar.gz artifact),
broker /query GET+POST, table views, segment delete — with the Python
client (parity: pinot-api Connection/ResultSetGroup) as the caller.
"""
import json
import os
import tempfile
import urllib.parse
import urllib.request

import numpy as np
import pytest

from fixtures import build_segment, make_schema, make_table_config
from oracle import Oracle

from pinot_tpu.client import (ControllerClient, PinotClientError, connect)
from pinot_tpu.tools.cluster import EmbeddedCluster


@pytest.fixture(scope="module")
def http_cluster():
    work = tempfile.mkdtemp()
    c = EmbeddedCluster(work, num_servers=2, http=True)
    ctl = ControllerClient("127.0.0.1", c.controller_port)
    ctl.add_schema(make_schema().to_json())
    ctl.add_table(make_table_config().to_json())
    all_cols = []
    for i in range(3):
        seg_dir = os.path.join(work, "build", str(i))
        _, cols = build_segment(seg_dir, n=1200, seed=500 + i,
                                name=f"ht_{i}")
        ctl.upload_segment_dir("baseballStats_OFFLINE", seg_dir)
        all_cols.append(cols)
    merged = {k: (np.concatenate([col[k] for col in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((col[k] for col in all_cols), []))
              for k in all_cols[0]}
    conn = connect(f"127.0.0.1:{c.broker_port}")
    yield c, ctl, conn, Oracle(merged)
    conn.close()
    ctl.close()
    c.stop()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read()


def test_rest_schema_and_table_crud(http_cluster):
    c, ctl, _, _ = http_cluster
    assert ctl.get_schema("baseballStats")["schemaName"] == "baseballStats"
    assert "baseballStats_OFFLINE" in ctl.list_tables()
    cfg = ctl.get_table("baseballStats_OFFLINE")
    assert cfg["tableName"].startswith("baseballStats")
    with pytest.raises(PinotClientError, match="404"):
        ctl.get_schema("nope")
    with pytest.raises(PinotClientError, match="404"):
        ctl.get_table("nope_OFFLINE")


def test_rest_upload_makes_segments_queryable(http_cluster):
    c, ctl, conn, oracle = http_cluster
    assert sorted(ctl.list_segments("baseballStats_OFFLINE")) == \
        ["ht_0", "ht_1", "ht_2"]
    ev = ctl.external_view("baseballStats_OFFLINE")
    assert set(ev) == {"ht_0", "ht_1", "ht_2"}
    rg = conn.execute("SELECT COUNT(*) FROM baseballStats")
    assert rg.result_set(0).get(0, 0) == "3600"
    assert rg.num_docs_scanned == 3600


def test_client_aggregation_matches_oracle(http_cluster):
    _, _, conn, oracle = http_cluster
    m = oracle.mask(lambda r: r["league"] == "NL")
    rg = conn.execute("SELECT COUNT(*), SUM(hits) FROM baseballStats "
                      "WHERE league = 'NL'")
    assert rg.result_set_count == 2
    assert rg.result_set(0).get(0, 0) == str(oracle.count(m))
    assert float(rg.result_set(1).get(0, 0)) == float(
        np.sum(oracle.vals("hits", m)))


def test_client_group_by_result_set(http_cluster):
    _, _, conn, oracle = http_cluster
    expected = oracle.group_by(["league"], oracle.mask(lambda r: True),
                               ("count", None))
    rg = conn.execute("SELECT COUNT(*) FROM baseballStats GROUP BY league")
    rs = rg.result_set(0)
    assert rs.group_key_columns == ["league"]
    got = {tuple(rs.group_key(i)): float(rs.get(i, 0))
           for i in range(rs.row_count)}
    assert got == {k: float(v) for k, v in expected.items()}


def test_client_selection_rows(http_cluster):
    _, _, conn, oracle = http_cluster
    rg = conn.execute("SELECT runs FROM baseballStats "
                      "ORDER BY runs DESC LIMIT 5")
    rs = rg.result_set(0)
    assert rs.column_name(0) == "runs"
    top = sorted(oracle.vals("runs", oracle.mask(lambda r: True)),
                 reverse=True)[:5]
    assert [int(rs.get(i, 0)) for i in range(5)] == [int(v) for v in top]


def test_client_trace_flag(http_cluster):
    _, _, conn, _ = http_cluster
    rg = conn.execute("SELECT COUNT(*) FROM baseballStats", trace=True)
    assert rg.trace_info is not None
    assert "broker" in rg.trace_info


def test_get_query_endpoint(http_cluster):
    c, _, _, _ = http_cluster
    q = urllib.parse.quote("SELECT MAX(runs) FROM baseballStats")
    status, payload = _get(c.broker_port, f"/query?pql={q}")
    assert status == 200
    data = json.loads(payload)
    assert data["aggregationResults"][0]["function"] == "max(runs)"


def test_broker_http_error_paths(http_cluster):
    c, _, _, _ = http_cluster
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(c.broker_port, "/query")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(c.broker_port, "/nothere")
    assert e.value.code == 404
    status, payload = _get(c.broker_port, "/health")
    assert (status, payload) == (200, b"OK")
    status, payload = _get(c.broker_port, "/metrics?format=json")
    assert json.loads(payload)["meter.queries.count"] >= 1
    # default /metrics is Prometheus text exposition
    status, payload = _get(c.broker_port, "/metrics")
    assert status == 200
    assert b"# TYPE pinot_broker_queries_total counter" in payload


def test_controller_views_and_segment_metadata(http_cluster):
    c, ctl, _, _ = http_cluster
    status, payload = _get(c.controller_port,
                           "/tables/baseballStats_OFFLINE/idealstate")
    ideal = json.loads(payload)
    assert set(ideal) == {"ht_0", "ht_1", "ht_2"}
    meta = ctl.segment_metadata("baseballStats_OFFLINE", "ht_0")
    assert meta["segmentName"] == "ht_0"
    assert meta["totalDocs"] == 1200
    reb = ctl.rebalance("baseballStats_OFFLINE", dry_run=True)
    assert reb["dryRun"] is True
    assert set(reb["targetState"]) == {"ht_0", "ht_1", "ht_2"}


def test_rest_delete_segment_and_requery(http_cluster):
    c, ctl, conn, _ = http_cluster
    work = tempfile.mkdtemp()
    seg_dir = os.path.join(work, "extra")
    build_segment(seg_dir, n=300, seed=999, name="ht_extra")
    ctl.upload_segment_dir("baseballStats_OFFLINE", seg_dir)
    import time
    deadline = time.time() + 10
    while time.time() < deadline:
        rg = conn.execute("SELECT COUNT(*) FROM baseballStats")
        if rg.result_set(0).get(0, 0) == "3900":
            break
        time.sleep(0.05)
    assert rg.result_set(0).get(0, 0) == "3900"
    ctl.delete_segment("baseballStats_OFFLINE", "ht_extra")
    deadline = time.time() + 10
    while time.time() < deadline:
        rg = conn.execute("SELECT COUNT(*) FROM baseballStats")
        if rg.result_set(0).get(0, 0) == "3600":
            break
        time.sleep(0.05)
    assert rg.result_set(0).get(0, 0) == "3600"
    with pytest.raises(PinotClientError, match="404"):
        ctl.segment_metadata("baseballStats_OFFLINE", "ht_extra")


def test_rest_upload_storage_quota_403(tmp_path):
    """Over-quota upload returns HTTP 403 (StorageQuotaChecker parity);
    a malformed quota string is a 400 at config time, not a 500 later."""
    from pinot_tpu.common.table_config import QuotaConfig
    c = EmbeddedCluster(str(tmp_path / "c"), num_servers=1, http=True)
    ctl = ControllerClient("127.0.0.1", c.controller_port)
    try:
        ctl.add_schema(make_schema().to_json())
        bad = make_table_config(quota_config=QuotaConfig(storage="lots"))
        with pytest.raises(PinotClientError, match="400"):
            ctl.add_table(bad.to_json())
        cfg = make_table_config(quota_config=QuotaConfig(storage="1K"))
        ctl.add_table(cfg.to_json())
        seg_dir = str(tmp_path / "seg")
        build_segment(seg_dir, n=1200, seed=9, name="quota_0")
        with pytest.raises(PinotClientError, match="403"):
            ctl.upload_segment_dir("baseballStats_OFFLINE", seg_dir)
        assert ctl.list_segments("baseballStats_OFFLINE") == []
    finally:
        ctl.close()
        c.stop()


# ---------------------------------------------------------------------------
# HTTP deep-store PinotFS (parity: pinot-common segment fetchers — servers
# without a shared filesystem fetch committed artifacts over HTTP)
# ---------------------------------------------------------------------------


def test_http_pinot_fs_fetch_and_load(http_cluster):
    c, ctl, conn, oracle = http_cluster
    from pinot_tpu.common.filesystem import HttpPinotFS, get_fs
    from pinot_tpu.segment.loader import ImmutableSegmentLoader

    base = f"http://127.0.0.1:{c.controller_port}/deepstore"
    seg_uri = f"{base}/baseballStats_OFFLINE/ht_0"
    fs = get_fs(seg_uri)
    assert isinstance(fs, HttpPinotFS)
    assert fs.exists(seg_uri)
    assert fs.is_directory(seg_uri)
    assert not fs.exists(f"{base}/baseballStats_OFFLINE/nope")
    files = fs.list_files(seg_uri)
    assert any(f.endswith("metadata.json") for f in files), files

    # download → local load → same row count as the uploaded artifact
    dst = tempfile.mkdtemp() + "/fetched_seg"
    assert fs.copy(seg_uri, dst)
    seg = ImmutableSegmentLoader.load(dst)
    assert seg.num_docs == 1200
    # read-only: the controller owns deep-store mutations
    with pytest.raises(PermissionError):
        fs.delete(seg_uri)


def test_http_deepstore_refuses_path_traversal(http_cluster):
    c, _, _, _ = http_cluster
    import urllib.error
    for rel in ("../../etc/passwd", "..%2F..%2Fetc%2Fpasswd"):
        try:
            status, _ = _get(c.controller_port,
                             f"/deepstore/download?path={rel}")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status in (403, 404), rel


def test_participant_fetches_http_download_path(http_cluster):
    """OFFLINE→ONLINE with an http:// downloadPath goes through the
    PinotFS fetch into the server's local cache (SegmentFetcherAndLoader
    parity) and serves queries identically."""
    c, ctl, conn, oracle = http_cluster
    srv = next(iter(c.servers.values()))
    from pinot_tpu.server.participant import ServerParticipant
    base = f"http://127.0.0.1:{c.controller_port}/deepstore"
    # craft a participant against the live manager and a remote path
    p = ServerParticipant(srv, c.controller.manager,
                          work_dir=tempfile.mkdtemp())
    local = p._fetch_segment_dir(
        "baseballStats_OFFLINE", "ht_1",
        f"{base}/baseballStats_OFFLINE/ht_1")
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    seg = ImmutableSegmentLoader.load(local)
    assert seg.num_docs == 1200
    # a plain local path passes through untouched
    meta = c.controller.manager.segment_metadata("baseballStats_OFFLINE",
                                                 "ht_2")
    assert p._fetch_segment_dir("baseballStats_OFFLINE", "ht_2",
                                meta["downloadPath"]) == \
        meta["downloadPath"]


def test_broker_debug_endpoints(tmp_path):
    """Parity: the broker's debug resources — sampled routing table and
    hybrid time boundary over HTTP."""
    import json as _json
    import urllib.error
    import urllib.request

    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.cluster import EmbeddedCluster

    c = EmbeddedCluster(str(tmp_path), num_servers=2, http=True)
    try:
        c.add_schema(make_schema())
        c.add_table(make_table_config())
        d = str(tmp_path / "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "dbg_seg").build(make_columns(500, seed=5), d)
        c.upload_segment("baseballStats_OFFLINE", d)
        base = f"http://127.0.0.1:{c.broker_port}"
        with urllib.request.urlopen(
                f"{base}/debug/routingTable/baseballStats") as r:
            rt = _json.loads(r.read())
        assert "baseballStats_OFFLINE" in rt
        assert any("dbg_seg" in segs
                   for segs in rt["baseballStats_OFFLINE"].values()), rt
        # the offline table has a time column → boundary is published
        with urllib.request.urlopen(
                f"{base}/debug/timeBoundary/baseballStats") as r:
            tbv = _json.loads(r.read())
        assert tbv["timeColumn"] == "yearID" and int(tbv["timeValue"])
        # offline-only table: the boundary exists but is NOT attached
        assert tbv["appliedToQueries"] is False
        # a table with no boundary: 404
        try:
            urllib.request.urlopen(f"{base}/debug/timeBoundary/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # unknown table: routing view is 404
        try:
            urllib.request.urlopen(f"{base}/debug/routingTable/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        c.stop()


def test_broker_debug_endpoints_honor_acl(tmp_path):
    """Debug views consult the same AccessControl SPI as /query."""
    import json as _json
    import urllib.error
    import urllib.request

    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.broker.access_control import TableAclAccessControl
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.cluster import EmbeddedCluster

    c = EmbeddedCluster(str(tmp_path), num_servers=1, http=True)
    try:
        c.add_schema(make_schema())
        c.add_table(make_table_config())
        d = str(tmp_path / "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "acl_seg").build(make_columns(200, seed=7), d)
        c.upload_segment("baseballStats_OFFLINE", d)
        c.broker.access_control = TableAclAccessControl(
            {"baseballStats": ["s3cret"]})
        base = f"http://127.0.0.1:{c.broker_port}"
        url = f"{base}/debug/routingTable/baseballStats"
        try:
            urllib.request.urlopen(url)
            raise AssertionError("expected 403")
        except urllib.error.HTTPError as e:
            assert e.code == 403
        req = urllib.request.Request(
            url, headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req) as r:
            assert "baseballStats_OFFLINE" in _json.loads(r.read())
    finally:
        c.stop()


def test_controller_size_schema_and_pql_passthrough(tmp_path):
    """Parity: TableSize aggregate, GET /tables/{t}/schema, and the
    PqlQueryResource-style query passthrough to a live broker."""
    import json as _json
    import urllib.error
    import urllib.request

    from fixtures import make_columns, make_schema, make_table_config
    from pinot_tpu.controller.state_machine import LIVE
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.cluster import EmbeddedCluster

    c = EmbeddedCluster(str(tmp_path), num_servers=1, http=True)
    try:
        c.add_schema(make_schema())
        c.add_table(make_table_config())
        d = str(tmp_path / "seg0")
        SegmentCreator(make_schema(), make_table_config(),
                       "sz_seg").build(make_columns(800, seed=13), d)
        c.upload_segment("baseballStats_OFFLINE", d)
        base = f"http://127.0.0.1:{c.controller_port}"

        with urllib.request.urlopen(
                f"{base}/tables/baseballStats_OFFLINE/size") as r:
            sz = _json.loads(r.read())
        assert sz["reportedSizeInBytes"] > 0
        assert sz["segments"]["sz_seg"] > 0

        with urllib.request.urlopen(
                f"{base}/tables/baseballStats_OFFLINE/schema") as r:
            sch = _json.loads(r.read())
        assert sch["schemaName"] == "baseballStats"

        # no broker registered yet: passthrough reports 503
        try:
            urllib.request.urlopen(
                f"{base}/pql?pql=SELECT+COUNT(*)+FROM+baseballStats")
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503

        # register the embedded broker's HTTP endpoint as a live broker
        c.controller.manager.store.set(
            f"{LIVE}/Broker_embedded",
            {"tags": ["DefaultTenant_BROKER"], "host": "127.0.0.1",
             "port": c.broker_port})
        with urllib.request.urlopen(
                f"{base}/pql?pql=SELECT+COUNT(*)+FROM+baseballStats") as r:
            out = _json.loads(r.read())
        assert out["aggregationResults"][0]["value"] == "800", out
    finally:
        c.stop()


def test_cluster_manager_ui_served(http_cluster):
    """/ui serves the cluster-manager page (controller web app parity)
    wired to the same-origin REST endpoints."""
    import urllib.request
    cluster, _ctl, _conn, _oracle = http_cluster
    with urllib.request.urlopen(
            f"http://127.0.0.1:{cluster.controller_port}/ui",
            timeout=10) as r:
        body = r.read().decode("utf-8")
    assert "cluster manager" in body
    assert "/instances" in body and "/tables" in body
