"""Server-plane tests: serde, DataTable, refcounted segments, scheduler,
and the full request path (bytes in → DataTable bytes out, over TCP too).

Mirrors the reference's server-side unit tiers: data-manager refcount
semantics, QueryScheduler behavior, DataTable round-trips, and
ScheduledRequestHandler-style end-to-end request handling.
"""
import asyncio
import tempfile
import threading
import time

import numpy as np
import pytest

from fixtures import build_segment
from oracle import Oracle

from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.serde import (instance_request_from_bytes,
                                    instance_request_to_bytes,
                                    obj_from_bytes, obj_to_bytes,
                                    request_from_json, request_to_json)
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.reduce import BrokerReduceService
from pinot_tpu.server import (ServerInstance, TableDataManager,
                              make_scheduler)
from pinot_tpu.transport.tcp import EventLoopThread, ServerConnection


# -- serde ------------------------------------------------------------------

def test_object_serde_roundtrip():
    cases = [
        None, 0, -1, 2**62, 2**100, 3.14, float("inf"), "héllo", b"\x00\xff",
        (1, 2.5, "x"), [1, [2, [3]]], {1, 2, 3}, {"a", "b"},
        {"k": 1, "j": (2.0, 3)}, {(1, 2): {3, 4}},
        (None, set(), {}, []),
        True, False, (True, 1, False, 0), {"flag": True},
    ]
    for v in cases:
        assert obj_from_bytes(obj_to_bytes(v)) == v, v
    # booleans must keep their type across the wire (distinct tag), not
    # collapse to 1/0 like the round-1 int encoding did
    for v in (True, False):
        rt = obj_from_bytes(obj_to_bytes(v))
        assert isinstance(rt, bool) and rt is v
    rt = obj_from_bytes(obj_to_bytes((True, 1)))
    assert isinstance(rt[0], bool) and not isinstance(rt[1], bool)


def test_request_json_roundtrip():
    pqls = [
        "SELECT COUNT(*) FROM t WHERE a = 'x' AND b IN (1,2,3) OR c > 5",
        "SELECT SUM(m), PERCENTILE95(m) FROM t WHERE x BETWEEN 1 AND 9 "
        "GROUP BY d1, d2 HAVING SUM(m) > 100 TOP 42",
        "SELECT c1, c2 FROM t ORDER BY c1 DESC LIMIT 7, 21",
    ]
    for pql in pqls:
        r = compile_pql(pql)
        r2 = request_from_json(request_to_json(r))
        assert request_to_json(r2) == request_to_json(r), pql


def test_instance_request_bytes_roundtrip():
    req = InstanceRequest(
        request_id=42, query=compile_pql("SELECT MAX(x) FROM t"),
        search_segments=["s1", "s2"], enable_trace=True, broker_id="b0")
    r2 = instance_request_from_bytes(instance_request_to_bytes(req))
    assert r2.request_id == 42
    assert r2.search_segments == ["s1", "s2"]
    assert r2.enable_trace is True
    assert r2.query.aggregations[0].function_name == "MAX"


def test_datatable_roundtrip_group_by():
    req = compile_pql("SELECT SUM(m), AVG(m) FROM t GROUP BY d1, d2")
    dt = DataTable(kind=2, columns=["d1", "d2", "sum(m)", "avg(m)"],
                   num_group_cols=2,
                   rows=[("x", 1, 10.0, (10.0, 2)), ("y", 2, 5.5, (5.5, 1))],
                   metadata={"numDocsScanned": "3", "totalDocs": "10"},
                   exceptions=["boom"])
    dt2 = DataTable.from_bytes(dt.to_bytes())
    assert dt2.rows == dt.rows
    assert dt2.columns == dt.columns
    assert dt2.exceptions == ["boom"]
    blk = dt2.to_block()
    assert blk.group_map[("x", 1)] == [10.0, (10.0, 2)]
    assert blk.stats.num_docs_scanned == 3


# -- data manager -----------------------------------------------------------

def test_refcounted_segment_swap():
    base = tempfile.mkdtemp()
    seg1, _ = build_segment(base + "/a", n=1000, seed=1, name="seg_a")
    tdm = TableDataManager("t")
    tdm.add_segment(seg1)
    acquired, missing = tdm.acquire_segments(["seg_a", "nope"])
    assert [s.name for s in acquired] == ["seg_a"]
    assert missing == ["nope"]

    # replace while acquired: old manager stays alive until released
    seg1b, _ = build_segment(base + "/b", n=500, seed=2, name="seg_a")
    tdm.add_segment(seg1b)
    assert acquired[0].refcount == 1           # table dropped its ref
    assert acquired[0].segment.num_docs == 1000
    acquired2, _ = tdm.acquire_segments(["seg_a"])
    assert acquired2[0].segment.num_docs == 500
    tdm.release_segment(acquired[0])
    assert acquired[0].refcount == 0
    tdm.release_segment(acquired2[0])
    tdm.remove_segment("seg_a")
    assert tdm.segment_names() == []


def test_scheduler_fcfs_and_tokenbucket():
    for algo in ("fcfs", "tokenbucket"):
        sched = make_scheduler(algo, num_workers=2)
        futures = [sched.submit("t", lambda i=i: i * i) for i in range(8)]
        assert sorted(f.result(timeout=5) for f in futures) == \
            [i * i for i in range(8)]
        err = sched.submit("t", lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            err.result(timeout=5)
        sched.shutdown()


def test_tokenbucket_prefers_higher_token_group():
    sched = make_scheduler("tokenbucket", num_workers=1)
    release = threading.Event()
    blocked = sched.submit("warm", lambda: release.wait(5))
    # pin balances: "hog" deeply in debt, "idle" fresh — then queue both
    # while the single worker is occupied so the drain order is decided
    # purely by token priority
    sched.queue.group("hog").available_tokens = -1e6
    sched.queue.group("idle").available_tokens = 100.0
    order = []
    f_hog = sched.submit("hog", lambda: order.append("hog"))
    f_idle = sched.submit("idle", lambda: order.append("idle"))
    release.set()
    f_hog.result(timeout=5)
    f_idle.result(timeout=5)
    blocked.result(timeout=5)
    sched.shutdown()
    assert order == ["idle", "hog"]


# -- end-to-end server path -------------------------------------------------

@pytest.fixture(scope="module")
def server_with_data():
    base = tempfile.mkdtemp()
    segs, all_cols = [], []
    for i in range(3):
        seg, cols = build_segment(f"{base}/seg{i}", n=2000, seed=50 + i,
                                  name=f"bs_{i}")
        segs.append(seg)
        all_cols.append(cols)
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    server = ServerInstance("server_0")
    tdm = server.data_manager.table("baseballStats", create=True)
    for seg in segs:
        tdm.add_segment(seg)
    yield server, Oracle(merged)
    server.stop()


def _query_server(server, pql, segments=None):
    req = InstanceRequest(request_id=1, query=compile_pql(pql),
                          search_segments=segments)
    dt = DataTable.from_bytes(
        server.handle_request_bytes(instance_request_to_bytes(req)))
    return dt


def test_server_executes_aggregation(server_with_data):
    server, oracle = server_with_data
    m = oracle.mask(lambda r: r["yearID"] >= 2005)
    dt = _query_server(server,
                       "SELECT COUNT(*), SUM(runs) FROM baseballStats "
                       "WHERE yearID >= 2005")
    blk = dt.to_block()
    assert blk.agg_intermediates[0] == oracle.count(m)
    assert blk.agg_intermediates[1] == pytest.approx(oracle.sum("runs", m))
    assert blk.stats.num_segments_processed == 3
    assert dt.metadata["requestId"] == "1"


def test_server_respects_search_segments(server_with_data):
    server, _ = server_with_data
    dt = _query_server(server, "SELECT COUNT(*) FROM baseballStats",
                       segments=["bs_0", "bs_2"])
    blk = dt.to_block()
    assert blk.agg_intermediates[0] == 4000


def test_server_reports_missing_segments(server_with_data):
    server, _ = server_with_data
    dt = _query_server(server, "SELECT COUNT(*) FROM baseballStats",
                       segments=["bs_0", "gone_1"])
    assert any("SegmentMissingError" in e for e in dt.exceptions)
    assert dt.to_block().agg_intermediates[0] == 2000


def test_server_unknown_table(server_with_data):
    server, _ = server_with_data
    dt = _query_server(server, "SELECT COUNT(*) FROM nope")
    assert any("TableDoesNotExistError" in e for e in dt.exceptions)


def test_server_over_tcp_and_broker_reduce(server_with_data):
    server, oracle = server_with_data
    port = server.start(port=0)
    loop = EventLoopThread()
    conn = ServerConnection("127.0.0.1", port)
    try:
        pql = ("SELECT AVG(hits) FROM baseballStats WHERE league = 'AL' "
               "GROUP BY teamID TOP 500")
        req = InstanceRequest(request_id=7, query=compile_pql(pql))
        payload = instance_request_to_bytes(req)
        raw = loop.run(conn.request(payload, timeout=30))
        dt = DataTable.from_bytes(raw)
        resp = BrokerReduceService().reduce(compile_pql(pql),
                                            [dt.to_block()])
        m = oracle.mask(lambda r: r["league"] == "AL")
        expected = oracle.group_by(["teamID"], m, ("avg", "hits"))
        got = {tuple(g["group"]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        for k, v in expected.items():
            assert got[k] == pytest.approx(v), k
    finally:
        loop.run(conn.close())
        loop.stop()


# ---------------------------------------------------------------------------
# Instance-level execution-path coverage (VERDICT r2 #9): with a mesh
# present, shardable sets ride the ICI combine and un-shardable sets fall
# back to sequential per-segment execution — both answering identically,
# both RECORDING which path served (reference behavior: per-segment
# combine, CombineOperator.java:27)
# ---------------------------------------------------------------------------


def test_instance_executor_records_sharded_and_fallback_paths():
    import tempfile as _tf

    from fixtures import make_schema, make_table_config
    from pinot_tpu.common.request import InstanceRequest
    from pinot_tpu.parallel import make_mesh
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
    from pinot_tpu.server.data_manager import InstanceDataManager
    from pinot_tpu.server.query_executor import InstanceQueryExecutor

    base = _tf.mkdtemp()
    dm = InstanceDataManager()
    tdm = dm.table("baseballStats", create=True)
    all_cols = []
    # independently built segments: different dictionaries, SAME padded
    # size — the union remap keeps these on the sharded device path
    for i in range(3):
        seg, cols = build_segment(f"{base}/p{i}", n=2048, seed=70 + i,
                                  name=f"path_{i}")
        tdm.add_segment(seg)
        all_cols.append(cols)
    ex = InstanceQueryExecutor(dm, mesh=make_mesh())

    def ask():
        req = InstanceRequest(request_id=9, query=compile_pql(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats "
            "WHERE yearID >= 1990"))
        return ex.execute(req)

    runs = np.concatenate([c["runs"] for c in all_cols])
    years = np.concatenate([c["yearID"] for c in all_cols])
    exp_cnt = int((years >= 1990).sum())
    exp_sum = float(runs[years >= 1990].sum())

    dt = ask()
    blk = dt.to_block()
    assert dt.metadata["executionPath"] == "sharded"
    assert blk.agg_intermediates[0] == exp_cnt
    assert blk.agg_intermediates[1] == pytest.approx(exp_sum)

    # a consuming (mutable) segment in the set is genuinely un-stackable:
    # the executor must serve the same query via the sequential fallback
    # and say so
    mseg = MutableSegmentImpl(make_schema(), make_table_config(),
                              "cons_path")
    extra = {"teamID": "BOS", "league": "AL", "playerName": "x",
             "position": ["P"], "runs": 7, "hits": 3, "average": 0.3,
             "salary": 1.0, "yearID": 1999}
    mseg.index_row(extra)
    tdm.add_segment(mseg)
    dt2 = ask()
    blk2 = dt2.to_block()
    assert dt2.metadata["executionPath"] == "sequential"
    assert blk2.agg_intermediates[0] == exp_cnt + 1
    assert blk2.agg_intermediates[1] == pytest.approx(exp_sum + 7)


def test_server_admin_http_api():
    """Parity: pinot-server api/resources — TablesResource,
    TableSizeResource, HealthCheckResource, and the MmapDebugResource
    analogue (/debug/memory reports HBM-resident lane bytes — the TPU
    build's native-memory accounting)."""
    import json as _json
    import tempfile as _tf
    import urllib.request

    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.server.http_api import ServerApiServer
    from pinot_tpu.server.instance import ServerInstance

    base = _tf.mkdtemp()
    seg, _cols = build_segment(f"{base}/adm", n=1024, seed=91,
                               name="adm_seg")
    srv = ServerInstance("adm_srv")
    srv.data_manager.table("baseballStats", create=True).add_segment(seg)
    api = ServerApiServer(srv)
    port = api.start()

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            body = r.read()
            return r.status, body

    try:
        st, body = get("/health")
        assert st == 200 and body == b"OK"
        st, body = get("/tables")
        assert _json.loads(body)["tables"] == ["baseballStats"]
        st, body = get("/tables/baseballStats/segments")
        segs = _json.loads(body)["segments"]
        assert segs["adm_seg"]["totalDocs"] == 1024
        assert segs["adm_seg"]["mutable"] is False
        st, body = get("/tables/baseballStats/size")
        size = _json.loads(body)
        assert size["totalHostBytes"] > 0
        # nothing uploaded yet → zero HBM residency
        st, body = get("/debug/memory")
        mem = _json.loads(body)
        assert mem["totalHbmResidentBytes"] == 0
        # run a device query → lanes become HBM-resident
        engine = QueryEngine([seg])
        engine.query("SELECT SUM(runs) FROM baseballStats "
                     "WHERE yearID >= 1990")
        st, body = get("/debug/memory")
        mem = _json.loads(body)
        assert mem["totalHbmResidentBytes"] > 0
        t = mem["tables"]["baseballStats"]["adm_seg"]
        assert t["hbmResidentBytes"] > 0 and t["hostBytes"] > 0
    finally:
        api.stop()
        srv.stop()


def test_retry_policies():
    """Parity: common/utils/retry/ — fixed/exponential/random policies,
    attempt() contract (N tries, policy-shaped sleeps, last failure
    chained when exhausted)."""
    import random as _random

    from pinot_tpu.common.retry import (ExponentialBackoffRetryPolicy,
                                        FixedDelayRetryPolicy,
                                        RandomDelayRetryPolicy,
                                        RetryExhaustedError)

    calls = []
    sleeps = []

    def flaky_then_ok():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    p = FixedDelayRetryPolicy(attempts=5, delay_s=0.01)
    assert p.attempt(flaky_then_ok, sleep=sleeps.append) == "ok"
    assert len(calls) == 3 and sleeps == [0.01, 0.01]

    def always_fails():
        raise ValueError("nope")

    with pytest.raises(RetryExhaustedError) as ei:
        FixedDelayRetryPolicy(attempts=2, delay_s=0).attempt(
            always_fails, sleep=lambda s: None)
    assert isinstance(ei.value.__cause__, ValueError)

    # a non-retryable exception propagates immediately
    n = []
    with pytest.raises(KeyError):
        FixedDelayRetryPolicy(attempts=3, delay_s=0).attempt(
            lambda: (n.append(1), {}["x"])[1],
            retry_on=(ConnectionError,), sleep=lambda s: None)
    assert len(n) == 1

    exp = ExponentialBackoffRetryPolicy(attempts=4, initial_delay_s=1.0,
                                        scale=2.0,
                                        rng=_random.Random(7))
    d0, d1, d2 = exp.delay_for(0), exp.delay_for(1), exp.delay_for(2)
    assert 0.5 <= d0 < 1.0 and 1.0 <= d1 < 2.0 and 2.0 <= d2 < 4.0

    rnd = RandomDelayRetryPolicy(attempts=3, min_delay_s=0.2,
                                 max_delay_s=0.4,
                                 rng=_random.Random(3))
    assert all(0.2 <= rnd.delay_for(i) <= 0.4 for i in range(5))


def test_deep_store_fetch_retries_transient_failures(tmp_path):
    """The participant's remote segment fetch survives transient
    deep-store failures (SegmentFetcherAndLoader retry parity)."""
    import os

    from pinot_tpu.common import filesystem as fsmod
    from pinot_tpu.server.participant import ServerParticipant

    class FlakyFS(fsmod.PinotFS):
        fails = 2                       # class-level: get_fs instantiates

        def copy(self, src, dst):
            if FlakyFS.fails > 0:
                FlakyFS.fails -= 1
                raise ConnectionError("deep store hiccup")
            os.makedirs(dst, exist_ok=True)
            with open(os.path.join(dst, "ok"), "w") as fh:
                fh.write("1")

    fsmod.register_fs("flaky", FlakyFS)
    try:
        part = ServerParticipant.__new__(ServerParticipant)
        part.work_dir = str(tmp_path)

        class _Srv:
            instance_id = "s0"
        part.server = _Srv()

        class _Mgr:          # no controller in this unit: identity resolve
            @staticmethod
            def resolve_download_path(p):
                return p
        part.manager = _Mgr()
        local = part._fetch_segment_dir(
            "t_OFFLINE", "seg0", "flaky://deep/t/seg0")
        assert os.path.isfile(os.path.join(local, "ok"))
        assert FlakyFS.fails == 0
    finally:
        fsmod._REGISTRY.pop("flaky", None)
