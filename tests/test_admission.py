"""Server admission control + typed server-busy routing.

Unit level: deterministic shed order (deadline [load-gated at the low
watermark] → capacity → hedge → tenant fair-share → brownout) on an
injectable clock, no wall-clock sleeps. Integration level: a shed request answers with the typed
server-busy DataTable, the router fails over to a replica WITHOUT
retrying the same server, and a cache hit bypasses admission entirely
even when the server is saturated.
"""
import tempfile

import pytest

from fixtures import build_segment

from pinot_tpu.broker import (BrokerRequestHandler, InProcessTransport,
                              RoutingManager)
from pinot_tpu.common.cluster_state import ONLINE, TableView
from pinot_tpu.common.datatable import (DataTable, RESULT_CACHE_HIT_KEY,
                                        RETRY_AFTER_MS_KEY,
                                        SERVER_BUSY_EXC_PREFIX,
                                        SERVER_BUSY_KEY)
from pinot_tpu.common.metrics import MetricsRegistry, ServerMeter
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.serde import instance_request_to_bytes
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.server import ServerInstance
from pinot_tpu.server.admission import (AdmissionController,
                                        ServiceTimeEstimator,
                                        busy_datatable)


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _controller(max_pending=10, est_table=None, est_ms=None, **kw):
    metrics = MetricsRegistry("server")
    estimator = ServiceTimeEstimator(metrics)
    if est_table is not None:
        # seed the SAME per-table timer query_executor.py feeds after
        # every execution — the estimator only reads it
        from pinot_tpu.common.metrics import ServerQueryPhase
        for _ in range(ServiceTimeEstimator.MIN_SAMPLES):
            metrics.timer(ServerQueryPhase.QUERY_PROCESSING,
                          table=est_table).update(est_ms)
    return AdmissionController(metrics=metrics, estimator=estimator,
                               max_pending=max_pending,
                               clock=FakeClock(), **kw), metrics


def _fill(ctrl, n, tenant="filler"):
    for _ in range(n):
        assert ctrl.admit("T", tenant)


# ---------------------------------------------------------------------------
# Shed order (deterministic, fake clock)
# ---------------------------------------------------------------------------


def test_deadline_aware_shed_uses_service_estimate():
    ctrl, _ = _controller(est_table="T", est_ms=100.0)   # low = 4
    # IDLE server: below the low watermark nothing deadline-sheds —
    # the p75 estimate is table-wide, so a cheap query class with a
    # tight timeout would otherwise hard-fail (terminally, since the
    # router never fails over a deadline shed) on an idle cluster;
    # the executor's deadline truncation handles truly doomed work
    assert ctrl.admit("T", "idle", budget_ms=50.0)
    _fill(ctrl, 3)                                       # depth 4 = low
    d = ctrl.admit("T", "a", budget_ms=50.0)
    assert not d and d.cause == "deadline"
    assert ctrl.admit("T", "a", budget_ms=200.0)
    # a table with no estimate yet never deadline-sheds
    assert ctrl.admit("U", "a", budget_ms=0.5)


def test_hedges_shed_first_at_low_watermark():
    ctrl, _ = _controller(max_pending=10)          # low = 4
    _fill(ctrl, 3)
    assert ctrl.admit("T", "a", hedge=True)        # below low: fine
    d = ctrl.admit("T", "a", hedge=True)           # depth 4 >= low
    assert not d and d.cause == "hedge"
    assert ctrl.admit("T", "a", hedge=False)       # primaries still admit


def test_hedge_joining_open_batch_window_is_admitted():
    """A hedged duplicate whose plan shape has an OPEN batch window on
    this server rides the primary's dispatch for (almost) free — the
    low-watermark hedge shed must not apply to it."""
    ctrl, _ = _controller(max_pending=10)          # low = 4
    _fill(ctrl, 4)
    d = ctrl.admit("T", "a", hedge=True)
    assert not d and d.cause == "hedge"            # no window: shed
    assert ctrl.admit("T", "a", hedge=True, batch_join=True)
    # the carve-out is hedge-specific sugar, not an admission bypass:
    # capacity still wins at max_pending (distinct tenants keep each
    # below its fair-share floor so only the capacity tier engages)
    for i in range(5):                             # depth 10 = max
        assert ctrl.admit("T", f"x{i}")
    d = ctrl.admit("T", "a", hedge=True, batch_join=True)
    assert not d and d.cause == "capacity"


def test_over_quota_tenant_shed_at_mid_watermark():
    ctrl, _ = _controller(max_pending=10)          # mid = 7
    _fill(ctrl, 6, tenant="aggressor")
    _fill(ctrl, 1, tenant="victim")                # depth 7, 2 active
    d = ctrl.admit("T", "aggressor")               # 6 >= fair (7//2=3)
    assert not d and d.cause == "tenantOverQuota"
    assert d.retry_after_ms > 0
    # the victim is under its fair share: admitted
    assert ctrl.admit("T", "victim")


def test_sole_tenant_never_fair_share_shed():
    # fair-share protects OTHER tenants: with a single active tenant
    # fair == depth == its own count, so the gate would shed EVERYTHING
    # at mid and brownout/capacity could never engage — it must not fire
    ctrl, _ = _controller(max_pending=10)          # mid = 7, high = 9
    _fill(ctrl, 7, tenant="only")
    d = ctrl.admit("T", "only")                    # depth 7 >= mid
    assert d and not d.brownout


def test_brownout_at_high_watermark_tightens_deadline():
    ctrl, _ = _controller(max_pending=10, est_table="T",
                          est_ms=40.0)             # high = 9
    _fill(ctrl, 5, tenant="a")
    _fill(ctrl, 4, tenant="b")                     # depth 9, fair split
    d = ctrl.admit("T", "c", budget_ms=10_000.0)
    assert d and d.brownout
    # deadline ≈ now + est × factor, far tighter than the 10s budget
    assert d.deadline_s == pytest.approx(
        100.0 + 40.0 * AdmissionController.BROWNOUT_FACTOR / 1e3)


def test_capacity_shed_at_max_pending():
    ctrl, metrics = _controller(max_pending=4)
    _fill(ctrl, 2, tenant="a")
    _fill(ctrl, 2, tenant="b")
    d = ctrl.admit("T", "c")
    assert not d and d.cause == "capacity"
    assert metrics.meter(ServerMeter.REQUESTS_SHED).count == 1
    assert metrics.meter(ServerMeter.REQUESTS_SHED,
                         table="capacity").count == 1


def test_release_restores_depth_and_tenant_share():
    ctrl, _ = _controller(max_pending=4)
    _fill(ctrl, 2, tenant="a")
    _fill(ctrl, 2, tenant="b")
    assert not ctrl.admit("T", "c")
    for _ in range(2):
        ctrl.release("a")
    assert ctrl.depth() == 2
    assert ctrl.admit("T", "c")


def test_estimator_never_registers_unknown_tables():
    # admission runs before any table-existence check — probing the
    # estimate must not create a per-table timer series, or a flood of
    # random table names grows the registry without bound
    ctrl, metrics = _controller(max_pending=100)
    for i in range(50):
        assert ctrl.admit(f"no-such-table-{i}", "a", budget_ms=1.0)
    _, _, timers = metrics.metric_maps()
    assert not any("no-such-table" in k for k in timers)


def test_busy_datatable_is_typed():
    dt = busy_datatable(7, "tenantOverQuota", 120.0)
    assert dt.metadata[SERVER_BUSY_KEY] == "tenantOverQuota"
    assert dt.metadata[RETRY_AFTER_MS_KEY] == "120"
    assert dt.metadata["requestId"] == "7"
    assert dt.exceptions[0].startswith(SERVER_BUSY_EXC_PREFIX)
    # survives the wire round-trip the router reads it from
    rt = DataTable.from_bytes(dt.to_bytes())
    assert rt.metadata[SERVER_BUSY_KEY] == "tenantOverQuota"


# ---------------------------------------------------------------------------
# Instance integration: typed busy replies + cache bypass
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    s = ServerInstance("s0", max_pending=8)
    seg, cols = build_segment(tempfile.mkdtemp(), n=800, seed=3,
                              name="adm_0")
    s.data_manager.table("baseballStats_OFFLINE",
                         create=True).add_segment(seg)
    yield s, cols
    s.stop()


def _request(pql, request_id=1, **kw):
    return instance_request_to_bytes(InstanceRequest(
        request_id=request_id, query=compile_pql(pql), **kw))


def test_saturated_server_sheds_with_typed_reply(server):
    s, _ = server
    # saturate admission without real threads (distinct tenants so
    # the fair-share gate doesn't fire before the capacity gate)
    for i in range(s.admission.max_pending):
        assert s.admission.admit("baseballStats_OFFLINE", f"x{i}")
    reply = DataTable.from_bytes(s.handle_request_bytes(
        _request("SELECT COUNT(*) FROM baseballStats_OFFLINE")))
    assert reply.metadata.get(SERVER_BUSY_KEY) == "capacity"
    assert reply.exceptions and \
        reply.exceptions[0].startswith(SERVER_BUSY_EXC_PREFIX)


def test_cache_hit_bypasses_saturated_admission(server):
    s, cols = server
    pql = "SELECT COUNT(*) FROM baseballStats_OFFLINE"
    warm = DataTable.from_bytes(s.handle_request_bytes(_request(pql)))
    assert not warm.exceptions
    for i in range(s.admission.max_pending):
        assert s.admission.admit("baseballStats_OFFLINE", f"x{i}")
    hit = DataTable.from_bytes(s.handle_request_bytes(_request(pql, 2)))
    assert hit.metadata.get(RESULT_CACHE_HIT_KEY) == "1"
    assert hit.rows == warm.rows           # bit-identical result
    # ...while an uncached query is still shed
    other = DataTable.from_bytes(s.handle_request_bytes(
        _request("SELECT SUM(runs) FROM baseballStats_OFFLINE", 3)))
    assert other.metadata.get(SERVER_BUSY_KEY) == "capacity"


def test_workload_tags_namespaced_and_bounded(server):
    s, _ = server
    q = compile_pql("SELECT COUNT(*) FROM baseballStats_OFFLINE")
    untagged = InstanceRequest(request_id=1, query=q)
    tagged = InstanceRequest(request_id=2, query=q, workload="alice")
    spoof = InstanceRequest(request_id=3, query=q,
                            workload="baseballStats_OFFLINE")
    assert s._tenant(untagged) == "baseballStats_OFFLINE"
    assert s._tenant(tagged) == "w:alice"
    # OPTION(workload=<table name>) must NOT join untagged traffic's
    # per-table scheduler group / fair-share bucket
    assert s._tenant(spoof) != s._tenant(untagged)
    # past the cap, unseen client-chosen tags fall back to the
    # (config-bounded) table group instead of growing scheduler state
    s._tenant_tags = {f"t{i}" for i in range(s.MAX_TENANT_TAGS - 1)} \
        | {"alice"}
    flood = InstanceRequest(request_id=4, query=q, workload="fresh-tag")
    assert s._tenant(flood) == "baseballStats_OFFLINE"
    assert s._tenant(tagged) == "w:alice"      # seen tags keep working


def test_shed_requests_do_not_burn_tag_budget(server):
    """A flood of unique workload tags that are ALL shed must not
    consume permanent tag slots — otherwise 256 rejected requests
    would lock every later tenant out of per-tenant isolation until
    server restart. Slots commit only on admission."""
    s, _ = server
    for i in range(s.admission.max_pending):
        assert s.admission.admit("baseballStats_OFFLINE", f"x{i}")
    for i in range(20):
        reply = DataTable.from_bytes(s.handle_request_bytes(_request(
            "SELECT COUNT(*) FROM baseballStats_OFFLINE", 10 + i,
            workload=f"flood-{i}")))
        assert reply.metadata.get(SERVER_BUSY_KEY) == "capacity"
    assert s._tenant_tags == set()          # nothing committed
    for i in range(s.admission.max_pending):
        s.admission.release(f"x{i}")
    ok = DataTable.from_bytes(s.handle_request_bytes(_request(
        "SELECT COUNT(*) FROM baseballStats_OFFLINE", 99,
        workload="alice")))
    assert not ok.exceptions
    assert s._tenant_tags == {"alice"}      # admitted → slot committed


def test_hedge_flag_travels_and_sheds_under_pressure(server):
    s, _ = server
    low = s.admission.low
    for _ in range(low):
        assert s.admission.admit("baseballStats_OFFLINE", "x")
    reply = DataTable.from_bytes(s.handle_request_bytes(
        _request("SELECT MAX(hits) FROM baseballStats_OFFLINE",
                 hedge=True)))
    assert reply.metadata.get(SERVER_BUSY_KEY) == "hedge"


# ---------------------------------------------------------------------------
# Router integration: busy is non-retriable-on-same-server
# ---------------------------------------------------------------------------


def _two_server_handler(tmpdir, busy_server=True):
    servers = {}
    view = TableView("baseballStats_OFFLINE", {})
    seg_a, cols = build_segment(f"{tmpdir}/sa", n=900, seed=11,
                                name="rb_0")
    seg_b, _ = build_segment(f"{tmpdir}/sb", n=900, seed=11, name="rb_0")
    # A sheds everything at the door (max_pending=0); B is healthy
    servers["A"] = ServerInstance("A", max_pending=0 if busy_server
                                  else 64)
    servers["B"] = ServerInstance("B")
    servers["A"].data_manager.table("baseballStats_OFFLINE",
                                    create=True).add_segment(seg_a)
    servers["B"].data_manager.table("baseballStats_OFFLINE",
                                    create=True).add_segment(seg_b)
    view.segment_states["rb_0"] = {"A": ONLINE, "B": ONLINE}
    routing = RoutingManager()
    routing.update_view(view)
    handler = BrokerRequestHandler(routing, InProcessTransport(servers))
    return handler, servers, cols


def test_busy_server_fails_over_to_replica_not_retried():
    base = tempfile.mkdtemp()
    handler, servers, cols = _two_server_handler(base)
    try:
        for _ in range(4):
            resp = handler.handle(
                "SELECT COUNT(*) FROM baseballStats_OFFLINE")
            # wherever the primary landed, the answer is complete:
            # either B answered directly, or A's shed failed over to B
            assert not resp.exceptions, resp.exceptions
            assert not resp.partial_response
            assert int(resp.aggregation_results[0].value) == 900
        # A executed NOTHING (every reaching request was shed pre-
        # scheduler) and its breaker never opened — busy is not a fault
        assert servers["A"].metrics.meter(ServerMeter.QUERIES).count == 0
        assert handler.fault_tolerance.breaker_state("A") == 0
    finally:
        for s in servers.values():
            s.stop()
        handler.close()


def test_deadline_shed_is_terminal_no_failover():
    # a deadline-cause shed means the remaining budget is below the
    # shedding server's service-time estimate for the table. The router
    # surfaces it instead of dispatching failover waves (per-shed
    # fan-out multiplies RPCs at the overload knee; a degraded-replica
    # false shed is self-correcting via the on_busy health ding).
    from pinot_tpu.common.metrics import BrokerMeter, ServerQueryPhase
    base = tempfile.mkdtemp()
    handler, servers, _ = _two_server_handler(base, busy_server=False)
    try:
        for s in servers.values():
            timer = s.metrics.timer(ServerQueryPhase.QUERY_PROCESSING,
                                    table="baseballStats_OFFLINE")
            for _ in range(8):
                timer.update(200.0)        # p75 est far above the budget
            # deadline shedding only engages under load (>= low
            # watermark): park admitted-never-released filler queries
            # so the gate is active on BOTH replicas
            for _ in range(s.admission.low):
                assert s.admission.admit("baseballStats_OFFLINE", "bg")
        resp = handler.handle("SELECT COUNT(*) FROM baseballStats_OFFLINE"
                              " OPTION(timeoutMs=40)")
        assert resp.partial_response
        assert any(e.get("errorCode") == 503 for e in resp.exceptions)
        assert "deadline" in str(resp.exceptions)
        # no failover wave was dispatched for the doomed query
        assert handler.metrics.meter(
            BrokerMeter.SEGMENT_RETRIES).count == 0
        # ...and the internal routing marker never leaks to the client
        assert "busyCause" not in str(resp.exceptions)
    finally:
        for s in servers.values():
            s.stop()
        handler.close()


def test_all_replicas_busy_surfaces_typed_503():
    base = tempfile.mkdtemp()
    servers = {}
    seg, _ = build_segment(f"{base}/s", n=500, seed=5, name="lone_0")
    servers["A"] = ServerInstance("A", max_pending=0)
    servers["A"].data_manager.table("baseballStats_OFFLINE",
                                    create=True).add_segment(seg)
    routing = RoutingManager()
    routing.update_view(TableView("baseballStats_OFFLINE",
                                  {"lone_0": {"A": ONLINE}}))
    handler = BrokerRequestHandler(routing, InProcessTransport(servers))
    try:
        resp = handler.handle("SELECT COUNT(*) FROM baseballStats_OFFLINE")
        assert resp.partial_response
        codes = {e.get("errorCode") for e in resp.exceptions}
        assert 503 in codes                 # typed server-busy, not 425
        assert 425 not in codes
        from pinot_tpu.common.metrics import BrokerMeter
        assert handler.metrics.meter(
            BrokerMeter.QUERIES_DROPPED, table="serverBusy").count == 1
        # the whole query was lost to shedding: the reply carries a
        # Retry-After so the HTTP layer can answer a real 503
        assert resp.retry_after_s >= 1.0
    finally:
        servers["A"].stop()
        handler.close()


def test_http_maps_whole_query_shed_to_503_with_retry_after():
    """A query FULLY lost to server-busy shedding must be a real HTTP
    503 + Retry-After — clients keying backoff on status codes must
    see overload, not a 200 that invites an instant retry."""
    import asyncio

    from pinot_tpu.broker.http_api import BrokerApiServer
    from pinot_tpu.common.response import BrokerResponse

    class _ShedHandler:
        metrics = MetricsRegistry("broker")

        def handle(self, pql, identity=None, force_trace=False):
            resp = BrokerResponse()
            resp.partial_response = True
            resp.exceptions.append(
                {"errorCode": 427, "message": "ServerNotRespondedError"})
            resp.exceptions.append(
                {"errorCode": 503,
                 "message": "ServerQueryError: ServerBusyError: shed"})
            resp.retry_after_s = 2.4     # what _finish sets on all-busy
            return resp

    api = BrokerApiServer(_ShedHandler())
    out = asyncio.run(api._run_query("SELECT 1", None))
    assert out.status == 503
    assert out.headers["Retry-After"] == "3"   # ceil(2.4)
    # a partial response that recovered data (no retry_after_s) stays 200
    class _PartialHandler(_ShedHandler):
        def handle(self, pql, identity=None, force_trace=False):
            resp = BrokerResponse()
            resp.partial_response = True
            resp.exceptions.append(
                {"errorCode": 503, "message": "one replica shed"})
            return resp
    out = asyncio.run(BrokerApiServer(_PartialHandler())
                      ._run_query("SELECT 1", None))
    assert out.status == 200
