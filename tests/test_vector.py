"""TPU-native vector similarity search over mutable embeddings (ISSUE 13).

Five tiers:

1. **Type + storage** — VECTOR schema validation (dimension bounds,
   controller rejection of vector columns in index configs / primary
   keys), packed float32 forward-block build/load round-trip (v1 and v3
   container), CRC stamping, schema-evolution default columns.
2. **PQL surface** — VECTOR_SIMILARITY parse (query vector literal, k,
   metric), rejection of malformed mixes (SELECT *, LIMIT, GROUP BY),
   request serde round-trip, canonical fingerprint keying.
3. **Exactness** — host oracle, device kernel and sharded paths agree
   BIT-IDENTICALLY on (ids, scores) with WHERE filters applied, checked
   against the independent tests/oracle.py numpy top-k.
4. **Mutable path** — upserting a key's embedding makes the very next
   query rank the NEW vector and never the superseded one (the vdoc
   lane), bit-identical host vs device vs sharded, including results
   straddling the frozen/tail boundary of a consuming segment.
5. **Caching** — the CRC+vdoc-version result-cache key changes on every
   upsert invalidation, so cached top-k can never serve a dead row.
"""
import os
import tempfile

import numpy as np
import pytest

from oracle import Oracle

from pinot_tpu.common.datatype import DataType
from pinot_tpu.common.request import VECTOR_RESULT_COLUMNS
from pinot_tpu.common.schema import (MAX_VECTOR_DIMENSION, Schema, dimension,
                                     metric, vector)
from pinot_tpu.common.serde import (instance_request_from_bytes,
                                    instance_request_to_bytes,
                                    request_from_json, request_to_json)
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.common.table_config import IndexingConfig, TableConfig
from pinot_tpu.engine import QueryEngine
from pinot_tpu.pql.lexer import PqlSyntaxError
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.fingerprint import query_fingerprint
from pinot_tpu.query.reduce import BrokerReduceService
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import ImmutableSegmentLoader
from pinot_tpu.server.result_cache import segment_cache_states

DIM = 16


def vec_schema(dim=DIM, name="vectab"):
    return Schema(name, [
        dimension("shard", DataType.INT),
        metric("rid", DataType.INT),
        vector("emb", dim),
    ])


def vec_columns(n, seed=0, dim=DIM, rid_base=0):
    rng = np.random.default_rng(seed)
    return {
        "shard": rng.integers(0, 4, n).astype(np.int32),
        "rid": (np.arange(n, dtype=np.int32) + rid_base),
        "emb": rng.standard_normal((n, dim)).astype(np.float32),
    }


def build_vec_segments(base, n_segs=2, n=2048, dim=DIM, seed=3,
                       version="v1"):
    segs, cols_list = [], []
    idx = IndexingConfig()
    idx.segment_version = version
    cfg = TableConfig("vectab", indexing_config=idx)
    for s in range(n_segs):
        cols = vec_columns(n, seed=seed + s, dim=dim, rid_base=s * n)
        d = os.path.join(base, f"v{s}")
        SegmentCreator(vec_schema(dim), cfg,
                       segment_name=f"v{s}").build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
        cols_list.append(cols)
    return segs, cols_list


def pql_for(q, k=7, metric="COSINE", where="WHERE shard < 2",
            select="rid, "):
    qs = ", ".join(repr(float(x)) for x in q)
    return (f"SELECT {select}VECTOR_SIMILARITY(emb, [{qs}], {k}, "
            f"'{metric}') FROM vectab {where}").strip()


def result_rows(resp):
    assert not resp.exceptions, resp.exceptions
    return [tuple(r) for r in resp.selection_results.results]


# ---------------------------------------------------------------------------
# tier 1: type + storage
# ---------------------------------------------------------------------------


def test_schema_validation_bounds():
    vec_schema().validate()                      # fine
    with pytest.raises(ValueError, match="dimension"):
        Schema("s", [vector("e", 0)]).validate()
    with pytest.raises(ValueError, match="dimension"):
        Schema("s", [vector("e", MAX_VECTOR_DIMENSION + 1)]).validate()
    from pinot_tpu.common.schema import FieldSpec, FieldType
    with pytest.raises(ValueError, match="single-value"):
        Schema("s", [FieldSpec("e", DataType.VECTOR, FieldType.DIMENSION,
                               single_value=False,
                               vector_dimension=4)]).validate()
    with pytest.raises(ValueError, match="vectorDimension"):
        Schema("s", [FieldSpec("x", DataType.INT,
                               vector_dimension=4)]).validate()


def test_schema_json_roundtrip_keeps_dimension():
    sch = vec_schema(dim=12)
    again = Schema.from_json_str(sch.to_json_str())
    f = again.field("emb")
    assert f.data_type == DataType.VECTOR
    assert f.vector_dimension == 12


def test_fieldspec_convert_validates_dimension():
    sch = vec_schema(dim=4)
    f = sch.field("emb")
    assert np.array_equal(f.convert(None), np.zeros(4, np.float32))
    assert f.convert([1, 2, 3, 4]).dtype == np.float32
    with pytest.raises(ValueError, match="4-dimension"):
        f.convert([1.0, 2.0])


def test_controller_rejects_bad_vector_configs(tmp_path):
    from pinot_tpu.controller.manager import InvalidTableConfigError
    from pinot_tpu.tools.cluster import EmbeddedCluster
    cluster = EmbeddedCluster(str(tmp_path), num_servers=1)
    try:
        with pytest.raises(InvalidTableConfigError, match="dimension"):
            cluster.add_schema(Schema("bad", [vector("e", 0)]))
        cluster.add_schema(vec_schema())
        bad = TableConfig("vectab", indexing_config=IndexingConfig(
            inverted_index_columns=["emb"]))
        with pytest.raises(InvalidTableConfigError, match="VECTOR"):
            cluster.add_table(bad)
        bad2 = TableConfig("vectab", indexing_config=IndexingConfig(
            no_dictionary_columns=["emb"]))
        with pytest.raises(InvalidTableConfigError, match="VECTOR"):
            cluster.add_table(bad2)
        ok = TableConfig("vectab")
        cluster.add_table(ok)
    finally:
        cluster.stop()


@pytest.mark.parametrize("version", ["v1", "v3"])
def test_build_load_roundtrip(tmp_path, version):
    segs, cols_list = build_vec_segments(str(tmp_path), n_segs=1, n=512,
                                         version=version)
    seg = segs[0]
    assert seg.metadata.crc
    cm = seg.data_source("emb").metadata
    assert cm.vector_dimension == DIM and not cm.has_dictionary
    assert np.array_equal(seg.data_source("emb").vec_values,
                          cols_list[0]["emb"])
    op = seg.data_source("emb").host_operand("vec")
    assert op.shape[0] % 8192 == 0 and op.dtype == np.float32
    assert np.array_equal(op[:512, :DIM], cols_list[0]["emb"])
    assert op[512:].sum() == 0


def test_dimension_mismatch_rejected_at_build(tmp_path):
    cols = vec_columns(64)
    cols["emb"] = cols["emb"][:, :8]             # wrong width
    with pytest.raises(ValueError, match="dimension"):
        SegmentCreator(vec_schema(), segment_name="bad").build(
            cols, str(tmp_path / "bad"))


def test_schema_evolution_default_vector_column(tmp_path):
    # segment built WITHOUT emb; loading with the evolved schema
    # synthesizes zero embeddings
    old = Schema("vectab", [dimension("shard", DataType.INT),
                            metric("rid", DataType.INT)])
    cols = vec_columns(128)
    SegmentCreator(old, segment_name="old").build(
        {"shard": cols["shard"], "rid": cols["rid"]}, str(tmp_path / "old"))
    seg = ImmutableSegmentLoader.load(str(tmp_path / "old"),
                                      schema=vec_schema())
    vv = seg.data_source("emb").vec_values
    assert vv.shape == (128, DIM) and vv.sum() == 0


# ---------------------------------------------------------------------------
# tier 2: PQL surface + serde + fingerprint
# ---------------------------------------------------------------------------


def test_pql_parse_vector_similarity():
    req = compile_pql("SELECT rid, VECTOR_SIMILARITY(emb, "
                      "[1.0, -2, 3e-1], 5, 'MIPS') FROM vectab "
                      "WHERE shard = 1")
    assert req.vector is not None
    assert req.vector.column == "emb"
    assert req.vector.query == [1.0, -2.0, 0.3]
    assert req.vector.k == 5 and req.vector.metric == "MIPS"
    assert req.selection.columns == ["rid"]
    assert req.selection.size == 5
    assert req.filter is not None
    # default metric
    req2 = compile_pql("SELECT VECTOR_SIMILARITY(emb, [1], 3) FROM t")
    assert req2.vector.metric == "COSINE" and req2.selection.columns == []


@pytest.mark.parametrize("bad", [
    "SELECT VECTOR_SIMILARITY(emb, [], 3) FROM t",
    "SELECT VECTOR_SIMILARITY(emb, [1.0], 3, 'L2') FROM t",
    "SELECT *, VECTOR_SIMILARITY(emb, [1.0], 3) FROM t",
    "SELECT VECTOR_SIMILARITY(emb, [1.0], 3) FROM t LIMIT 5",
    "SELECT VECTOR_SIMILARITY(emb, [1.0], 3) FROM t GROUP BY shard",
    "SELECT VECTOR_SIMILARITY(emb, [1.0], 3) FROM t ORDER BY rid",
    "SELECT COUNT(*), VECTOR_SIMILARITY(emb, [1.0], 3) FROM t",
    "SELECT VECTOR_SIMILARITY(emb, [1.0], 3), "
    "VECTOR_SIMILARITY(emb, [2.0], 3) FROM t",
])
def test_pql_rejects_malformed_vector_queries(bad):
    with pytest.raises(PqlSyntaxError):
        compile_pql(bad)


def test_request_serde_roundtrip_vector():
    req = compile_pql("SELECT rid, VECTOR_SIMILARITY(emb, [0.5, 1.5], 9, "
                      "'DOT') FROM vectab WHERE shard = 2")
    again = request_from_json(request_to_json(req))
    assert again.vector == req.vector
    assert again.selection == req.selection
    wire = instance_request_from_bytes(instance_request_to_bytes(
        InstanceRequest(request_id=7, query=req)))
    assert wire.query.vector == req.vector


def test_fingerprint_keys_vector_clause():
    base = "SELECT VECTOR_SIMILARITY(emb, [1.0, 2.0], 5) FROM t"
    fp = query_fingerprint(compile_pql(base))
    # same query → same fingerprint
    assert fp == query_fingerprint(compile_pql(base))
    # different query vector / k / metric → different fingerprints
    assert fp != query_fingerprint(compile_pql(
        "SELECT VECTOR_SIMILARITY(emb, [1.0, 2.5], 5) FROM t"))
    assert fp != query_fingerprint(compile_pql(
        "SELECT VECTOR_SIMILARITY(emb, [1.0, 2.0], 6) FROM t"))
    assert fp != query_fingerprint(compile_pql(
        "SELECT VECTOR_SIMILARITY(emb, [1.0, 2.0], 5, 'DOT') FROM t"))


# ---------------------------------------------------------------------------
# tier 3: exactness — host vs device vs sharded vs independent oracle
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def vec_setup():
    base = tempfile.mkdtemp()
    segs, cols_list = build_vec_segments(base, n_segs=2, n=2048)
    rng = np.random.default_rng(99)
    q = rng.standard_normal(DIM).astype(np.float32)
    return segs, cols_list, q


@pytest.mark.parametrize("metric", ["COSINE", "DOT"])
def test_filtered_topk_bit_identical_and_oracle(vec_setup, metric):
    from pinot_tpu.parallel import make_mesh
    segs, cols_list, q = vec_setup
    pql = pql_for(q, k=9, metric=metric)
    host = QueryEngine(segs, use_device=False)
    dev = QueryEngine(segs)
    sh = QueryEngine(segs, mesh=make_mesh())
    rh = result_rows(host.query(pql))
    rd = result_rows(dev.query(pql))
    rs = result_rows(sh.query(pql))
    assert rh == rd == rs
    assert len(rh) == 9
    # independent oracle: per-segment top-k merged by (score, seg, doc)
    cand = []
    for s, cols in enumerate(cols_list):
        o = Oracle(cols)
        m = o.mask(lambda r: r["shard"] < 2)
        for doc, score in o.vector_topk("emb", q, 9, m,
                                        metric=metric.lower()):
            cand.append((-score, f"v{s}", doc,
                         int(cols["rid"][doc]), score))
    cand.sort()
    exp = [(rid, doc, name, score)
           for _ns, name, doc, rid, score in cand[:9]]
    assert rh == exp
    cols = host.query(pql).selection_results.columns
    assert cols == ["rid"] + list(VECTOR_RESULT_COLUMNS)


def test_empty_filter_returns_no_rows(vec_setup):
    segs, _cols, q = vec_setup
    pql = pql_for(q, where="WHERE shard = 999")
    for engine in (QueryEngine(segs, use_device=False), QueryEngine(segs)):
        assert result_rows(engine.query(pql)) == []


def test_predicate_over_vector_column_rejected(vec_setup):
    segs, _cols, q = vec_setup
    pql = pql_for(q, where="WHERE emb = 1")
    with pytest.raises(ValueError, match="VECTOR"):
        QueryEngine(segs).query(pql)


def test_dimension_mismatch_query_errors(vec_setup):
    segs, _cols, _q = vec_setup
    with pytest.raises(ValueError, match="dimension"):
        QueryEngine(segs).query(
            "SELECT VECTOR_SIMILARITY(emb, [1.0, 2.0], 3) FROM vectab")


def test_zero_query_vector_cosine_rejected(vec_setup):
    segs, _cols, _q = vec_setup
    zeros = ", ".join(["0.0"] * DIM)
    with pytest.raises(ValueError, match="non-zero"):
        QueryEngine(segs).query(
            f"SELECT VECTOR_SIMILARITY(emb, [{zeros}], 3) FROM vectab")
    # DOT accepts a zero query (all scores 0.0, docid order)
    resp2 = QueryEngine(segs).query(
        f"SELECT VECTOR_SIMILARITY(emb, [{zeros}], 3, 'DOT') FROM vectab")
    rows = result_rows(resp2)
    assert [r[-1] for r in rows] == [0.0, 0.0, 0.0]
    assert [r[0] for r in rows] == [0, 1, 2]


def test_k_larger_than_matches_returns_all(vec_setup):
    segs, cols_list, q = vec_setup
    pql = pql_for(q, k=5000, where="WHERE shard = 3")
    n_exp = sum(int((c["shard"] == 3).sum()) for c in cols_list)
    # k caps at the match count (and at the merge trim)
    rh = result_rows(QueryEngine(segs, use_device=False).query(pql))
    rd = result_rows(QueryEngine(segs).query(pql))
    assert rh == rd
    assert len(rh) == min(n_exp, 5000)


def test_vector_column_selectable_on_host_path(vec_setup):
    segs, cols_list, _q = vec_setup
    resp = QueryEngine(segs, use_device=False).query(
        "SELECT emb FROM vectab LIMIT 2")
    rows = result_rows(resp)
    assert len(rows) == 2 and len(rows[0][0]) == DIM


# ---------------------------------------------------------------------------
# tier 4: the mutable-path invariant (upserted embeddings + freshness)
# ---------------------------------------------------------------------------


def _mutable_upsert_segment(n_rows=9000, dim=DIM):
    """Consuming segment with an upsert bitmap, big enough that the
    device path serves a frozen snapshot with a live host tail."""
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
    from pinot_tpu.realtime.upsert import ValidDocIds
    impl = MutableSegmentImpl(vec_schema(dim), TableConfig("vectab"),
                              "vectab__0__0")
    impl.valid_doc_ids = ValidDocIds()
    rng = np.random.default_rng(17)
    rows = [{"shard": int(i % 4), "rid": i,
             "emb": [float(x) for x in
                     rng.standard_normal(dim).astype(np.float32)]}
            for i in range(n_rows)]
    impl.index_rows(rows)
    return impl, rng


def _run(executor, req, segs):
    blk = executor.execute(req, segs)
    resp = BrokerReduceService().reduce(req, [blk])
    return result_rows(resp)


def test_upsert_makes_next_query_rank_new_vector():
    impl, rng = _mutable_upsert_segment()
    q = rng.standard_normal(DIM).astype(np.float32)
    unit = (q / np.linalg.norm(q)).astype(np.float32)
    req = compile_pql(pql_for(q, k=5, where=""))
    dev = ServerQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    r0_dev, r0_host = _run(dev, req, [impl]), _run(host, req, [impl])
    assert r0_dev == r0_host and len(r0_dev) == 5
    assert impl._frozen is not None      # device path took a snapshot

    # upsert doc 10's key with a perfect-match embedding; the OLD row
    # (a frozen-prefix row) must never rank again, the NEW row (a tail
    # row) must rank first on the IMMEDIATELY following query
    new_doc = impl.num_docs
    impl.index_rows([{"shard": 0, "rid": 555_000,
                      "emb": [float(x) for x in unit]}])
    impl.valid_doc_ids.invalidate(10)
    r1_dev, r1_host = _run(dev, req, [impl]), _run(host, req, [impl])
    assert r1_dev == r1_host
    assert r1_dev[0][:2] == (555_000, new_doc)
    assert all(row[1] != 10 for row in r1_dev)

    # supersede the new row too — the immediately following query must
    # drop it (never ranks a dead row, even the previous winner)
    impl.index_rows([{"shard": 0, "rid": 555_001,
                      "emb": [float(x) for x in unit]}])
    impl.valid_doc_ids.invalidate(new_doc)
    r2_dev, r2_host = _run(dev, req, [impl]), _run(host, req, [impl])
    assert r2_dev == r2_host
    assert r2_dev[0][0] == 555_001
    assert all(row[1] != new_doc for row in r2_dev)


def test_straddling_frozen_tail_boundary_bit_identical():
    impl, rng = _mutable_upsert_segment(n_rows=8300)
    # frozen covers [0, 8192); tail [8192, 8300) — craft a query whose
    # top-k straddles: plant strong matches on both sides
    q = rng.standard_normal(DIM).astype(np.float32)
    unit = (q / np.linalg.norm(q)).astype(np.float32)
    for doc, scale in ((100, 0.99), (8200, 0.98), (50, 0.97)):
        impl._sources["emb"]._vec._arr[doc] = unit * scale + \
            rng.standard_normal(DIM).astype(np.float32) * 1e-3
    req = compile_pql(pql_for(q, k=4, where=""))
    dev = ServerQueryExecutor()
    host = ServerQueryExecutor(use_device=False)
    rd, rh = _run(dev, req, [impl]), _run(host, req, [impl])
    assert rd == rh
    docs = [row[1] for row in rd]
    assert 100 in docs and 8200 in docs     # both sides of the boundary
    # ids are GLOBAL docids under the base segment name on both paths
    assert all(row[2] == "vectab__0__0" for row in rd)


def test_committed_upsert_masking_sharded(tmp_path):
    """Sealed segments with validDocIds invalidations: dead rows never
    rank on any path, and all three paths stay bit-identical."""
    from pinot_tpu.parallel import make_mesh
    from pinot_tpu.realtime.upsert import ValidDocIds
    segs, cols_list = build_vec_segments(str(tmp_path), n_segs=2, n=2048)
    rng = np.random.default_rng(5)
    q = rng.standard_normal(DIM).astype(np.float32)
    pql = pql_for(q, k=6, where="")
    base = result_rows(QueryEngine(segs, use_device=False).query(pql))
    # kill the current top hit on its segment
    top_rid, top_doc, top_seg, _s = base[0]
    seg_idx = int(top_seg[1:])
    vd = ValidDocIds()
    vd.invalidate(top_doc)
    segs[seg_idx].valid_doc_ids = vd
    rh = result_rows(QueryEngine(segs, use_device=False).query(pql))
    rd = result_rows(QueryEngine(segs).query(pql))
    rs = result_rows(QueryEngine(segs, mesh=make_mesh()).query(pql))
    assert rh == rd == rs
    assert all(not (row[1] == top_doc and row[2] == top_seg)
               for row in rh)
    assert rh[0] == base[1]      # ranking shifts up by exactly one


# ---------------------------------------------------------------------------
# tier 5: result-cache exactness (CRC + vdoc version keying)
# ---------------------------------------------------------------------------


def test_cache_key_changes_on_vdoc_bump(tmp_path):
    from pinot_tpu.realtime.upsert import ValidDocIds
    segs, _cols = build_vec_segments(str(tmp_path), n_segs=1, n=256)
    seg = segs[0]
    s0 = segment_cache_states(segs)
    assert s0 is not None
    vd = ValidDocIds()
    seg.valid_doc_ids = vd
    s1 = segment_cache_states(segs)
    vd.invalidate(3)
    s2 = segment_cache_states(segs)
    assert s0 != s1 != s2 and s0 != s2


def test_cached_topk_invalidates_on_upsert(tmp_path):
    """End-to-end through the server result cache: identical queries
    hit; an upsert invalidation changes the key so the stale top-k is
    never served."""
    from pinot_tpu.realtime.upsert import ValidDocIds
    from pinot_tpu.server.result_cache import ServerResultCache
    segs, _cols = build_vec_segments(str(tmp_path), n_segs=1, n=256)
    seg = segs[0]
    rng = np.random.default_rng(11)
    q = rng.standard_normal(DIM).astype(np.float32)
    req = compile_pql(pql_for(q, k=3, where=""))
    fp = query_fingerprint(req)
    cache = ServerResultCache()
    key0 = ServerResultCache.key("vectab", fp, segment_cache_states(segs))
    cache.put(key0, b"payload-0")
    assert cache.get(key0) == b"payload-0"
    vd = ValidDocIds()
    seg.valid_doc_ids = vd
    vd.invalidate(0)
    key1 = ServerResultCache.key("vectab", fp, segment_cache_states(segs))
    assert key1 != key0
    assert cache.get(key1) is None       # post-upsert key misses


# ---------------------------------------------------------------------------
# converter: consuming vector columns survive the commit build
# ---------------------------------------------------------------------------


def test_realtime_converter_preserves_vectors(tmp_path):
    from pinot_tpu.realtime.converter import convert
    impl, _rng = _mutable_upsert_segment(n_rows=200)
    before = np.array(impl._sources["emb"]._vec.snapshot(200), copy=True)
    meta = convert(impl, str(tmp_path / "committed"), "vectab_c0")
    seg = ImmutableSegmentLoader.load(str(tmp_path / "committed"))
    assert meta.crc and seg.num_docs == 200
    assert np.array_equal(seg.data_source("emb").vec_values, before)
