"""Controller-side task generation (parity: PinotTaskManager +
TaskGeneratorRegistry + ConvertToRawIndexTaskGenerator).

A periodic task walks every table's `task_configs`; each registered
generator emits PinotTaskConfigs for work not yet queued (dedup against
open tasks per segment).
"""
from __future__ import annotations

from typing import Dict, List

from pinot_tpu.minion.executors import (CONVERT_TO_RAW_TASK, MERGE_ROLLUP_TASK,
                                        PURGE_TASK)
from pinot_tpu.minion.tasks import (COLUMNS_TO_CONVERT_KEY, SEGMENT_NAME_KEY,
                                    TABLE_NAME_KEY, PinotTaskConfig,
                                    TaskQueue)


class PinotTaskGenerator:
    task_type: str = ""

    def generate(self, table: str, table_config, manager,
                 queue: TaskQueue) -> List[PinotTaskConfig]:
        raise NotImplementedError


class ConvertToRawIndexTaskGenerator(PinotTaskGenerator):
    """One task per segment that still has dictionaries on the configured
    columns (parity: ConvertToRawIndexTaskGenerator)."""

    task_type = CONVERT_TO_RAW_TASK

    def generate(self, table, table_config, manager, queue):
        cfg = table_config.task_configs.get(self.task_type, {})
        columns = cfg.get(COLUMNS_TO_CONVERT_KEY, "")
        out = []
        for seg in manager.segment_names(table):
            if queue.tasks_for_segment(self.task_type, table, seg):
                continue
            meta = manager.segment_metadata(table, seg) or {}
            if meta.get("customMap", {}).get(f"{self.task_type}.time"):
                continue                      # already converted
            out.append(PinotTaskConfig(self.task_type, {
                TABLE_NAME_KEY: table, SEGMENT_NAME_KEY: seg,
                COLUMNS_TO_CONVERT_KEY: columns}))
        return out


class PurgeTaskGenerator(PinotTaskGenerator):
    task_type = PURGE_TASK

    def generate(self, table, table_config, manager, queue):
        out = []
        for seg in manager.segment_names(table):
            if queue.tasks_for_segment(self.task_type, table, seg):
                continue
            out.append(PinotTaskConfig(self.task_type, {
                TABLE_NAME_KEY: table, SEGMENT_NAME_KEY: seg}))
        return out


class PinotTaskManager:
    """Walks tables and schedules generator output onto the queue."""

    def __init__(self, manager):
        self.manager = manager
        self.queue = TaskQueue(manager.store)
        self._generators: Dict[str, PinotTaskGenerator] = {}
        for g in (ConvertToRawIndexTaskGenerator(), PurgeTaskGenerator()):
            self.register(g)

    def register(self, gen: PinotTaskGenerator) -> None:
        self._generators[gen.task_type] = gen

    def schedule_tasks(self) -> List[str]:
        scheduled = []
        for table in self.manager.table_names():
            config = self.manager.get_table_config(table)
            if config is None:
                continue
            for ttype in config.task_configs:
                gen = self._generators.get(ttype)
                if gen is None:
                    continue
                for task in gen.generate(table, config, self.manager,
                                         self.queue):
                    scheduled.append(self.queue.submit(task))
        return scheduled
