"""Query schedulers: FCFS and token-bucket priority.

Parity: pinot-core/.../core/query/scheduler/ — QuerySchedulerFactory
(algorithms "fcfs" | "tokenbucket", QuerySchedulerFactory.java:40-68),
PriorityScheduler + TokenSchedulerGroup (token bucket ≈ CPU-ms accounting
with linear decay, TokenSchedulerGroup.java:31-56), bounded per-group
concurrency. Execution happens on a thread pool; the device serializes
kernels anyway, so scheduling decides ORDER and fairness, exactly the
role it plays in the reference.
"""
from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional


class QueryScheduler:
    """submit(group, fn) -> Future; subclasses order execution."""

    def __init__(self, num_workers: int = 4):
        self._pool = ThreadPoolExecutor(max_workers=num_workers)
        self.num_workers = num_workers

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class FCFSQueryScheduler(QueryScheduler):
    """First-come-first-served (the reference default)."""

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        return self._pool.submit(fn)


class TokenBucketScheduler(QueryScheduler):
    """Priority scheduling by per-group token accounting.

    Each group (table) accrues tokens linearly over time and spends
    wall-clock-ms tokens when its queries run; the pending query from the
    group with the most tokens runs first. Mirrors TokenSchedulerGroup's
    `tokens = tokens*decay + lifetime_ms*num_workers - used_ms`.
    """

    TOKEN_LIFETIME_MS = 100.0

    def __init__(self, num_workers: int = 4):
        super().__init__(num_workers)
        self._groups: Dict[str, float] = {}
        self._last_refresh: Dict[str, float] = {}
        self._queue: list = []            # (-tokens, seq, group, fn, future)
        self._seq = 0
        self._lock = threading.Lock()

    def _refresh_tokens(self, group: str) -> float:
        now = time.monotonic()
        last = self._last_refresh.get(group, now)
        tokens = self._groups.get(group, 0.0)
        tokens = tokens * 0.5 + (now - last) * 1e3 * self.num_workers
        tokens = min(tokens, self.TOKEN_LIFETIME_MS * self.num_workers * 2)
        self._groups[group] = tokens
        self._last_refresh[group] = now
        return tokens

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        future: Future = Future()
        with self._lock:
            tokens = self._refresh_tokens(group)
            heapq.heappush(self._queue,
                           (-tokens, self._seq, group, fn, future))
            self._seq += 1
        self._pool.submit(self._drain)
        return future

    def _drain(self) -> None:
        with self._lock:
            if not self._queue:
                return
            _, _, group, fn, future = heapq.heappop(self._queue)
        if not future.set_running_or_notify_cancel():
            return
        t0 = time.monotonic()
        try:
            future.set_result(fn())
        except BaseException as e:  # noqa: BLE001 — future carries it
            future.set_exception(e)
        finally:
            used_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self._groups[group] = self._groups.get(group, 0.0) - used_ms


def make_scheduler(algorithm: str = "fcfs", num_workers: int = 4
                   ) -> QueryScheduler:
    """Parity: QuerySchedulerFactory.create (falls back to FCFS)."""
    if algorithm == "tokenbucket":
        return TokenBucketScheduler(num_workers)
    if algorithm == "bounded_fcfs":
        return BoundedFCFSScheduler(num_workers)
    return FCFSQueryScheduler(num_workers)


class SchedulerOutOfCapacityError(Exception):
    """Parity: OutOfCapacityException — bounded queue rejected the query."""


class ResourceLimitPolicy:
    """Per-group concurrency/queue bounds.

    Parity: core/query/scheduler/resources/ResourceLimitPolicy — a group
    (table) may use at most `table_threads_hard_limit` workers at once,
    and at most `max_pending_per_group` queries may wait.
    """

    def __init__(self, num_workers: int,
                 max_threads_per_group_pct: float = 0.5,
                 max_pending_per_group: int = 64):
        self.table_threads_hard_limit = max(
            1, int(num_workers * max_threads_per_group_pct))
        self.max_pending_per_group = max_pending_per_group


class BoundedFCFSScheduler(QueryScheduler):
    """Per-group FCFS with bounded per-group resources.

    Parity: BoundedFCFSScheduler + PolicyBasedResourceManager — FCFS
    order across groups (oldest pending first), but a group already at
    its thread limit is skipped, and a group with a full pending queue
    rejects new queries instead of growing without bound.
    """

    def __init__(self, num_workers: int = 4,
                 policy: Optional[ResourceLimitPolicy] = None):
        super().__init__(num_workers)
        self.policy = policy or ResourceLimitPolicy(num_workers)
        self._pending: Dict[str, list] = {}
        self._running: Dict[str, int] = {}
        self._order: list = []            # (seq, group) FCFS across groups
        self._seq = 0
        self._lock = threading.Lock()

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        future: Future = Future()
        with self._lock:
            q = self._pending.setdefault(group, [])
            if len(q) >= self.policy.max_pending_per_group:
                future.set_exception(SchedulerOutOfCapacityError(
                    f"group {group}: {len(q)} pending >= "
                    f"{self.policy.max_pending_per_group}"))
                return future
            q.append((fn, future))
            heapq.heappush(self._order, (self._seq, group))
            self._seq += 1
        self._pool.submit(self._drain)
        return future

    def _next(self):
        """Oldest pending entry whose group is under its thread limit."""
        skipped = []
        try:
            while self._order:
                seq, group = heapq.heappop(self._order)
                if not self._pending.get(group):
                    continue            # stale order entry
                if self._running.get(group, 0) >= \
                        self.policy.table_threads_hard_limit:
                    skipped.append((seq, group))
                    continue
                fn, future = self._pending[group].pop(0)
                self._running[group] = self._running.get(group, 0) + 1
                return group, fn, future
            return None
        finally:
            for item in skipped:
                heapq.heappush(self._order, item)

    def _drain(self) -> None:
        with self._lock:
            item = self._next()
        if item is None:
            return
        group, fn, future = item
        try:
            if future.set_running_or_notify_cancel():
                try:
                    future.set_result(fn())
                except BaseException as e:  # noqa: BLE001
                    future.set_exception(e)
        finally:
            with self._lock:
                self._running[group] -= 1
                more = any(self._pending.values())
            if more:
                self._pool.submit(self._drain)
