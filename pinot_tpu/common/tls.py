"""TLS configuration for the HTTP planes.

Parity: pinot-common/.../segment/fetcher/HttpsSegmentFetcher.java +
ClientSSLContextGenerator — the reference configures a client SSLContext
from PEM material (server CA cert, optional client cert/key for mTLS) and
an `enable-server-verification` flag; the controller/server side terminates
TLS at the embedded HTTP layer. Here both directions are driven by one
TlsConfig mapped onto the stdlib `ssl` module, and the asyncio HTTP server
(transport/http.py) passes the server context straight into
asyncio.start_server(ssl=...).
"""
from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TlsConfig:
    """PEM file paths (None = feature off for that direction).

    server_cert/server_key: the listening side's certificate chain + key.
    ca_cert: trust anchor for verifying the PEER (client side: the server
    CA — HttpsSegmentFetcher's `server.ca-cert`; server side: client CA
    for mTLS).
    client_cert/client_key: client certificate for mTLS.
    verify_server: HttpsSegmentFetcher's enable-server-verification — when
    False the client skips chain + hostname checks (the reference logs a
    warning and disables verification; same trade here).
    """
    server_cert: Optional[str] = None
    server_key: Optional[str] = None
    ca_cert: Optional[str] = None
    client_cert: Optional[str] = None
    client_key: Optional[str] = None
    verify_server: bool = True
    require_client_cert: bool = False

    # -- context builders --------------------------------------------------
    def server_context(self) -> Optional[ssl.SSLContext]:
        if not (self.server_cert and self.server_key):
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.server_cert, self.server_key)
        if self.require_client_cert:
            ctx.verify_mode = ssl.CERT_REQUIRED
            if self.ca_cert:
                ctx.load_verify_locations(self.ca_cert)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        ctx = ssl.create_default_context()
        if not self.verify_server:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_cert:
            ctx.load_verify_locations(self.ca_cert)
        if self.client_cert and self.client_key:
            ctx.load_cert_chain(self.client_cert, self.client_key)
        return ctx

    def to_json(self) -> dict:
        return {"serverCert": self.server_cert, "serverKey": self.server_key,
                "caCert": self.ca_cert, "clientCert": self.client_cert,
                "clientKey": self.client_key,
                "verifyServer": self.verify_server,
                "requireClientCert": self.require_client_cert}

    @classmethod
    def from_json(cls, d: dict) -> "TlsConfig":
        return cls(server_cert=d.get("serverCert"),
                   server_key=d.get("serverKey"),
                   ca_cert=d.get("caCert"),
                   client_cert=d.get("clientCert"),
                   client_key=d.get("clientKey"),
                   verify_server=d.get("verifyServer", True),
                   require_client_cert=d.get("requireClientCert", False))


def generate_self_signed(dir_path: str, cn: str = "localhost"
                         ) -> TlsConfig:
    """Self-signed cert/key pair via the openssl CLI (test/dev helper —
    production deployments bring their own PEMs)."""
    import os
    import subprocess
    cert = os.path.join(dir_path, "server.crt")
    key = os.path.join(dir_path, "server.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2", "-subj", f"/CN={cn}",
         "-addext", f"subjectAltName=DNS:{cn},IP:127.0.0.1"],
        check=True, capture_output=True)
    return TlsConfig(server_cert=cert, server_key=key, ca_cert=cert)
