"""PropertyStore: hierarchical JSON records with watches.

Parity: the ZooKeeper property store as Pinot uses it through Helix
(ZKMetadataProvider paths: /CONFIGS/TABLE, /SEGMENTS/<table>/<segment>,
ideal states, external views). In-process, thread-safe, watch callbacks on
path prefixes — the single source of truth for cluster state, exactly the
role ZK plays; a networked implementation can replace it behind the same
interface.
"""
from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional

Watcher = Callable[[str, Optional[dict]], None]


class PropertyStore:
    def __init__(self):
        self._data: Dict[str, dict] = {}
        self._watchers: List[tuple] = []        # (prefix, callback)
        self._lock = threading.RLock()
        # serializes external-view composition (state_machine.compose_view
        # read-compute-write cycles from coordinator + ViewComposer threads)
        self.compose_lock = threading.Lock()

    # -- records -----------------------------------------------------------
    def set(self, path: str, record: dict, ephemeral: bool = False) -> None:
        """`ephemeral` is accepted for interface parity with
        RemotePropertyStore; the in-process store has no sessions, so it
        is ignored."""
        with self._lock:
            self._data[path] = json.loads(json.dumps(record))
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        for cb in watchers:
            cb(path, record)

    def get(self, path: str) -> Optional[dict]:
        with self._lock:
            rec = self._data.get(path)
            return json.loads(json.dumps(rec)) if rec is not None else None

    def update(self, path: str, fn: Callable[[Optional[dict]], dict]
               ) -> dict:
        """Atomic read-modify-write (single-writer ideal-state updates)."""
        with self._lock:
            rec = fn(self.get(path))
            self._data[path] = json.loads(json.dumps(rec))
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        for cb in watchers:
            cb(path, rec)
        return rec

    def cas(self, path: str, expected: Optional[dict],
            record: dict) -> bool:
        """Compare-and-set: apply only if the current record equals
        `expected` (None = path absent). The remote client's update()
        builds its read-modify-write loop on this."""
        with self._lock:
            if self._data.get(path) != expected:
                return False
            self._data[path] = json.loads(json.dumps(record))
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)]
        for cb in watchers:
            cb(path, record)
        return True

    def remove(self, path: str) -> bool:
        with self._lock:
            existed = self._data.pop(path, None) is not None
            watchers = [cb for p, cb in self._watchers
                        if path.startswith(p)] if existed else []
        for cb in watchers:
            cb(path, None)
        return existed

    def children(self, prefix: str) -> List[str]:
        """Paths directly under prefix (like ZK getChildren)."""
        if not prefix.endswith("/"):
            prefix += "/"
        with self._lock:
            out = set()
            for p in self._data:
                if p.startswith(prefix):
                    out.add(p[len(prefix):].split("/", 1)[0])
            return sorted(out)

    def list_paths(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(p for p in self._data if p.startswith(prefix))

    # -- watches -----------------------------------------------------------
    def watch(self, prefix: str, callback: Watcher) -> None:
        with self._lock:
            self._watchers.append((prefix, callback))

    def unwatch(self, callback: Watcher) -> None:
        with self._lock:
            self._watchers = [(p, cb) for p, cb in self._watchers
                              if cb is not callback]
