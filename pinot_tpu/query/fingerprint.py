"""Canonical query fingerprint: the result-cache key.

Two requests share a fingerprint iff they MUST produce identical
results over identical data. The fingerprint therefore hashes a
canonicalized form of the compiled request:

- execution-irrelevant options are dropped (trace, timeoutMs — they
  shape metadata and deadlines, never result values;
  minConsumingFreshnessTimeMs is enforced per-query at cache-GET time
  as a max-age bound, so queries that differ only in their freshness
  bound share one entry);
- IN/NOT_IN value lists are sorted (set semantics);
- AND/OR children are sorted by their canonical encoding (conjunction
  and disjunction are commutative over result values).

Canonicalization only ever MERGES equivalent queries — a query pair
with different results always hashes differently, so a cache keyed on
the fingerprint (plus segment CRCs) is exact by construction; an
imperfect canonicalization costs hit rate, never correctness.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.common.serde import filter_to_json, request_to_json

_COMMUTATIVE = (FilterOperator.AND, FilterOperator.OR)
_SET_VALUED = (FilterOperator.IN, FilterOperator.NOT_IN)


def _canonical_filter(node: Optional[FilterQueryTree]):
    if node is None:
        return None
    d = filter_to_json(node)
    if node.operator in _COMMUTATIVE:
        children = [_canonical_filter(c) for c in node.children]
        children.sort(key=lambda c: json.dumps(c, sort_keys=True))
        d["children"] = children
    elif node.operator in _SET_VALUED:
        d["vals"] = sorted(node.values)
    return d


def canonical_request_dict(request: BrokerRequest) -> dict:
    d = request_to_json(request)
    d["filter"] = _canonical_filter(request.filter)
    opts = d.get("options") or {}
    # execution-shaping keys never change result values: "workload" is
    # a scheduling/quota tag (two tenants issuing the same query must
    # share one cache entry), trace/timeoutMs shape metadata and
    # deadlines (the parser mirrors them into options.options too)
    drop = {"workload", "trace", "timeoutMs",
            "minConsumingFreshnessTimeMs"}
    d["options"] = {"options": dict(sorted(
        (k, v) for k, v in (opts.get("options") or {}).items()
        if k not in drop))}
    return d


def query_fingerprint(request: BrokerRequest) -> str:
    """Stable hex digest of the canonicalized request (table included)."""
    payload = json.dumps(canonical_request_dict(request), sort_keys=True,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:32]
