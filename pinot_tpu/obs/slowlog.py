"""Sampling JSONL slow-query log.

Parity: the reference broker logs every query's summary line
(BaseBrokerRequestHandler's requestId/table/timeMs log) and operators
grep for the slow ones; here the broker writes a structured JSONL
record for queries over a latency threshold, with deterministic
sampling so a pathological workload can't turn the log into the
bottleneck it is diagnosing.

Config (constructor args, env-overridable via `from_env`):

- ``PINOT_TPU_SLOWLOG``          — log file path (enables the log)
- ``PINOT_TPU_SLOWLOG_MS``       — threshold, default 500 ms
- ``PINOT_TPU_SLOWLOG_SAMPLE``   — fraction of over-threshold queries
  kept, default 1.0; sampling is counter-based (`floor(n*rate)`
  crossings), so it is deterministic and exactly rate-proportional.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Optional


class SlowQueryLog:
    def __init__(self, path: str, threshold_ms: float = 500.0,
                 sample_rate: float = 1.0):
        self.path = path
        self.threshold_ms = float(threshold_ms)
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self._lock = threading.Lock()       # sampling counters only
        self._io_lock = threading.Lock()    # the append handle
        self._fh = None                     # opened lazily, kept open
        self._seen = 0          # queries over threshold (sampling input)
        self._logged = 0

    @classmethod
    def from_env(cls) -> Optional["SlowQueryLog"]:
        path = os.environ.get("PINOT_TPU_SLOWLOG")
        if not path:
            return None
        return cls(path,
                   threshold_ms=float(
                       os.environ.get("PINOT_TPU_SLOWLOG_MS", "500")),
                   sample_rate=float(
                       os.environ.get("PINOT_TPU_SLOWLOG_SAMPLE", "1")))

    def _sampled(self) -> bool:
        """Counter-based sampling: keep the n-th slow query iff
        floor(n*rate) > floor((n-1)*rate) — deterministic, and over any
        window the kept fraction is exactly the configured rate."""
        self._seen += 1
        n = self._seen
        return math.floor(n * self.sample_rate) > \
            math.floor((n - 1) * self.sample_rate)

    def maybe_log(self, time_used_ms: float, entry: dict) -> bool:
        """Append `entry` when the query is slow AND sampled. Returns
        whether a record was written.

        The sampling decision and the write hold different locks: a
        slow-query storm (exactly what this log diagnoses) must not
        serialize every caller thread's _finish on disk I/O just to
        bump a counter, and the record is formatted outside both."""
        if time_used_ms < self.threshold_ms:
            return False
        with self._lock:
            if not self._sampled():
                return False
            self._logged += 1
        record = {"ts": round(time.time(), 3),
                  "timeUsedMs": round(time_used_ms, 3)}
        record.update(entry)
        line = json.dumps(record) + "\n"
        with self._io_lock:
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")  # tpulint: disable=lock-blocking -- lazy one-shot open of the append handle; steady-state logging only pays the in-memory write under this lock
            self._fh.write(line)
            self._fh.flush()
        return True

    def close(self) -> None:
        with self._io_lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def stats(self) -> dict:
        with self._lock:
            return {"path": self.path, "thresholdMs": self.threshold_ms,
                    "sampleRate": self.sample_rate,
                    "slowSeen": self._seen, "logged": self._logged}
