"""Shared AST helpers: alias-aware dotted-name resolution, jit detection.

Every rule works on resolved dotted paths (``jnp.sum`` → ``jax.numpy.sum``)
so rules match semantics, not spelling. Resolution is purely syntactic —
it follows ``import``/``from ... import`` aliases within one file, which
is exactly the granularity an AST linter can promise.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional


def collect_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name → fully dotted path, from this module's imports.

    ``import jax.numpy as jnp`` → {"jnp": "jax.numpy"};
    ``from jax import lax`` → {"lax": "jax.lax"};
    ``import jax`` → {"jax": "jax"}; likewise for numpy and everything
    else (resolution is generic; rules filter by root).
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                aliases[local] = a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module and \
                not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def safe_unparse(node: ast.AST) -> str:
    """ast.unparse that degrades to "" instead of raising — shape
    matchers treat an unparsable node as a non-match."""
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001
        return ""


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted chain with its root rewritten through the import aliases."""
    d = dotted(node)
    if d is None:
        return None
    root, _, rest = d.partition(".")
    base = aliases.get(root, root)
    return f"{base}.{rest}" if rest else base


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name underlying an expression, looking through
    attribute access, subscripts and method-call receivers
    (``outs.get(...)`` → ``outs``; ``a[i].x`` → ``a``)."""
    while True:
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id
        else:
            return None


_JIT_PATHS = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


def is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for ``jax.jit`` / aliased jit, bare or via functools.partial."""
    if resolve(node, aliases) in _JIT_PATHS:
        return True
    if isinstance(node, ast.Call):
        f = resolve(node.func, aliases)
        if f in _JIT_PATHS:
            return True
        if f in ("functools.partial", "partial") and node.args and \
                resolve(node.args[0], aliases) in _JIT_PATHS:
            return True
    return False


def is_jitted(fn: ast.AST, aliases: Dict[str, str]) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    return any(is_jit_expr(d, aliases) for d in fn.decorator_list)


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_shallow(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function
    definitions (their hazards are judged in their own scope)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def const_str(node: ast.AST) -> Optional[str]:
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None
