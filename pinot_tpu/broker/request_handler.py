"""Broker request pipeline: compile → quota → route → scatter-gather →
reduce.

Parity: pinot-broker/.../requesthandler/BaseBrokerRequestHandler.java:127-346
(compile, ACL, table lookup offline/realtime/hybrid, QPS quota, optimizer,
time-boundary split, routing) and
SingleConnectionBrokerRequestHandler.java:54-111 + core/transport/
QueryRouter.java:43-57 (per-server InstanceRequests, gather with timeout,
partial-response tolerance, reduce via BrokerReduceService).
"""
from __future__ import annotations

import asyncio
import itertools
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from pinot_tpu.common.cluster_state import CONSUMING, ONLINE
from pinot_tpu.common.datatable import (DataTable, MISSING_SEGMENTS_KEY,
                                        RESULT_CACHE_HIT_KEY,
                                        RETRY_AFTER_MS_KEY,
                                        SEGMENT_MISSING_EXC_PREFIX,
                                        SERVER_BUSY_EXC_PREFIX,
                                        SERVER_BUSY_KEY, STAGE_ERROR_KEY)
from pinot_tpu.common.metrics import (BrokerGauge, BrokerMeter,
                                      BrokerQueryPhase, MetricsRegistry)
from pinot_tpu.transport import shm as _shm_mod
from pinot_tpu.common.request import BrokerRequest, InstanceRequest
from pinot_tpu.common.response import (BrokerResponse, classify_exception,
                                       exception_entry)
from pinot_tpu.common.serde import instance_request_to_bytes
from pinot_tpu.obs.slowlog import SlowQueryLog
from pinot_tpu.obs.profiler import TableStatsAggregator
from pinot_tpu.obs.tracing import (TraceContext, build_trace_tree,
                                   make_trace_context)
from pinot_tpu.common.table_name import (offline_table, raw_table,
                                         realtime_table)
from pinot_tpu.broker.fault_tolerance import FaultToleranceManager
from pinot_tpu.broker.quota import QueryQuotaManager
from pinot_tpu.broker.result_cache import BrokerResultCache
from pinot_tpu.broker.routing import RoutingError, RoutingManager
from pinot_tpu.broker.time_boundary import (TimeBoundaryService,
                                            attach_time_boundary)
from pinot_tpu.pql.optimizer import BrokerRequestOptimizer
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.reduce import BrokerReduceService
from pinot_tpu.transport.tcp import EventLoopThread, ServerConnection


class ServerTransport:
    """Sends framed InstanceRequest bytes to a named server."""

    async def query(self, server: str, payload: bytes,
                    timeout: float) -> bytes:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class InProcessTransport(ServerTransport):
    """Embedded-cluster transport: servers in this process (the reference's
    single-JVM ClusterTest pattern, full serde still exercised)."""

    def __init__(self, servers: Dict[str, object]):
        self.servers = servers        # name -> ServerInstance

    async def query(self, server: str, payload: bytes,
                    timeout: float) -> bytes:
        instance = self.servers[server]
        loop = asyncio.get_running_loop()
        return await asyncio.wait_for(
            loop.run_in_executor(None, instance.handle_request_bytes,
                                 payload),
            timeout)


class TcpTransport(ServerTransport):
    """One persistent MULTIPLEXED framed TCP connection per server:
    every concurrent query to a server shares its channel, correlated by
    requestId (ServerChannels parity), so in-flight requests are bounded
    by the server, not by a one-at-a-time connection lock."""

    def __init__(self, endpoints: Dict[str, Tuple[str, int]]):
        self.endpoints = dict(endpoints)
        self._conns: Dict[str, ServerConnection] = {}

    def set_endpoint(self, server: str, host: str, port: int) -> None:
        self.endpoints[server] = (host, port)
        stale = self._conns.pop(server, None)
        if stale is not None:
            # fail the old channel's in-flight requests promptly (they
            # were sent to the departed endpoint) instead of leaking a
            # reader task on a dead socket until its peers time out.
            # Callers are watcher threads, not the event loop — the
            # connection schedules close() onto ITS OWN loop.
            stale.close_threadsafe()

    async def query(self, server: str, payload: bytes,
                    timeout: float) -> bytes:
        conn = self._conns.get(server)
        if conn is None:
            host, port = self.endpoints[server]
            # concurrent first-queries race to create the channel;
            # setdefault keeps exactly one so they truly share it
            conn = self._conns.setdefault(server,
                                          ServerConnection(host, port))
        # the deadline covers connect + write + read: a black-holed
        # server (dropped SYNs) or a slow reply must still surface as a
        # timely partial response — and a timeout abandons only THIS
        # request's future, never the shared channel
        return await asyncio.wait_for(conn.request(payload, timeout),
                                      timeout)

    async def close(self) -> None:
        for conn in self._conns.values():
            # inline-HTTP brokers create connections on the API loop;
            # a close arriving from the handler's own loop must hop to
            # the connection's loop instead of awaiting cross-loop
            if conn._loop is None or \
                    conn._loop is asyncio.get_running_loop():
                await conn.close()
            else:
                conn.close_threadsafe()
        self._conns.clear()


def _server_error(server: str, message: str) -> dict:
    """One per-server failure record; `recovered` flips to True when a
    replica re-dispatch later produced the data anyway."""
    return {"server": server, "message": message, "recovered": False}


class QueryRouter:
    """Budget-aware scatter engine: deadline propagation, breaker
    gating, hedged replica retries, per-server failure accounting.

    Each (sub-request, server, segments) dispatch unit runs through:
    1. breaker gate — an OPEN server is skipped outright,
    2. the primary call with the REMAINING deadline budget stamped into
       the InstanceRequest (deadline propagation),
    3. an optional hedge: if the primary is still pending past the
       server's p95-derived hedge threshold, the same segments go to
       another live replica and the first good answer wins,
    4. failover: on error / corrupt frame / timeout, the unit's
       segments are re-routed to other ONLINE/CONSUMING replicas from
       the current view (ranked by health score) while budget remains.

    Failures are never swallowed: every one is recorded (server +
    reason + whether a replica recovered it) and metered.
    """

    # primary + up to two failover waves per segment
    MAX_ATTEMPTS = 3

    def __init__(self, transport: ServerTransport, broker_id: str,
                 fault_tolerance: Optional[FaultToleranceManager] = None,
                 routing: Optional[RoutingManager] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 clock=time.monotonic):
        self.transport = transport
        self.broker_id = broker_id
        self.fault_tolerance = fault_tolerance
        self.routing = routing
        self.metrics = metrics or MetricsRegistry("broker")
        self._clock = clock

    async def submit(self, request_id: int,
                     routes: List[Tuple[BrokerRequest, Dict[str,
                                                            List[str]]]],
                     timeout: float, enable_trace: bool = False,
                     deadline: Optional[float] = None,
                     trace: Optional[TraceContext] = None,
                     parent_span_id: Optional[str] = None,
                     workload: Optional[str] = None,
                     exchange_sources: Optional[List[dict]] = None
                     ) -> Tuple[List[DataTable], int, int, List[dict]]:
        """routes: [(per-table request, {server: segments})] — returns
        (tables, num_queried, num_responded, errors). `deadline` is an
        absolute clock() instant shared by retries so re-dispatches
        never extend user-visible latency past the requested timeout.
        `trace`/`parent_span_id`: every dispatch (primary, hedge,
        failover) records a span under the scatter phase and stamps its
        own span id into the InstanceRequest as the server subtree's
        parent."""
        if deadline is None:
            deadline = self._clock() + timeout
        units = []
        for sub_request, routing in routes:
            for server, segments in routing.items():
                units.append((sub_request, server, segments))
        outcomes = await asyncio.gather(
            *(self._query_unit(request_id, sub, server, segments,
                               deadline, enable_trace, trace,
                               parent_span_id, workload,
                               exchange_sources)
              for sub, server, segments in units))
        tables: List[DataTable] = []
        errors: List[dict] = []
        responded = 0
        for unit_tables, unit_errors in outcomes:
            errors.extend(unit_errors)
            if unit_tables:
                tables.extend(unit_tables)
                responded += 1
        return tables, len(units), responded, errors

    # -- one dispatch unit --------------------------------------------------
    async def _query_unit(self, request_id: int, sub: BrokerRequest,
                          server: str, segments: List[str],
                          deadline: float, enable_trace: bool,
                          trace: Optional[TraceContext] = None,
                          parent_span_id: Optional[str] = None,
                          workload: Optional[str] = None,
                          exchange_sources: Optional[List[dict]] = None):
        errors: List[dict] = []
        tried = {server}
        tables: List[DataTable] = []
        # breaker gating happens inside _call_once (uniformly for the
        # primary, hedges and failovers); an OPEN primary just records
        # CircuitBreakerOpen there and falls through to failover
        dt = await self._dispatch_hedged(request_id, sub, server,
                                         segments, deadline,
                                         enable_trace, errors, tried,
                                         trace, parent_span_id, workload,
                                         exchange_sources)
        if dt is not None:
            for e in errors:         # e.g. primary failed, hedge won
                e["recovered"] = True
            return [dt], errors
        # failover: re-route this unit's segments to other live replicas
        # (waves, because the replacement can fail too) within budget.
        # EXCEPT a deadline-cause shed: the server judged the remaining
        # budget below the table's service-time estimate. The estimate
        # is the SHEDDING server's own rolling p75 — a transiently
        # degraded replica can shed what a healthy one would answer —
        # but under deadline pressure per-shed failover fan-out is the
        # worse failure mode (every doomed query multiplies RPCs right
        # at the overload knee), and each busy reply soft-dings the
        # shedder's health (on_busy), so routing steers subsequent
        # queries to healthier replicas within a few requests
        remaining_segs = list(segments)
        for _ in range(1, self.MAX_ATTEMPTS):
            if not remaining_segs or self._clock() >= deadline:
                break
            if any(e.get("busyCause") == "deadline" for e in errors):
                break
            groups = self._replica_groups(sub, remaining_segs, tried)
            if not groups:
                break
            self.metrics.meter(BrokerMeter.SEGMENT_RETRIES).mark(
                len(remaining_segs))
            items = sorted(groups.items())
            results = await asyncio.gather(
                *(self._call_once(request_id, sub, srv, segs, deadline,
                                  enable_trace, errors, trace,
                                  parent_span_id, workload,
                                  exchange_sources=exchange_sources)
                  for srv, segs in items))
            next_remaining: List[str] = []
            for (srv, segs), dt in zip(items, results):
                tried.add(srv)
                if dt is None:
                    next_remaining.extend(segs)
                else:
                    tables.append(dt)
            remaining_segs = next_remaining
        if not remaining_segs and tables:
            # every segment of the failed unit was recovered elsewhere:
            # the response is complete, demote the failures to telemetry
            for e in errors:
                e["recovered"] = True
        return tables, errors

    async def _dispatch_hedged(self, request_id, sub, server, segments,
                               deadline, enable_trace, errors, tried,
                               trace=None, parent_span_id=None,
                               workload=None, exchange_sources=None):
        """Primary call with a latency hedge to one replica."""
        ft = self.fault_tolerance
        primary = asyncio.ensure_future(self._call_once(
            request_id, sub, server, segments, deadline, enable_trace,
            errors, trace, parent_span_id, workload,
            exchange_sources=exchange_sources))
        hedge_after = ft.hedge_delay_s(server) if ft is not None else None
        if hedge_after is None:
            return await primary
        budget = deadline - self._clock()
        done, _pending = await asyncio.wait(
            {primary}, timeout=max(0.0, min(hedge_after, budget)))
        for t in done:
            # t came out of asyncio.wait's done set, so .result() is a
            # completed-future value read, not a loop-blocking wait —
            # the async-blocking rule VERIFIES this iteration pattern
            # (the audited `primary.result()` form was equivalent but
            # unverifiable statically)
            return t.result()
        hedge_server = self._hedge_candidate(sub, segments, tried)
        if hedge_server is None:
            return await primary
        tried.add(hedge_server)
        ft.on_hedge(server)
        # hedge=True travels in the request: under queue pressure the
        # server sheds hedged duplicates FIRST (deterministic order)
        hedge = asyncio.ensure_future(self._call_once(
            request_id, sub, hedge_server, segments, deadline,
            enable_trace, errors, trace, parent_span_id, workload,
            hedge=True, exchange_sources=exchange_sources))
        pending = {primary, hedge}
        winner = None
        while pending and winner is None:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                dt = t.result()
                if dt is not None and winner is None:
                    winner = dt
        for t in pending:
            t.cancel()       # loser keeps running server-side; drop it
        if pending:
            # AWAIT the cancelled losers: their CancelledError handlers
            # patch the dispatch span (ms + attrs.cancelled), and those
            # dicts must be settled before _finish serializes the trace
            # tree on another thread
            await asyncio.wait(pending)
        return winner

    async def _call_once(self, request_id, sub, server, segments,
                         deadline, enable_trace, errors, trace=None,
                         parent_span_id=None, workload=None,
                         hedge=False, exchange_sources=None):
        """One dispatch to one server; stamps the remaining budget,
        classifies failures, feeds the health/breaker state."""
        ft = self.fault_tolerance
        if ft is not None and not ft.allow_request(server):
            # the one place every dispatch kind passes through, so the
            # breaker's single-probe half-open invariant holds for
            # hedges and failover waves too, not just primaries
            errors.append(_server_error(
                server, f"CircuitBreakerOpen: {server} is shedding load"))
            return None
        budget = deadline - self._clock()
        if budget <= 0:
            errors.append(_server_error(
                server, "DeadlineExceededError: no budget left to "
                f"dispatch to {server}"))
            return None
        # the dispatch span is created BEFORE the send so its id can
        # travel in the request as the server subtree's parent link;
        # concurrent dispatches of one query share an event-loop thread,
        # so parenting is explicit (parent_span_id), never stack-based.
        # ms is patched in when the reply lands (same dict object).
        dspan = None
        if trace is not None and trace.enabled:
            dspan = trace.record(f"dispatch:{server}", 0.0,
                                 parent_id=parent_span_id,
                                 segments=len(segments))
        payload = instance_request_to_bytes(InstanceRequest(
            request_id=request_id, query=sub, search_segments=segments,
            broker_id=self.broker_id, enable_trace=enable_trace,
            deadline_budget_ms=budget * 1e3,
            trace_id=trace.trace_id if dspan is not None else None,
            parent_span_id=dspan["spanId"] if dspan is not None else None,
            workload=workload, hedge=hedge,
            exchange_sources=exchange_sources))
        self.metrics.meter(BrokerMeter.INSTANCE_REQUEST_BYTES).mark(
            len(payload))
        t0 = self._clock()
        try:
            raw = await asyncio.wait_for(
                self.transport.query(server, payload, budget), budget)
            # per-hop serde attribution: the decode share of the gather
            # is timed and its byte volume metered, so PROFILE
            # artifacts can split serde from transport+queueing
            self.metrics.meter(BrokerMeter.SERVER_RESPONSE_BYTES).mark(
                len(raw))
            with self.metrics.timer(
                    BrokerQueryPhase
                    .SERVER_RESPONSE_DESERIALIZATION).time():
                # colocated shared-memory replies decode straight from
                # the segment, then unlink (the decoder copies blocks
                # out of writable buffers by contract)
                dt = _shm_mod.datatable_from_reply(raw)
        except asyncio.CancelledError:
            # hedge loser / caller teardown: mark the span so the tree
            # shows an abandoned dispatch, not a 0ms "success"
            if dspan is not None:
                dspan["ms"] = round((self._clock() - t0) * 1e3, 3)
                dspan.setdefault("attrs", {})["cancelled"] = True
            raise
        except Exception as e:  # noqa: BLE001 — classified, never silent
            self.metrics.meter(BrokerMeter.SERVER_ERRORS).mark()
            self.metrics.meter(BrokerMeter.SERVER_ERRORS,
                               table=server).mark()
            if ft is not None:
                ft.on_failure(server)
            kind = "ServerTimeoutError" if \
                isinstance(e, asyncio.TimeoutError) else type(e).__name__
            errors.append(_server_error(server, f"{kind}: {e}"))
            if dspan is not None:
                dspan["ms"] = round((self._clock() - t0) * 1e3, 3)
                dspan.setdefault("attrs", {})["error"] = kind
            return None
        if dspan is not None:
            dspan["ms"] = round((self._clock() - t0) * 1e3, 3)
        busy_cause = dt.metadata.get(SERVER_BUSY_KEY)
        if busy_cause is not None:
            # typed server-busy: the server's admission control shed
            # this request. NON-RETRIABLE on the same server (it just
            # told us it is drowning) — record the failure so the unit
            # fails over to a replica; `tried` already excludes this
            # server from hedges and failover waves. Health takes a
            # soft ding, the breaker NEVER trips on honest shedding.
            self.metrics.meter(BrokerMeter.SERVER_BUSY_RESPONSES).mark()
            self.metrics.meter(BrokerMeter.SERVER_BUSY_RESPONSES,
                               table=busy_cause).mark()
            if ft is not None:
                ft.on_busy(server)
            retry_ms = dt.metadata.get(RETRY_AFTER_MS_KEY, "0")
            err = _server_error(
                server, f"{SERVER_BUSY_EXC_PREFIX} shed ({busy_cause}), "
                f"retryAfterMs={retry_ms}")
            # internal routing markers only — _finish surfaces just
            # server/message, so these never reach the client.
            # busyCause is ALSO the structured busy classifier _finish
            # keys 503-vs-425 on (never the message text, whose wording
            # is free to change); retryAfterMs feeds the whole-query-
            # shed Retry-After the HTTP layer returns with its 503
            err["busyCause"] = busy_cause
            try:
                err["retryAfterMs"] = float(retry_ms)
            except (TypeError, ValueError):
                err["retryAfterMs"] = 0.0
            errors.append(err)
            if dspan is not None:
                dspan.setdefault("attrs", {})["busy"] = busy_cause
            return None
        if ft is not None:
            ft.on_success(server, (self._clock() - t0) * 1e3)
        dt.metadata.setdefault("serverName", server)
        return dt

    # -- replica selection --------------------------------------------------
    def _view_for(self, sub: BrokerRequest):
        """Fetch the routing view ONCE per selection scan — view() deep-
        copies the table under the routing lock, so per-segment fetches
        would make failover O(segments × view size) in copies."""
        return self.routing.view(sub.table_name) \
            if self.routing is not None else None

    def _live_replicas(self, view, segment: str, tried: set) -> List[str]:
        if view is None:
            return []
        ft = self.fault_tolerance
        out = [srv for srv in view.servers_for(segment,
                                               states=(ONLINE, CONSUMING))
               if srv not in tried and (ft is None or ft.available(srv))]
        if ft is not None:
            out.sort(key=lambda s: -ft.health(s))
        return out

    def _replica_groups(self, sub: BrokerRequest, segments: List[str],
                        tried: set) -> Dict[str, List[str]]:
        """Healthiest untried live replica per segment, grouped into
        per-server dispatch lists."""
        view = self._view_for(sub)
        groups: Dict[str, List[str]] = {}
        for segment in segments:
            candidates = self._live_replicas(view, segment, tried)
            if candidates:
                groups.setdefault(candidates[0], []).append(segment)
        return groups

    def _hedge_candidate(self, sub: BrokerRequest, segments: List[str],
                         tried: set) -> Optional[str]:
        """A single untried replica serving EVERY segment of the unit
        (a hedge duplicates the whole unit, it does not split it)."""
        if not segments:
            return None
        view = self._view_for(sub)
        common: Optional[set] = None
        for segment in segments:
            servers = set(self._live_replicas(view, segment, tried))
            common = servers if common is None else common & servers
            if not common:
                return None
        ft = self.fault_tolerance
        if ft is not None:
            return max(common, key=ft.health)
        return sorted(common)[0]


class BrokerRequestHandler:
    """The broker's query entry point (PQL string → BrokerResponse)."""

    def __init__(self, routing: RoutingManager,
                 transport: ServerTransport,
                 time_boundary: Optional[TimeBoundaryService] = None,
                 quota: Optional[QueryQuotaManager] = None,
                 broker_id: str = "broker_0",
                 default_timeout_s: float = 15.0,
                 metrics: Optional[MetricsRegistry] = None,
                 access_control=None,
                 segment_pruner=None,
                 fault_tolerance: Optional[FaultToleranceManager] = None,
                 slow_log: Optional[SlowQueryLog] = None,
                 result_cache: Optional[BrokerResultCache] = None,
                 cache_freshness_ms: Optional[float] = None,
                 cache_offline: Optional[bool] = None):
        # optional broker-side segment pruner (PartitionZKMetadataPruner):
        # prune(request, table, segments) -> segments
        self.segment_pruner = segment_pruner
        self.routing = routing
        self.metrics = metrics or MetricsRegistry("broker")
        from pinot_tpu.obs import residency
        residency.bind_registry(self.metrics)
        # sampling JSONL slow-query log (obs/slowlog.py); default: the
        # PINOT_TPU_SLOWLOG* env config, None = disabled
        self.slow_log = slow_log if slow_log is not None else \
            SlowQueryLog.from_env()
        # rolling per-table operator stats folded from every query's
        # server-side profile (obs/profiler.py)
        self.table_stats = TableStatsAggregator()
        # pre-register the core series so /metrics serves a meaningful
        # exposition from boot (a counter that exists at 0 beats one
        # that appears after the first query) and export uptime
        self._t_boot = time.monotonic()
        self.metrics.meter(BrokerMeter.QUERIES)
        self.metrics.gauge(BrokerGauge.UPTIME_SECONDS).set_callable(
            lambda: time.monotonic() - self._t_boot)
        self.fault_tolerance = fault_tolerance or FaultToleranceManager(
            metrics=self.metrics)
        self.router = QueryRouter(transport, broker_id,
                                  fault_tolerance=self.fault_tolerance,
                                  routing=routing, metrics=self.metrics)
        self.time_boundary = time_boundary or TimeBoundaryService()
        self.quota = quota or QueryQuotaManager()
        # broker-level result cache for tables with a realtime part,
        # bounded by minConsumingFreshnessTimeMs: the query option opts
        # in per query; `cache_freshness_ms` sets a broker-wide default
        # bound (None = only explicitly-bounded queries are cached)
        self.result_cache = result_cache or BrokerResultCache()
        self.default_cache_freshness_ms = cache_freshness_ms
        # pure-OFFLINE tables: results change only on segment lifecycle
        # events, and the cluster watcher flushes this cache on exactly
        # those (register_result_cache) — so caching them is EXACT, not
        # freshness-bounded, keyed on the same canonical fingerprint.
        # Default off (opt in per deployment / via env for bench rigs).
        if cache_offline is None:
            import os
            cache_offline = os.environ.get(
                "PINOT_TPU_BROKER_CACHE_OFFLINE", "0") != "0"
        self.cache_offline = bool(cache_offline)
        # compiled-request cache: the serving plane replays a small set
        # of query STRINGS at high rate; re-lexing the same PQL per
        # request was ~0.4ms of the per-query CPU budget. Entries are
        # treated as immutable downstream (_retable/attach_time_boundary
        # copy; force_trace copies below). Fingerprints memoize beside
        # the compiled form since they hash the same canonical tree.
        self._compile_cache: Dict[str, list] = {}
        self._compile_cache_max = 512
        self.optimizer = BrokerRequestOptimizer()
        self.reducer = BrokerReduceService()
        if access_control is None:
            from pinot_tpu.broker.access_control import AllowAllAccessControl
            access_control = AllowAllAccessControl()
        self.access_control = access_control
        self.default_timeout_s = default_timeout_s
        self._request_ids = itertools.count(1)
        self._loop: Optional[EventLoopThread] = None
        self._loop_lock = threading.Lock()

    # -- sync facade -------------------------------------------------------
    def handle(self, pql: str, identity=None,
               force_trace: bool = False) -> BrokerResponse:
        """The CPU stages (compile, ACL, route, reduce) run HERE, on the
        caller's thread; only the scatter-gather await shares the event
        loop. One loop thread carries every concurrent query's network
        waits just fine — it cannot also carry every query's compile and
        reduce without becoming the serving plane's bottleneck."""
        with self._loop_lock:
            if self._loop is None:
                self._loop = EventLoopThread()
            loop = self._loop
        prepared = self._prepare(pql, identity, force_trace)
        if isinstance(prepared, BrokerResponse):
            return prepared
        request, trace, routes, timeout_s, deadline, t0, workload, \
            fingerprint = prepared
        tables, queried, responded, errors = loop.run(
            self._scatter(request, trace, routes, timeout_s, deadline,
                          workload))
        return self._finish(request, trace, t0, tables, queried,
                            responded, errors, pql=pql,
                            fingerprint=fingerprint)

    def close(self) -> None:
        if self._loop is not None:
            self._loop.run(self.router.transport.close())
            self._loop.stop()
            self._loop = None
        if self.slow_log is not None:
            self.slow_log.close()

    async def handle_async(self, pql: str, identity=None,
                           force_trace: bool = False) -> BrokerResponse:
        prepared = self._prepare(pql, identity, force_trace)
        if isinstance(prepared, BrokerResponse):
            return prepared
        request, trace, routes, timeout_s, deadline, t0, workload, \
            fingerprint = prepared
        tables, queried, responded, errors = await self._scatter(
            request, trace, routes, timeout_s, deadline, workload)
        return self._finish(request, trace, t0, tables, queried,
                            responded, errors, pql=pql,
                            fingerprint=fingerprint)

    # -- pipeline stages ---------------------------------------------------
    def _prepare(self, pql: str, identity, force_trace: bool):
        """Sync CPU stage: compile → ACL → quota → route. Returns a
        BrokerResponse on early exit, else the scatter inputs."""
        t0 = time.perf_counter()
        self.metrics.meter(BrokerMeter.QUERIES).mark()
        t = time.perf_counter()
        entry = self._compile_cache.get(pql)
        if entry is None:
            try:
                request = compile_pql(pql)
            except Exception as e:  # noqa: BLE001 — compile errors → resp
                self.metrics.meter(
                    BrokerMeter.REQUEST_COMPILATION_EXCEPTIONS).mark()
                return _error_response(150, f"PQLParsingError: {e}")
            if len(self._compile_cache) >= self._compile_cache_max:
                self._compile_cache.clear()    # rare: bounded, not LRU
            # [request, memoized fingerprint] — fp filled lazily below
            entry = self._compile_cache[pql] = [request, None]
        request = entry[0]
        if force_trace and "trace" not in request.query_options.options:
            # the HTTP client's JSON trace flag; an explicit OPTION(trace=…)
            # in the query wins. COPY before flipping: the cached
            # compiled request is shared across concurrent queries.
            import copy
            request = copy.copy(request)
            request.query_options = copy.copy(request.query_options)
            request.query_options.trace = True
        compile_ms = (time.perf_counter() - t) * 1e3
        self.metrics.timer(BrokerQueryPhase.REQUEST_COMPILATION).update(
            compile_ms)
        trace = make_trace_context(request.query_options.trace)
        trace.record(BrokerQueryPhase.REQUEST_COMPILATION, compile_ms)

        with self.metrics.timer(BrokerQueryPhase.AUTHORIZATION).time(), \
                trace.span(BrokerQueryPhase.AUTHORIZATION):
            allowed = self.access_control.has_access(identity, request)
        if not allowed:
            self.metrics.meter(
                BrokerMeter.REQUEST_DROPPED_DUE_TO_ACCESS_ERROR).mark()
            return _error_response(180, "AccessDeniedError: permission "
                                   f"denied for table {request.table_name}")

        raw = raw_table(request.table_name)
        # tenant/workload tag: OPTION(workload=...) in the query, else
        # a DIGEST of the authenticated identity's token — the key the
        # per-tenant quota buckets and the server's scheduler groups
        # isolate on. Never the raw token: the tag travels in plaintext
        # in every InstanceRequest and surfaces in scheduler-group
        # names and debug views, so a bearer credential must not be it.
        workload = request.query_options.options.get("workload")
        if workload:
            # an explicit tag spends THAT tenant's quota and joins its
            # scheduler group — give the ACL a chance to bind tags to
            # authenticated principals (default SPI: allow, tags are
            # cooperative; getattr tolerates duck-typed implementations)
            gate = getattr(self.access_control, "allow_workload", None)
            if gate is not None and not gate(identity, workload):
                self.metrics.meter(
                    BrokerMeter.REQUEST_DROPPED_DUE_TO_ACCESS_ERROR).mark()
                return _error_response(
                    180, "AccessDeniedError: identity may not use "
                    f"workload {workload}")
        else:
            token = getattr(identity, "token", None)
            if token:
                import hashlib
                workload = "id-" + hashlib.sha256(
                    token.encode("utf-8")).hexdigest()[:12]
        decision = self.quota.acquire(raw, workload)
        if not decision:
            self.metrics.meter(BrokerMeter.QUERY_QUOTA_EXCEEDED).mark()
            cause = decision.cause or "tableQuota"
            self.metrics.meter(BrokerMeter.QUERIES_DROPPED).mark()
            self.metrics.meter(BrokerMeter.QUERIES_DROPPED,
                               table=cause).mark()
            scope = f"tenant {workload} of table {raw}" \
                if cause == "tenantQuota" else f"table {raw}"
            resp = _error_response(
                429, f"QuotaExceededError: {scope} exceeded its QPS "
                f"quota; retry after {decision.retry_after_s:.2f}s")
            resp.exceptions[0]["retryAfterSeconds"] = round(
                decision.retry_after_s, 3)
            # the HTTP layer turns this into a 429 + Retry-After header
            resp.retry_after_s = decision.retry_after_s
            return resp

        # broker-level result cache: only tables with a realtime part
        # (the server-side CRC cache already covers pure-offline), only
        # under an explicit freshness bound. Probed BEFORE routing —
        # the hit path is the graceful-degradation valve under
        # overload, so it must not pay route computation + segment
        # pruning just to discard them (has_table on the realtime
        # variant also guarantees the table still exists)
        fingerprint = None
        opt_bound = request.query_options.options.get(
            "minConsumingFreshnessTimeMs")
        try:
            bound_ms = float(opt_bound) if opt_bound is not None \
                else self.default_cache_freshness_ms
        except (TypeError, ValueError):
            bound_ms = self.default_cache_freshness_ms
        # traced queries bypass the cache both ways: the client asked
        # to watch THIS execution, and a cached reply has no spans
        # (the put at _finish has the matching guard). Multi-stage
        # queries bypass too: the fingerprint keys on ONE table, but a
        # join answer also depends on the DIM table's segment state — a
        # cached join result would survive dim-table changes (the server
        # cache has the matching guard in ServerInstance._stage_request)
        cache_bound = None
        if not request.query_options.trace and request.join is None and \
                not request.windows:
            if bound_ms is not None and \
                    self.routing.has_table(realtime_table(raw)):
                cache_bound = bound_ms
            elif self.cache_offline and \
                    not self.routing.has_table(realtime_table(raw)) and \
                    self.routing.has_table(offline_table(raw)):
                # pure-offline: exact (not freshness-bounded) — every
                # segment lifecycle event flushes this cache, so age
                # never bounds validity
                cache_bound = float("inf")
        if cache_bound is not None:
            fp = entry[1]
            if fp is None:
                from pinot_tpu.query.fingerprint import query_fingerprint
                fp = entry[1] = query_fingerprint(request)
            # generation captured BEFORE execution: a view change that
            # clear()s the cache while this query is in flight (an
            # OFFLINE backfill) must not be undone by _finish's put
            # re-inserting the pre-backfill result
            fingerprint = (fp, self.result_cache.generation)
            cached = self.result_cache.get(fp, cache_bound)
            if cached is not None:
                self.metrics.meter(BrokerMeter.RESULT_CACHE_HITS).mark()
                cached.time_used_ms = (time.perf_counter() - t0) * 1e3
                return cached
            self.metrics.meter(BrokerMeter.RESULT_CACHE_MISSES).mark()

        with self.metrics.timer(BrokerQueryPhase.QUERY_ROUTING).time(), \
                trace.span(BrokerQueryPhase.QUERY_ROUTING):
            routes, error = self._resolve_routes(request, raw)
        if error is not None:
            self.metrics.meter(
                BrokerMeter.RESOURCE_MISSING_EXCEPTIONS).mark()
            return error

        timeout_s = (request.query_options.timeout_ms or
                     self.default_timeout_s * 1e3) / 1e3
        # ONE absolute deadline governs the scatter, every hedge and
        # every retry: re-dispatches spend the remaining budget, they
        # never extend user-visible latency past the requested timeout
        deadline = time.monotonic() + timeout_s
        return request, trace, routes, timeout_s, deadline, t0, \
            workload, fingerprint

    async def _scatter(self, request: BrokerRequest, trace: TraceContext,
                       routes, timeout_s: float, deadline: float,
                       workload: Optional[str] = None):
        """Async network stage: dispatch + gather + missing-segment
        retry. The only stage that runs on the shared event loop."""
        with self.metrics.timer(BrokerQueryPhase.SCATTER_GATHER).time(), \
                trace.span(BrokerQueryPhase.SCATTER_GATHER) as sg:
            sg_id = sg["spanId"] if sg is not None else None
            if request.join is not None or request.windows:
                # multi-stage plan: stage-1 exchange publish, then the
                # stage-2 scatter (query/stages/broker.py)
                from pinot_tpu.query.stages import broker as stages_broker
                return await stages_broker.scatter_stages(
                    self, request, routes, timeout_s, deadline, trace,
                    workload, next(self._request_ids))
            tables, queried, responded, errors = await self.router.submit(
                next(self._request_ids), routes, timeout_s,
                enable_trace=request.query_options.trace,
                deadline=deadline, trace=trace, parent_span_id=sg_id,
                workload=workload)
            tables, rq, rr, retry_errors = \
                await self._retry_missing_segments(
                    routes, tables, deadline,
                    enable_trace=request.query_options.trace,
                    trace=trace, parent_span_id=sg_id,
                    workload=workload)
            queried += rq
            responded += rr
            errors += retry_errors
        return tables, queried, responded, errors

    def _finish(self, request: BrokerRequest, trace: TraceContext,
                t0: float, tables: List[DataTable], queried: int,
                responded: int, errors: List[dict],
                pql: Optional[str] = None,
                fingerprint: Optional[str] = None) -> BrokerResponse:
        """Sync CPU stage: reduce + failure surfacing + trace merge."""
        if responded < queried:
            self.metrics.meter(
                BrokerMeter.BROKER_RESPONSES_WITH_PARTIAL_SERVERS).mark()
        # multi-stage compile errors come back as STAGE_ERROR_KEY-tagged
        # tables (deterministic query properties → 4xx, never reduced)
        stage_errs = [dt for dt in tables if STAGE_ERROR_KEY in dt.metadata]
        tables = [dt for dt in tables
                  if STAGE_ERROR_KEY not in dt.metadata]
        unrecovered = [e for e in errors if not e.get("recovered")]
        with self.metrics.timer(BrokerQueryPhase.REDUCE).time(), \
                trace.span(BrokerQueryPhase.REDUCE):
            blocks = [dt.to_block() for dt in tables]
            if blocks:
                resp = self.reducer.reduce(request, blocks)
            elif stage_errs:
                from pinot_tpu.query.stages.errors import \
                    STAGE_COMPILE_ERROR_CODE
                msg = stage_errs[0].exceptions[0] if \
                    stage_errs[0].exceptions else \
                    stage_errs[0].metadata[STAGE_ERROR_KEY]
                resp = _error_response(STAGE_COMPILE_ERROR_CODE, str(msg))
                stage_errs = stage_errs[1:]
            else:
                typed = next((e for e in unrecovered
                              if e.get("errorCode")), None)
                resp = _error_response(typed["errorCode"],
                                       typed["message"]) \
                    if typed is not None else \
                    _error_response(427, "ServerNotRespondedError: no "
                                    "server responded in time")
                if typed is not None:
                    unrecovered = [e for e in unrecovered
                                   if e is not typed]
        for dt in stage_errs:
            from pinot_tpu.query.stages.errors import \
                STAGE_COMPILE_ERROR_CODE
            resp.exceptions.append({
                "errorCode": STAGE_COMPILE_ERROR_CODE,
                "cause": "stageCompile",
                "message": str(dt.exceptions[0] if dt.exceptions
                               else dt.metadata[STAGE_ERROR_KEY])})
        # surface per-server failures a replica did NOT recover (the
        # old code silently `continue`d over them); recovered ones are
        # telemetry-only (meters/health), not client-facing noise
        for e in unrecovered:
            # the structured busyCause marker from _call_once is the
            # classifier — never the message text, whose wording is
            # free to change without turning sheds into 425 faults
            busy = e.get("busyCause") is not None
            # the machine cause ladder: a shed carries its admission
            # busyCause; otherwise classify the underlying message
            # prefix; otherwise it is a generic server fault
            inner = classify_exception(e.get("message") or "")
            resp.exceptions.append({
                # 503: typed server-busy (admission shed) — distinct
                # from 425 server errors so clients can back off
                # instead of treating overload as a fault; stage
                # orchestration errors carry their own code
                "errorCode": e.get("errorCode") or (503 if busy else 425),
                "cause": (e["busyCause"] if busy else
                          inner[1] if inner is not None else
                          "serverFault"),
                "message": f"ServerQueryError: server={e['server']}: "
                           f"{e['message']}"})
        if not tables and unrecovered and \
                all(e.get("busyCause") is not None for e in unrecovered):
            # the whole query was lost to shedding: a per-cause drop
            # meter mirrors the broker-side quota drops, and the reply
            # carries a real Retry-After (worst drain estimate across
            # the shedding servers) so the HTTP layer can answer 503 +
            # Retry-After instead of a 200 that invites instant retry
            self.metrics.meter(BrokerMeter.QUERIES_DROPPED).mark()
            self.metrics.meter(BrokerMeter.QUERIES_DROPPED,
                               table="serverBusy").mark()
            retry_s = max((e.get("retryAfterMs") or 0.0)
                          for e in unrecovered) / 1e3
            resp.retry_after_s = max(retry_s, 1.0)
        resp.partial_response = bool(
            responded < queried or unrecovered or
            any(dt.exceptions for dt in tables))
        resp.num_servers_queried = queried
        resp.num_servers_responded = responded
        resp.time_used_ms = (time.perf_counter() - t0) * 1e3
        if fingerprint is not None and not request.query_options.trace:
            # put() itself refuses partial/excepted/oversized responses
            # and drops inserts that lost a race with a clear()
            fp, gen = fingerprint
            self.result_cache.put(fp, resp, gen=gen)
        self.metrics.timer(BrokerQueryPhase.QUERY_TOTAL).update(
            resp.time_used_ms)
        self.metrics.meter(BrokerMeter.DOCUMENTS_SCANNED).mark(
            resp.num_docs_scanned)
        self._fold_profiles(request, tables, resp.time_used_ms)
        if request.query_options.trace:
            trace.finish_root()
            resp.trace_info = {"broker": trace.to_list()}
            merged = trace.to_list()
            for dt in tables:
                server_trace = dt.metadata.get("traceInfo")
                if not server_trace:
                    continue
                try:
                    spans = TraceContext.from_json_str(
                        server_trace).to_list()
                except Exception:  # noqa: BLE001 — skewed/corrupt metadata
                    continue       # a bad trace must not fail the query
                name = dt.metadata.get("serverName", "server")
                for s in spans:
                    s.setdefault("server", name)
                # hybrid tables: one server answers both the OFFLINE and
                # REALTIME sub-requests — merge, don't overwrite
                resp.trace_info.setdefault(name, []).extend(spans)
                merged.extend(spans)
            # ONE cross-process tree: each server subtree hangs off the
            # dispatch span whose id the broker stamped into its request
            resp.trace_tree = build_trace_tree(merged, trace.trace_id)
        if self.slow_log is not None:
            self.slow_log.maybe_log(resp.time_used_ms, {
                "table": raw_table(request.table_name),
                "pql": pql,
                "traceId": trace.trace_id,
                "numDocsScanned": resp.num_docs_scanned,
                "numSegmentsMatched": resp.num_segments_matched,
                "numServersQueried": queried,
                "numServersResponded": responded,
                "partialResponse": resp.partial_response,
                "exceptions": len(resp.exceptions)})
        return resp

    def _fold_profiles(self, request: BrokerRequest,
                       tables: List[DataTable],
                       time_used_ms: float) -> None:
        """Merge every server's per-query operator profile into one
        query-level record on the rolling per-table stats."""
        merged: Optional[dict] = None
        for dt in tables:
            if dt.metadata.get(RESULT_CACHE_HIT_KEY):
                # a cache hit replays the ORIGINAL execution's profile
                # bytes; folding it again would add a phantom copy of
                # those operator timings per hit to the rolling stats
                # an operator sizes quotas from, for ~0 actual work
                continue
            raw = dt.metadata.get("profileInfo")
            if not raw:
                continue
            try:
                p = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(p, dict):
                continue
            if merged is None:
                merged = p
                continue
            for k, v in p.items():
                if k == "paths":
                    paths = merged.setdefault("paths", {})
                    for path, n in (v or {}).items():
                        paths[path] = paths.get(path, 0) + int(n)
                elif isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0) + v
        if merged is not None:
            self.table_stats.record(raw_table(request.table_name),
                                    merged, time_used_ms)

    async def _retry_missing_segments(self, routes, tables,
                                      deadline: float,
                                      enable_trace: bool = False,
                                      trace: Optional[TraceContext] = None,
                                      parent_span_id: Optional[str] = None,
                                      workload: Optional[str] = None,
                                      exchange_sources: Optional[
                                          List[dict]] = None):
        """One re-dispatch of segments a server reported missing.

        A routing table sampled just before a rebalance drop step / a
        reload bounce can point at a server that has already unloaded
        the segment (the server still answers for the rest and reports
        SegmentMissingError). The make-before-break invariant means
        another replica IS serving — re-resolve those segments against
        the CURRENT external view and dispatch once more; segments with
        no live replica keep their exception (an honest miss). Parity:
        the reference broker re-resolving routing on external-view
        change + tolerating partial responses.
        """
        if not any(MISSING_SEGMENTS_KEY in dt.metadata for dt in tables):
            return tables, 0, 0, []    # hot path: nothing to inspect
        if time.monotonic() >= deadline:
            # budget exhausted: keep the honest SegmentMissingError
            # exceptions rather than re-dispatching past the timeout
            # (the old code reused the FULL timeout here, so a retry
            # after a slow first wave could double user latency)
            return tables, 0, 0, []

        seg_home: Dict[str, tuple] = {}
        for sub, routing in routes:
            for server, segs in routing.items():
                for g in segs:
                    seg_home[g] = (sub, server)

        # grouped per sub-request: a retry route must pair each server's
        # segment list with the SAME request those segments belong to
        retry_groups: Dict[int, tuple] = {}
        for dt in tables:
            raw = dt.metadata.pop(MISSING_SEGMENTS_KEY, None)
            if raw is None:
                continue
            try:
                missing = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(missing, list):
                continue        # skewed-version server: ignore, keep exc
            unresolved = []
            views: Dict[str, object] = {}
            for g in missing:
                sub, failed = seg_home.get(g, (None, None))
                view = None
                if sub is not None:
                    if sub.table_name not in views:
                        views[sub.table_name] = \
                            self.routing.view(sub.table_name)
                    view = views[sub.table_name]
                candidates = [srv for srv in
                              (view.servers_for(g, states=("ONLINE",
                                                           "CONSUMING"))
                               if view is not None else [])
                              if srv != failed]
                if sub is None or not candidates:
                    unresolved.append(g)
                    continue
                grp = retry_groups.setdefault(id(sub), (sub, {}))
                grp[1].setdefault(candidates[0], []).append(g)
            # the re-dispatch owns these segments now: drop the server's
            # human-facing exception and re-state only the honest misses
            dt.exceptions = [e for e in dt.exceptions if not
                             str(e).startswith(SEGMENT_MISSING_EXC_PREFIX)]
            if unresolved:
                dt.exceptions.append(
                    f"{SEGMENT_MISSING_EXC_PREFIX} {sorted(unresolved)}")
        retry_routes = list(retry_groups.values())

        if not retry_routes:
            return tables, 0, 0, []
        # the re-dispatch spends only the REMAINING budget (deadline is
        # absolute); a slow first wave leaves a short, honest retry
        remaining_s = max(deadline - time.monotonic(), 0.0)
        retry_tables, rq, rr, errors = await self.router.submit(
            next(self._request_ids), retry_routes, remaining_s,
            enable_trace=enable_trace, deadline=deadline, trace=trace,
            parent_span_id=parent_span_id, workload=workload,
            exchange_sources=exchange_sources)
        return tables + retry_tables, rq, rr, errors

    def _pruned_route(self, sub_request: BrokerRequest, table: str
                      ) -> Dict[str, List[str]]:
        routing = self.routing.route(table)
        if self.segment_pruner is None:
            return routing
        out = {}
        for server, segments in routing.items():
            kept = self.segment_pruner.prune(sub_request, table, segments)
            if kept:
                out[server] = kept
        # all segments pruned: keep one server with an empty segment list
        # so the response still carries the table's schema/zero counts
        if not out and routing:
            server = sorted(routing)[0]
            out[server] = []
        return out

    def _resolve_routes(self, request: BrokerRequest, raw: str):
        """Physical-table fan-out with hybrid time-boundary split."""
        off, rt = offline_table(raw), realtime_table(raw)
        has_off = self.routing.has_table(off)
        has_rt = self.routing.has_table(rt)
        if not has_off and not has_rt:
            return None, _error_response(
                190, f"TableDoesNotExistError: {raw}")
        routes = []
        boundary = self.time_boundary.get(off) if (has_off and has_rt) \
            else None
        try:
            if has_off:
                sub = self.optimizer.optimize(_retable(request, off))
                if boundary is not None:
                    sub = attach_time_boundary(sub, boundary, offline=True)
                routes.append((sub, self._pruned_route(sub, off)))
            if has_rt:
                sub = self.optimizer.optimize(_retable(request, rt))
                if boundary is not None:
                    sub = attach_time_boundary(sub, boundary, offline=False)
                routes.append((sub, self._pruned_route(sub, rt)))
        except RoutingError as e:
            # table removed between has_table and route (external-view race)
            return None, _error_response(190, f"RoutingError: {e}")
        return routes, None


def _retable(request: BrokerRequest, table: str) -> BrokerRequest:
    import copy
    out = copy.copy(request)
    out.table_name = table
    return out


def _error_response(code: int, message: str) -> BrokerResponse:
    resp = BrokerResponse()
    # exception_entry stamps the machine `cause` from the message
    # prefix; the explicit code always wins (e.g. stage compile 422)
    resp.exceptions.append(exception_entry(message, error_code=code))
    return resp
