"""Column data types and their host/device dtype mappings.

Parity: org.apache.pinot.common.data.FieldSpec.DataType
(reference: pinot-common/src/main/java/org/apache/pinot/common/data/FieldSpec.java).

TPU note: device compute runs on int32/float32 (TPU-native widths). LONG and
DOUBLE columns keep full-width numpy arrays host-side for exact oracle-grade
results; on-device copies are downcast unless x64 is enabled (tests run on the
CPU backend with x64 on, so correctness tests are exact).
"""
from __future__ import annotations

import enum

import numpy as np


class DataType(enum.Enum):
    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    BOOLEAN = "BOOLEAN"
    STRING = "STRING"
    BYTES = "BYTES"
    # dense embedding column: each row is a fixed-dimension float32
    # vector (FieldSpec.vector_dimension). Stored as a packed [n, dim]
    # forward block; served by the batched top-k similarity kernels.
    # The index SPI's TPU-native family (SURVEY §2.5) — no 2019-era
    # Pinot analogue.
    VECTOR = "VECTOR"

    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def np_dtype(self):
        """Host-side storage dtype (exact width)."""
        return _NP_DTYPES[self]

    @property
    def device_dtype(self):
        """Device compute dtype (TPU-native width)."""
        return _DEVICE_DTYPES[self]

    @property
    def default_null_value(self):
        """Default padding value for missing fields.

        Parity: FieldSpec.getDefaultNullValue (dimension defaults; metrics
        default to 0).
        """
        return _NULL_DIM[self]

    def convert(self, value):
        """Coerce a raw ingestion value to this type's python value."""
        if value is None:
            return self.default_null_value
        if self is DataType.INT:
            return int(value)
        if self is DataType.LONG:
            return int(value)
        if self is DataType.FLOAT:
            return float(value)
        if self is DataType.DOUBLE:
            return float(value)
        if self is DataType.BOOLEAN:
            # reference stores booleans as strings "true"/"false"
            if isinstance(value, bool):
                return "true" if value else "false"
            return str(value)
        if self is DataType.STRING:
            return str(value)
        if self is DataType.BYTES:
            if isinstance(value, (bytes, bytearray)):
                return bytes(value)
            return bytes.fromhex(str(value))
        if self is DataType.VECTOR:
            # dimension validation lives in FieldSpec.convert (the field
            # knows its dimension); this is the dimension-less coercion
            return np.asarray(value, dtype=np.float32)
        raise ValueError(f"unsupported type {self}")


_NUMERIC = {DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE}

_NP_DTYPES = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    DataType.BOOLEAN: np.dtype(object),
    DataType.STRING: np.dtype(object),
    DataType.BYTES: np.dtype(object),
    DataType.VECTOR: np.dtype(np.float32),
}

_DEVICE_DTYPES = {
    DataType.INT: np.dtype(np.int32),
    DataType.LONG: np.dtype(np.int64),
    DataType.FLOAT: np.dtype(np.float32),
    DataType.DOUBLE: np.dtype(np.float64),
    # non-numeric columns live on device as dictionary ids only
    DataType.BOOLEAN: np.dtype(np.int32),
    DataType.STRING: np.dtype(np.int32),
    DataType.BYTES: np.dtype(np.int32),
    DataType.VECTOR: np.dtype(np.float32),
}

_NULL_DIM = {
    DataType.INT: -(2**31) + 1,  # Integer.MIN_VALUE + 1? reference uses MIN_VALUE
    DataType.LONG: -(2**63) + 1,
    DataType.FLOAT: float(np.finfo(np.float32).min),
    DataType.DOUBLE: float(np.finfo(np.float64).min),
    DataType.BOOLEAN: "null",
    DataType.STRING: "null",
    DataType.BYTES: b"",
    DataType.VECTOR: None,   # FieldSpec.convert substitutes a zero vector
}
