"""Self-healing chaos suite: liveness-driven rebalance, realtime
partition takeover, standby controller failover, graceful drain.

Invariants under every scenario (docs/ROBUSTNESS.md "Self-healing &
membership churn"):
  1. no double-owned consuming partition,
  2. no replica-count regression below live capacity once converged,
  3. a deposed leader's store writes are fenced,
  4. a drained (SIGTERM) server costs zero query errors.

Clock-sensitive pieces (death grace window, leader lease) run on
injectable clocks — no wall-clock sleeps in the unit tier; only the
distributed end-to-end tests wait on real convergence like
test_distributed.py does.
"""
import os
import time

import pytest

from fixtures import build_segment, make_schema, make_table_config
from test_realtime import make_rows, rt_config, wait_until

from pinot_tpu.common.cluster_state import CONSUMING, ONLINE
from pinot_tpu.common.faults import InjectedCrash, crash_points
from pinot_tpu.common.table_config import SegmentsConfig
from pinot_tpu.controller.rebalance import (ClusterHealthMonitor,
                                            SegmentRebalancer,
                                            replication_deficit)
from pinot_tpu.tools.cluster import EmbeddedCluster

TABLE = "baseballStats_OFFLINE"


@pytest.fixture(autouse=True)
def _clear_crash_points():
    crash_points.clear()
    yield
    crash_points.clear()


def _offline_cluster(tmp_path, num_servers=3, replication=2, segments=4):
    cluster = EmbeddedCluster(str(tmp_path), num_servers=num_servers)
    cluster.add_schema(make_schema())
    cfg = make_table_config(
        segments_config=SegmentsConfig(replication=replication))
    cluster.add_table(cfg)
    total = 0
    for i in range(segments):
        d = os.path.join(str(tmp_path), f"seg{i}")
        os.makedirs(d)
        build_segment(d, n=500, seed=30 + i, name=f"healseg_{i}")
        cluster.upload_segment(TABLE, d)
        total += 500
    return cluster, total


def _monitor(cluster, clock, grace_s=5.0):
    return ClusterHealthMonitor(
        rebalancer=SegmentRebalancer(cluster.controller.manager,
                                     metrics=cluster.controller.metrics),
        realtime_manager=cluster.controller.realtime,
        grace_s=grace_s, clock=lambda: clock["t"],
        metrics=cluster.controller.metrics)


def _ideal(cluster, table=TABLE):
    return cluster.controller.coordinator.ideal_state(table)


def _count(cluster):
    resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
    return -1 if resp.exceptions else \
        int(resp.aggregation_results[0].value)


# ---------------------------------------------------------------------------
# Liveness monitor + rebalancer
# ---------------------------------------------------------------------------

def test_death_repair_waits_for_grace_then_heals(tmp_path):
    cluster, total = _offline_cluster(tmp_path)
    mgr = cluster.controller.manager
    clock = {"t": 100.0}
    mon = _monitor(cluster, clock, grace_s=5.0)
    mon.run(mgr)                       # baseline: learn the membership
    assert replication_deficit(mgr) == 0

    cluster.remove_server("Server_1")  # kill -9 analogue
    assert replication_deficit(mgr) > 0
    mon.run(mgr)                       # observed missing, inside grace
    assert any("Server_1" in states for states in _ideal(cluster).values())

    clock["t"] += 4.0                  # still inside the grace window
    mon.run(mgr)
    assert mon.last_report["dead"] == []

    clock["t"] += 2.0                  # grace passed: declared dead
    mon.run(mgr)
    assert mon.last_report["dead"] == ["Server_1"]
    ideal = _ideal(cluster)
    live = {"Server_0", "Server_2"}
    for seg, states in ideal.items():
        assert set(states) <= live, f"{seg} still names the corpse"
        assert len(states) == 2      # back at full replication
    assert replication_deficit(mgr) == 0
    assert cluster.controller.metrics.meter("rebalanceMoves").count > 0
    assert _count(cluster) == total
    # converged: the next cycle is a no-op (no ideal-state churn)
    before = _ideal(cluster)
    mon.run(mgr)
    assert _ideal(cluster) == before
    cluster.stop()


def test_restart_within_grace_is_not_a_death(tmp_path):
    cluster, _ = _offline_cluster(tmp_path, num_servers=2)
    mgr = cluster.controller.manager
    clock = {"t": 0.0}
    mon = _monitor(cluster, clock, grace_s=10.0)
    mon.run(mgr)
    before = _ideal(cluster)
    cluster.remove_server("Server_1")
    clock["t"] += 5.0
    mon.run(mgr)                       # missing but inside grace
    cluster.add_server("Server_1")     # restarted under the same id
    clock["t"] += 20.0
    mon.run(mgr)
    assert mon.last_report["dead"] == []
    # the restart reloaded its replicas: assignment unchanged
    assert _ideal(cluster) == before
    cluster.stop()


def test_same_id_rejoin_after_prune_heals(tmp_path):
    """A server declared dead (replicas pruned) that REJOINS under the
    same id is a comeback, not a resurrection: the join path must
    re-add replicas — nothing else would, since the id is already in
    the monitor's seen-set and no further death event fires."""
    cluster, total = _offline_cluster(tmp_path, num_servers=2,
                                      replication=2)
    mgr = cluster.controller.manager
    clock = {"t": 0.0}
    mon = _monitor(cluster, clock, grace_s=0.0)
    mon.run(mgr)
    cluster.remove_server("Server_1")
    mon.run(mgr)                       # dead + pruned to capacity 1
    for states in _ideal(cluster).values():
        assert set(states) == {"Server_0"}
    cluster.add_server("Server_1")     # same id returns
    mon.run(mgr)
    assert "Server_1" in mon.last_report["joined"]
    for states in _ideal(cluster).values():
        assert len(states) == 2        # topped back up
    assert replication_deficit(mgr) == 0
    assert _count(cluster) == total
    cluster.stop()


def test_selfheal_metrics_exposed_from_boot(tmp_path):
    """The self-healing meters/gauge ride the controller's Prometheus
    exposition from boot — operators see zeros, not absence."""
    import re
    from pinot_tpu.obs.prometheus import render_prometheus
    # 3 servers: live CAPACITY stays >= replication after one death, so
    # the lost replicas register as deficit (with 2 servers the cap
    # itself would drop and the gauge honestly read 0)
    cluster, _ = _offline_cluster(tmp_path, num_servers=3)
    text = render_prometheus(cluster.controller.metrics)
    for name in ("pinot_controller_rebalance_moves_total",
                 "pinot_controller_partition_takeovers_total",
                 "pinot_controller_leader_failovers_total",
                 "pinot_controller_cluster_replication_deficit"):
        assert name in text, f"{name} missing from /metrics"
    # the gauge is live: a death raises it until repair lands
    cluster.remove_server("Server_1")
    deficit = replication_deficit(cluster.controller.manager)
    assert deficit > 0
    assert re.search(r"pinot_controller_cluster_replication_deficit "
                     rf"{deficit}\b",
                     render_prometheus(cluster.controller.metrics))
    cluster.stop()


def test_repair_caps_at_live_capacity(tmp_path):
    """Replication 2, both remaining servers die except one: the
    rebalancer repairs to ONE live replica (capacity), never below,
    and tops back up when capacity returns."""
    cluster, total = _offline_cluster(tmp_path, num_servers=2,
                                      replication=2)
    mgr = cluster.controller.manager
    clock = {"t": 0.0}
    mon = _monitor(cluster, clock, grace_s=0.0)
    mon.run(mgr)
    cluster.remove_server("Server_1")
    mon.run(mgr)
    for seg, states in _ideal(cluster).items():
        assert set(states) == {"Server_0"}, seg
    assert replication_deficit(mgr) == 0      # capped at live capacity
    assert _count(cluster) == total
    # capacity returns: join triggers repair back to full replication
    cluster.add_server("Server_9")
    mon.run(mgr)
    # the join event rebalances; the deficit (repl 2 > 1 holder) is the
    # repair path's job on the same cycle
    for states in _ideal(cluster).values():
        assert len(states) == 2
    assert _count(cluster) == total
    cluster.stop()


def test_rebalance_on_join_is_throttled_and_makes_before_breaking(tmp_path):
    cluster, total = _offline_cluster(tmp_path, num_servers=2,
                                      replication=1, segments=6)
    mgr = cluster.controller.manager
    clock = {"t": 0.0}
    mon = _monitor(cluster, clock)
    mon.rebalancer.max_moves_per_cycle = 2      # tight throttle
    mon.run(mgr)
    cluster.add_server("Server_new")
    mon.run(mgr)
    assert mon.last_report["joined"] == ["Server_new"]
    moved = mon.last_report["joinMoves"].get("Server_new", {})
    n_moved = sum(len(m) for m in moved.values())
    assert 1 <= n_moved <= 2                    # bounded per cycle
    # every segment still has exactly its replica count — the move was
    # make-before-break, never a drop-first
    for seg, states in _ideal(cluster).items():
        assert len(states) == 1, (seg, states)
    assert _count(cluster) == total
    cluster.stop()


# ---------------------------------------------------------------------------
# Crash points: a controller dying mid-rebalance/mid-takeover leaves no
# double-owned or orphaned replica; a fresh monitor (restart) converges.
# ---------------------------------------------------------------------------

def _assert_healthy(cluster, live, replication):
    for seg, states in _ideal(cluster).items():
        holders = [i for i in states]
        assert len(set(holders)) == len(holders)          # no double-own
        assert set(holders) <= set(live)
        assert len(holders) == min(replication, len(live))  # no orphan


@pytest.mark.parametrize("point", ["rebalance.move_staged",
                                   "rebalance.pre_commit"])
def test_controller_crash_mid_rebalance_converges(tmp_path, point):
    cluster, total = _offline_cluster(tmp_path, num_servers=3,
                                      replication=2)
    mgr = cluster.controller.manager
    clock = {"t": 0.0}
    mon = _monitor(cluster, clock, grace_s=0.0)
    mon.run(mgr)
    cluster.remove_server("Server_1")
    crash_points.arm(point)
    with pytest.raises(InjectedCrash):
        mon.run(mgr)
    # "restart": all in-memory monitor/rebalancer state is lost; the
    # durable ideal state is whatever the crash left behind
    mon2 = _monitor(cluster, clock, grace_s=0.0)
    mon2.run(mgr)       # learns membership fresh (baseline has no corpse)
    mon2.run(mgr)
    _assert_healthy(cluster, ["Server_0", "Server_2"], 2)
    assert _count(cluster) == total
    cluster.stop()


def test_controller_crash_mid_takeover_resumes_consumption(tmp_path):
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    stream = MemoryStream("topic_heal", num_partitions=1)
    registry.register_stream_factory(
        "mem_heal", MemoryStreamConsumerFactory(stream, batch_size=32))
    cluster = EmbeddedCluster(str(tmp_path), num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_heal", "topic_heal",
                                    flush_rows=100))
        rows = make_rows(250, seed=5)
        for r in rows[:150]:
            stream.publish(r, partition=0)
        assert wait_until(lambda: _count(cluster) == 150, timeout=30)
        rt = "baseballStats_REALTIME"
        ideal = cluster.controller.coordinator.ideal_state(rt)
        owner = next(i for states in ideal.values()
                     for i, st in states.items() if st == CONSUMING)

        clock = {"t": 0.0}
        mon = _monitor(cluster, clock, grace_s=0.0)
        mon.run(cluster.controller.manager)
        cluster.remove_server(owner)
        crash_points.arm("takeover.pre_resume")
        with pytest.raises(InjectedCrash):
            mon.run(cluster.controller.manager)
        # crash window: partition bounced OFFLINE, no new owner yet —
        # exactly one of OFFLINE-parked or unassigned, never two owners
        ideal = cluster.controller.coordinator.ideal_state(rt)
        assert not any(st == CONSUMING and i != owner
                       for states in ideal.values()
                       for i, st in states.items())
        # restarted controller's monitor finishes the takeover
        mon2 = _monitor(cluster, clock, grace_s=0.0)
        mon2.run(cluster.controller.manager)
        ideal = cluster.controller.coordinator.ideal_state(rt)
        consuming = [(s, i) for s, states in ideal.items()
                     for i, st in states.items() if st == CONSUMING]
        assert len(consuming) == 1          # no double-owned partition
        assert consuming[0][1] != owner
        for r in rows[150:]:
            stream.publish(r, partition=0)
        # the new owner resumed from the last committed offset: exact
        # count, nothing lost, nothing doubled
        assert wait_until(lambda: _count(cluster) == 250, timeout=30)
        assert cluster.controller.metrics.meter(
            "partitionTakeovers").count >= 1
    finally:
        registry.unregister_stream_factory("mem_heal")
        cluster.stop()


# ---------------------------------------------------------------------------
# Scrubber: dead-host replicas defer to the rebalancer (no bounce burn)
# ---------------------------------------------------------------------------

def test_scrubber_defers_dead_host_to_rebalancer(tmp_path):
    from pinot_tpu.controller.periodic import SegmentIntegrityChecker
    cluster, total = _offline_cluster(tmp_path, num_servers=3,
                                      replication=2)
    mgr = cluster.controller.manager
    cluster.remove_server("Server_1")   # permanently dead instance
    checker = SegmentIntegrityChecker()
    checker.run(mgr)                    # ONE run, no grace, no bounces
    # the corpse was reassigned immediately — zero bounce budget burned
    assert not any(key[2] == "Server_1"
                   for key in checker._bounce_counts)
    for seg, states in _ideal(cluster).items():
        assert "Server_1" not in states, seg
        assert len(states) == 2
    report = checker.last_report.get(TABLE, {})
    assert report.get("reassigned"), report
    assert _count(cluster) == total
    # converged: a second run reports nothing
    checker.run(mgr)
    assert not checker.last_report.get(TABLE, {}).get("reassigned")
    cluster.stop()


# ---------------------------------------------------------------------------
# Broker fault-tolerance state for deregistered servers
# ---------------------------------------------------------------------------

def test_forget_clears_breaker_for_reincarnation():
    from pinot_tpu.broker.fault_tolerance import (BREAKER_CLOSED,
                                                  BREAKER_OPEN,
                                                  FaultToleranceManager)
    now = {"t": 0.0}
    ft = FaultToleranceManager(clock=lambda: now["t"],
                               breaker_failure_threshold=2)
    for _ in range(3):
        ft.on_failure("Server_X")
    assert ft.breaker_state("Server_X") == BREAKER_OPEN
    assert ft.health("Server_X") < 0.5
    ft.forget("Server_X")
    # a reincarnation under the same id starts CLEAN — no inherited
    # breaker, full health, and the exported gauges reset with it
    assert ft.breaker_state("Server_X") == BREAKER_CLOSED
    assert ft.health("Server_X") == 1.0
    assert ft.allow_request("Server_X")
    snap = ft.metrics.snapshot()
    assert snap["gauge.Server_X.breakerState"] == BREAKER_CLOSED
    assert snap["gauge.Server_X.serverHealth"] == 1.0


def test_live_instance_removal_forgets_ft_state(tmp_path):
    from pinot_tpu.broker.fault_tolerance import BREAKER_OPEN
    cluster, total = _offline_cluster(tmp_path, num_servers=2,
                                      replication=2)
    ft = cluster.broker.fault_tolerance
    for _ in range(10):
        ft.on_failure("Server_1")
    assert ft.breaker_state("Server_1") == BREAKER_OPEN
    # the SAME watch event that drops the live record clears the state
    cluster.remove_server("Server_1")
    with ft._lock:
        assert "Server_1" not in ft._servers
    assert _count(cluster) == total     # survivor serves everything
    cluster.stop()


# ---------------------------------------------------------------------------
# Graceful drain: planned departure costs zero query errors
# ---------------------------------------------------------------------------

def test_drain_is_errorless_under_load(tmp_path):
    import threading
    cluster, total = _offline_cluster(tmp_path, num_servers=2,
                                      replication=2)
    failures, stop = [], threading.Event()

    def hammer():
        while not stop.is_set():
            r = cluster.query("SELECT COUNT(*) FROM baseballStats")
            if r.exceptions or \
                    int(r.aggregation_results[0].value) != total:
                failures.append(r.to_json())

    t = threading.Thread(target=hammer)
    t.start()
    try:
        time.sleep(0.2)
        cluster.drain_server("Server_1")
        time.sleep(0.3)
    finally:
        stop.set()
        t.join()
    assert not failures, failures[:2]
    assert _count(cluster) == total
    cluster.stop()


def test_drain_seals_consuming_segment(tmp_path):
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    stream = MemoryStream("topic_drain", num_partitions=1)
    registry.register_stream_factory(
        "mem_drain", MemoryStreamConsumerFactory(stream, batch_size=32))
    cluster = EmbeddedCluster(str(tmp_path), num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_drain", "topic_drain",
                                    flush_rows=100_000))
        rows = make_rows(120, seed=8)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: _count(cluster) == 120, timeout=30)
        sealed = cluster.drain_server("Server_0")
        assert sealed
        rt = "baseballStats_REALTIME"
        mgr = cluster.controller.manager
        done = [s for s in mgr.segment_names(rt)
                if (mgr.segment_metadata(rt, s) or {}).get("status") ==
                "DONE"]
        # the in-flight rows were committed durably BEFORE departure —
        # a replacement server serves them from the deep store without
        # re-consuming the stream
        assert done, "drain did not seal the consuming segment"
        name = cluster.add_server("Server_1")
        # the departed server's committed replica + consuming successor
        # move to the replacement via the self-healing plane (the
        # drained holder is a stale ideal-state entry, repaired like a
        # death once its grace elapses — zero here)
        clock = {"t": 0.0}
        mon = _monitor(cluster, clock, grace_s=0.0)
        mon.run(cluster.controller.manager)
        assert wait_until(lambda: _count(cluster) == 120, timeout=30)
        assert name in cluster.servers
    finally:
        registry.unregister_stream_factory("mem_drain")
        cluster.stop()


# ---------------------------------------------------------------------------
# Leadership: lease expiry, fencing, split-brain impossibility — all on
# the injectable clock (test_crash_recovery.py style, no wall sleeps)
# ---------------------------------------------------------------------------

def _two_controllers(lease_s=10.0):
    from pinot_tpu.controller.leadership import ControllerLeadershipManager
    from pinot_tpu.controller.property_store import PropertyStore
    store = PropertyStore()
    now = {"t": 1000.0}
    a = ControllerLeadershipManager(store, "ctrl_a", lease_s=lease_s,
                                    clock=lambda: now["t"])
    b = ControllerLeadershipManager(store, "ctrl_b", lease_s=lease_s,
                                    clock=lambda: now["t"])
    return store, now, a, b


def test_lease_expiry_promotes_standby_and_bumps_epoch():
    store, now, a, b = _two_controllers(lease_s=10.0)
    assert a.try_acquire() is True
    epoch_a = a.fencing_token()
    assert epoch_a == 1
    assert b.try_acquire() is False          # unexpired lease holds
    now["t"] += 5.0
    assert a.try_acquire() is True           # refresh keeps the epoch
    assert a.fencing_token() == epoch_a
    now["t"] += 10.1                         # a went silent: lease dies
    assert b.try_acquire() is True           # standby takes over
    assert b.fencing_token() == epoch_a + 1  # fencing token advanced
    assert not a.is_leader()
    assert not a.holds_fenced_lease()
    assert b.holds_fenced_lease()


def test_fencing_rejects_deposed_leaders_delayed_write():
    from pinot_tpu.controller.leadership import (FencedStore,
                                                 FencedWriteError)
    store, now, a, b = _two_controllers(lease_s=10.0)
    fenced_a = FencedStore(store, a)
    fenced_b = FencedStore(store, b)
    assert a.try_acquire()
    fenced_a.set("/IDEALSTATES/t1", {"segments": {"s": {"a": "ONLINE"}}})
    now["t"] += 11.0
    assert b.try_acquire()                   # a is deposed
    # the delayed write a had in flight when its lease expired: REJECTED
    with pytest.raises(FencedWriteError):
        fenced_a.set("/IDEALSTATES/t1",
                     {"segments": {"s": {"a": "STALE"}}})
    with pytest.raises(FencedWriteError):
        fenced_a.update("/IDEALSTATES/t1", lambda old: {"segments": {}})
    with pytest.raises(FencedWriteError):
        fenced_a.remove("/IDEALSTATES/t1")
    # the store still holds what the NEW leader sees; b's writes pass
    assert store.get("/IDEALSTATES/t1")["segments"]["s"]["a"] == "ONLINE"
    fenced_b.set("/IDEALSTATES/t1", {"segments": {"s": {"b": "ONLINE"}}})
    assert store.get("/IDEALSTATES/t1")["segments"]["s"] == {
        "b": "ONLINE"}
    # reads on a deposed controller's fenced view keep working (a
    # standby must stay hot)
    assert fenced_a.get("/IDEALSTATES/t1") is not None


def test_fencing_rejects_old_incarnation_after_reacquire():
    """a loses the lease, b leads and dies, a re-acquires: a's NEW
    incarnation writes fine, but a FencedStore still holding the OLD
    epoch (a delayed write queued before deposition) stays fenced."""
    from pinot_tpu.controller.leadership import (FencedStore,
                                                 FencedWriteError)

    class _FrozenToken:
        """The in-flight write's view of leadership: the epoch captured
        when the write was issued."""

        def __init__(self, inner, epoch):
            self._inner = inner
            self._epoch = epoch
            self.instance_id = inner.instance_id

        def fencing_token(self):
            return self._epoch

        def holds_fenced_lease(self):
            rec = self._inner.store.get("/CONTROLLER/LEADER") or {}
            return rec.get("instance") == self.instance_id and \
                rec.get("leaseUntil", 0) >= self._inner._clock() and \
                int(rec.get("epoch", 0)) == self._epoch

    store, now, a, b = _two_controllers(lease_s=10.0)
    assert a.try_acquire()
    old = _FrozenToken(a, a.fencing_token())
    now["t"] += 11.0
    assert b.try_acquire()
    now["t"] += 11.0
    assert a.try_acquire()                   # legitimate re-election
    assert a.holds_fenced_lease()
    FencedStore(store, a).set("/x", {"v": 1})        # new incarnation: ok
    with pytest.raises(FencedWriteError):
        FencedStore(store, old).set("/x", {"v": 0})  # old epoch: fenced
    assert store.get("/x") == {"v": 1}


def test_split_brain_impossible_under_clock_walk():
    """At NO instant do two controllers both hold a valid lease — walk
    the clock through acquisition, refresh, expiry, takeover, failback
    and assert mutual exclusion at every step."""
    store, now, a, b = _two_controllers(lease_s=10.0)

    def exclusive():
        assert not (a.is_leader() and b.is_leader())
        assert not (a.holds_fenced_lease() and b.holds_fenced_lease())

    rng_steps = [0.0, 3.0, 3.0, 3.0, 2.0, 10.1, 0.0, 3.0, 9.0, 2.0,
                 10.1, 0.0, 1.0]
    actors = [a, b]
    for i, step in enumerate(rng_steps):
        now["t"] += step
        # both race the lease every step; CAS admits at most one
        actors[i % 2].try_acquire()
        actors[(i + 1) % 2].try_acquire()
        exclusive()
    # and the lease is live at the end with exactly one holder
    assert a.is_leader() != b.is_leader()


# ---------------------------------------------------------------------------
# Standby controller failover, end to end over real TCP
# ---------------------------------------------------------------------------

def test_standby_controller_takes_over_within_lease(tmp_path):
    from pinot_tpu.tools.distributed import (DistributedBroker,
                                             DistributedController,
                                             DistributedServer,
                                             StandaloneStore)
    base = str(tmp_path)
    zk = StandaloneStore(os.path.join(base, "zk"))
    lead = DistributedController(
        base, store_addr=("127.0.0.1", zk.port), instance_id="ctrl_lead",
        lease_s=1.0)
    standby = DistributedController(
        base, store_addr=("127.0.0.1", zk.port), standby=True,
        instance_id="ctrl_standby", lease_s=1.0)
    server = DistributedServer("Server_0", "127.0.0.1", zk.port,
                               lead.deep_store_dir,
                               work_dir=os.path.join(base, "s0"))
    broker = DistributedBroker("127.0.0.1", zk.port, lead.deep_store_dir)
    try:
        assert wait_until(lead.is_leader, timeout=10)
        assert not standby.is_leader()
        mgr = lead.controller.manager
        mgr.add_schema(make_schema())
        mgr.add_table(make_table_config())
        d = os.path.join(base, "seg0")
        os.makedirs(d)
        build_segment(d, n=800, seed=3, name="ha_seg0")
        mgr.add_segment(TABLE, d)

        def served(n):
            r = broker.query("SELECT COUNT(*) FROM baseballStats")
            return not r.exceptions and \
                int(r.aggregation_results[0].value) == n
        assert wait_until(lambda: served(800), timeout=30)

        # kill -9 the lead: no resignation, the lease must EXPIRE
        lead.kill()
        t0 = time.monotonic()
        assert wait_until(standby.is_leader, timeout=10), \
            "standby never took over"
        takeover_s = time.monotonic() - t0
        # within ~one lease period (+ heartbeat granularity)
        assert takeover_s < 3.0, takeover_s
        assert standby.controller.metrics.meter(
            "leaderFailovers").count >= 1

        # the promoted standby now RUNS the controller plane: admin
        # mutations pass its fence and reach the servers
        d2 = os.path.join(base, "seg1")
        os.makedirs(d2)
        build_segment(d2, n=700, seed=4, name="ha_seg1")
        standby.controller.manager.add_segment(TABLE, d2)
        assert wait_until(lambda: served(1500), timeout=30)
    finally:
        broker.stop()
        try:
            server.stop()
        except Exception:  # noqa: BLE001
            pass
        standby.stop()
        zk.stop()
