"""QueryRunner perf driver tests (parity: tools/perf/QueryRunner.java —
query-file replay in singleThread / multiThreads / targetQPS /
increasingQPS modes with a latency report)."""
import os
import tempfile

import pytest

from fixtures import build_segment, make_schema, make_table_config

from pinot_tpu.tools.cluster import EmbeddedCluster
from pinot_tpu.tools.perf import (PerfReport, QueryRunner, http_query_fn,
                                  load_query_file)


@pytest.fixture(scope="module")
def perf_cluster():
    base = tempfile.mkdtemp()
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2,
                              http=True)
    cluster.add_schema(make_schema())
    cluster.add_table(make_table_config())
    seg_dir = os.path.join(base, "seg")
    build_segment(seg_dir, n=2000, seed=11, name="perf_seg")
    cluster.controller.manager.add_segment("baseballStats_OFFLINE", seg_dir)
    qfile = os.path.join(base, "queries.pql")
    with open(qfile, "w") as f:
        f.write("# replay file\n"
                "SELECT COUNT(*) FROM baseballStats\n"
                "\n"
                "SELECT SUM(runs) FROM baseballStats WHERE yearID >= 1990\n"
                "SELECT COUNT(*) FROM baseballStats GROUP BY league TOP 5\n")
    yield cluster, qfile
    cluster.stop()


def test_load_query_file(perf_cluster):
    _, qfile = perf_cluster
    qs = load_query_file(qfile)
    assert len(qs) == 3 and all(q.startswith("SELECT") for q in qs)


def test_single_and_multi_thread_replay(perf_cluster):
    cluster, qfile = perf_cluster
    runner = QueryRunner(cluster.broker.handle, load_query_file(qfile))
    r = runner.single_thread(num_times=3)
    assert isinstance(r, PerfReport)
    assert r.num_queries == 9 and r.num_errors == 0
    assert r.latency_p50_ms <= r.latency_p99_ms <= r.latency_max_ms
    assert r.qps > 0

    r2 = runner.multi_threads(num_threads=4, num_times=4)
    assert r2.num_queries == 12 and r2.num_errors == 0


def test_target_and_increasing_qps(perf_cluster):
    cluster, qfile = perf_cluster
    runner = QueryRunner(cluster.broker.handle, load_query_file(qfile))
    r = runner.target_qps(qps=50, duration_s=1.0, num_threads=4)
    assert r.mode == "targetQPS" and r.target_qps == 50
    # scheduled dispatch: close to the target unless saturated; slots
    # past the deadline never run, so the window can end slightly early
    assert 10 <= r.num_queries <= 60
    assert r.duration_s <= 1.5
    rungs = runner.increasing_qps(start_qps=20, step_qps=20, steps=2,
                                  step_duration_s=0.5, num_threads=4)
    assert len(rungs) == 2
    assert rungs[1].target_qps == 40


def test_http_replay_and_error_counting(perf_cluster):
    cluster, qfile = perf_cluster
    fn = http_query_fn(f"127.0.0.1:{cluster.broker_port}")
    runner = QueryRunner(fn, load_query_file(qfile))
    r = runner.single_thread()
    assert r.num_queries == 3 and r.num_errors == 0
    bad = QueryRunner(fn, ["SELECT COUNT(*) FROM missing_table"])
    rb = bad.single_thread()
    assert rb.num_errors == 1


def test_microbench_smoke():
    """pinot-perf JMH-analogue harness runs end-to-end at smoke scale
    and emits well-formed records."""
    from pinot_tpu.tools.microbench import BENCHES, run_all

    records = run_all(scale=0.005)
    assert len(records) == len(BENCHES)
    for r in records:
        assert {"bench", "value", "unit"} <= set(r)
        assert r["value"] > 0


def test_qps_headroom_small_segments(perf_cluster):
    """The serving plane (broker compile/route/scatter/reduce + engine)
    sustains >100 QPS on small segments — the throughput-culture check
    behind QPS_r05.json (QueryRunner.java targetQPS parity)."""
    cluster, _ = perf_cluster
    qs = ["SELECT COUNT(*) FROM baseballStats",
          "SELECT SUM(runs) FROM baseballStats WHERE teamID = 'BOS'"]
    runner = QueryRunner(cluster.broker.handle, qs)
    runner.single_thread(num_times=2)        # warm the plan caches
    r = runner.single_thread(num_times=25)
    assert r.num_errors == 0
    assert r.qps > 100, str(r)
    # offered load at 100 QPS: no errors, latency stays sane
    r2 = runner.target_qps(qps=100, duration_s=1.5, num_threads=8)
    assert r2.num_errors == 0, str(r2)
    assert r2.latency_p99_ms < 1000, str(r2)
