"""Forward indexes: bit-packed dictIds, sorted ranges, raw values, multi-value.

Parity: pinot-core/.../io/reader/impl/v1/{FixedBitSingleValueReader,
FixedBitMultiValueReader,FixedByteChunkSingleValueReader}.java and the
creator-side fwd index writers (core/segment/creator/impl/fwd/). On disk we
bit-pack dictIds into uint32 words exactly like the fixed-bit format; in HBM
the loader keeps unpacked int32 lanes (TPU-native width) — the pack exists
for storage parity + compactness, the device layout is chosen for the VPU.
"""
from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from pinot_tpu.segment import format as fmt


def bits_required(cardinality: int) -> int:
    if cardinality <= 1:
        return 1
    return int(np.ceil(np.log2(cardinality))) or 1


# -- fixed-bit packing (vectorized) ---------------------------------------

def pack_bits(ids: np.ndarray, num_bits: int) -> np.ndarray:
    """Pack int32 ids (< 2**num_bits) into a dense little-endian bitstream
    stored as uint32 words.

    Pure word arithmetic: k = lcm(nb, 32)/nb ids fill exactly
    lcm(nb, 32)/32 words, so the stream is a [groups, k] view combined by
    k shift+or passes over group-scale uint64 lanes (straddling bits land
    in the next word via the uint64 carry). Measured 12x faster than the
    previous bit-matrix + np.packbits at 13 bits / 5M rows (0.13s vs
    1.56s) — the bit matrix materialized n*32 bytes and a non-contiguous
    reshape copy."""
    from pinot_tpu import native
    packed = native.pack_bits(ids, num_bits)
    if packed is not None:
        return packed
    import math
    n = len(ids)
    n_words = (n * num_bits + 31) // 32
    lcm = math.lcm(num_bits, 32)
    k = lcm // num_bits                      # ids per group
    gw = lcm // 32                           # words per group
    npad = (-n) % k
    a = np.ascontiguousarray(ids, dtype=np.uint32).astype(np.uint64)
    if npad:
        a = np.concatenate([a, np.zeros(npad, np.uint64)])
    a = a.reshape(-1, k)
    words = np.zeros((a.shape[0], gw + 1), np.uint64)
    for j in range(k):
        o = j * num_bits
        wi, sh = o // 32, o % 32
        v = a[:, j] << np.uint64(sh)
        words[:, wi] |= v & np.uint64(0xFFFFFFFF)
        if sh + num_bits > 32:
            words[:, wi + 1] |= v >> np.uint64(32)
    return words[:, :gw].astype(np.uint32).reshape(-1)[:n_words]


def unpack_bits(words: np.ndarray, num_bits: int, n: int) -> np.ndarray:
    """Inverse of pack_bits → int32[n]."""
    from pinot_tpu import native
    out = native.unpack_bits(words, num_bits, n)
    if out is not None:
        return out
    byts = np.ascontiguousarray(words, dtype="<u4").view(np.uint8)
    flat = np.unpackbits(byts, bitorder="little", count=n * num_bits)
    padded = np.zeros((n, 32), np.uint8)
    padded[:, :num_bits] = flat.reshape(n, num_bits)
    return np.packbits(padded, axis=1, bitorder="little") \
        .view("<u4").reshape(n).astype(np.int32)


# -- single-value dict-encoded --------------------------------------------

class SVForwardIndexWriter:
    @staticmethod
    def write(seg_dir: str, col: str, ids: np.ndarray, cardinality: int) -> int:
        nb = bits_required(cardinality)
        words = pack_bits(ids.astype(np.int32), nb)
        np.save(os.path.join(seg_dir, fmt.SV_FWD.format(col=col)), words)
        return nb


def read_sv_fwd(seg_dir, col: str, num_bits: int, num_docs: int
                ) -> np.ndarray:
    words = fmt.open_dir(seg_dir).load_array(fmt.SV_FWD.format(col=col))
    return unpack_bits(np.asarray(words), num_bits, num_docs)


# -- sorted column ---------------------------------------------------------

def write_sorted_fwd(seg_dir: str, col: str, ids: np.ndarray,
                     cardinality: int) -> None:
    """Sorted column forward index = per-dictId [start, end) doc ranges.

    Parity: SortedIndexReaderImpl / SingleValueSortedForwardIndexCreator.
    """
    starts = np.searchsorted(ids, np.arange(cardinality), side="left")
    ends = np.searchsorted(ids, np.arange(cardinality), side="right")
    ranges = np.stack([starts, ends], axis=1).astype(np.int32)
    np.save(os.path.join(seg_dir, fmt.SV_SORTED_FWD.format(col=col)), ranges)


def read_sorted_fwd(seg_dir, col: str) -> np.ndarray:
    return np.asarray(fmt.open_dir(seg_dir).load_array(
        fmt.SV_SORTED_FWD.format(col=col)))


# -- raw (no-dictionary) ---------------------------------------------------

def write_raw_fwd(seg_dir: str, col: str, values: np.ndarray) -> None:
    np.save(os.path.join(seg_dir, fmt.SV_RAW_FWD.format(col=col)), values)


def read_raw_fwd(seg_dir, col: str) -> np.ndarray:
    return np.asarray(fmt.open_dir(seg_dir).load_array(
        fmt.SV_RAW_FWD.format(col=col)))


# -- vector (fixed-width float32 embedding block) --------------------------

def write_vec_fwd(seg_dir: str, col: str, mat: np.ndarray) -> None:
    """Packed [num_docs, dimension] float32 forward block — the dense
    layout the batched similarity kernels read row-parallel (no
    dictionary: embeddings are effectively all-distinct, a dictionary
    would double the bytes for nothing)."""
    mat = np.ascontiguousarray(mat, dtype=np.float32)
    if mat.ndim != 2:
        raise ValueError(f"vector fwd block for '{col}' must be 2-D, "
                         f"got shape {mat.shape}")
    np.save(os.path.join(seg_dir, fmt.VEC_FWD.format(col=col)), mat)


def read_vec_fwd(seg_dir, col: str) -> np.ndarray:
    return np.asarray(fmt.open_dir(seg_dir).load_array(
        fmt.VEC_FWD.format(col=col)), dtype=np.float32)


# -- multi-value -----------------------------------------------------------

def write_mv_fwd(seg_dir: str, col: str, flat_ids: np.ndarray,
                 offsets: np.ndarray) -> None:
    """MV fwd index as CSR: flat dictIds + int64 row offsets."""
    np.save(os.path.join(seg_dir, fmt.MV_FWD.format(col=col)),
            flat_ids.astype(np.int32))
    np.save(os.path.join(seg_dir, fmt.MV_OFFSETS.format(col=col)),
            offsets.astype(np.int64))


def read_mv_fwd(seg_dir, col: str) -> Tuple[np.ndarray, np.ndarray]:
    d = fmt.open_dir(seg_dir)
    flat = np.asarray(d.load_array(fmt.MV_FWD.format(col=col)))
    offs = np.asarray(d.load_array(fmt.MV_OFFSETS.format(col=col)))
    return flat, offs


def mv_to_padded(flat_ids: np.ndarray, offsets: np.ndarray,
                 fill_value: int) -> np.ndarray:
    """CSR → dense [num_docs, max_entries] padded matrix for device kernels.

    The fill value is the column cardinality (an invalid dictId) so predicate
    kernels can mask padding with ``id < cardinality``.
    """
    counts = np.diff(offsets)
    num_docs = len(counts)
    width = int(counts.max()) if num_docs and counts.size else 1
    width = max(width, 1)
    out = np.full((num_docs, width), fill_value, dtype=np.int32)
    rows = np.repeat(np.arange(num_docs), counts)
    cols = np.arange(len(flat_ids)) - np.repeat(offsets[:-1], counts)
    out[rows, cols] = flat_ids
    return out
