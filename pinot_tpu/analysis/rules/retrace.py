"""retrace: jit inputs that defeat the compilation cache.

jax.jit caches by (shapes, dtypes, static-arg VALUES). Unhashable
Python arguments (lists/dicts/sets) raise at call time when marked
static and retrace-per-call when not; mutable defaults and mutable
module globals closed over by a jitted function bake trace-time state
into the executable (silent staleness) or retrace on every identity
change. ``jax.jit`` inside a loop builds a fresh cache per iteration —
the classic recompilation storm.
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = {"list", "dict", "set", "collections.defaultdict",
                  "collections.OrderedDict", "collections.deque"}
_UNHASHABLE_ANN = {"list", "dict", "set", "List", "Dict", "Set",
                   "typing.List", "typing.Dict", "typing.Set"}


def _is_mutable_value(node: ast.AST, aliases) -> bool:
    if isinstance(node, _MUTABLE_LITERALS):
        return True
    if isinstance(node, ast.Call):
        return astutil.resolve(node.func, aliases) in _MUTABLE_CTORS
    return False


def _module_mutable_globals(tree: ast.Module, aliases) -> Set[str]:
    names: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and \
                _is_mutable_value(stmt.value, aliases):
            names.update(t.id for t in stmt.targets
                         if isinstance(t, ast.Name))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name) and \
                _is_mutable_value(stmt.value, aliases):
            names.add(stmt.target.id)
    return names


@register
class RetraceRule(Rule):
    id = "retrace"
    description = ("jitted functions taking unhashable/mutable Python "
                   "args, closing over mutable state, or jax.jit built "
                   "inside a loop")

    def check(self, ctx) -> Iterator[Finding]:
        mutable_globals = _module_mutable_globals(ctx.tree, ctx.aliases)
        for fn in astutil.iter_functions(ctx.tree):
            if astutil.is_jitted(fn, ctx.aliases):
                yield from self._check_jitted_fn(ctx, fn, mutable_globals)
        yield from self._check_jit_in_loop(ctx)

    def _check_jitted_fn(self, ctx, fn, mutable_globals: Set[str]
                         ) -> Iterator[Finding]:
        # (a) mutable defaults — unhashable as static, identity-keyed as
        # traced: either way the cache can never hit
        args = fn.args
        all_defaults = list(args.defaults) + list(args.kw_defaults or [])
        for d in all_defaults:
            if d is not None and _is_mutable_value(d, ctx.aliases):
                yield ctx.finding(
                    self.id, d,
                    f"jitted `{fn.name}` has a mutable default argument — "
                    "unhashable under static_argnums, retraces otherwise")
        # (b) parameters annotated as unhashable containers
        for a in list(args.args) + list(args.kwonlyargs) + \
                list(getattr(args, "posonlyargs", [])):
            if a.annotation is None:
                continue
            ann = a.annotation
            if isinstance(ann, ast.Subscript):
                ann = ann.value
            d = astutil.resolve(ann, ctx.aliases)
            if d in _UNHASHABLE_ANN:
                yield ctx.finding(
                    self.id, a,
                    f"jitted `{fn.name}` takes `{a.arg}: {d}` — "
                    "unhashable Python container as a jit argument "
                    "(pass a tuple, or restructure as a pytree leaf)")
        # (c) closing over mutable module state / object attributes
        reported: Set[str] = set()
        local_names = {a.arg for a in list(args.args) +
                       list(args.kwonlyargs) +
                       list(getattr(args, "posonlyargs", []))}
        for node in astutil.walk_shallow(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_globals and \
                    node.id not in local_names and node.id not in reported:
                reported.add(node.id)
                yield ctx.finding(
                    self.id, node,
                    f"jitted `{fn.name}` closes over mutable module "
                    f"global `{node.id}` — its trace-time contents are "
                    "baked into the executable")
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and "self" not in reported:
                reported.add("self")
                yield ctx.finding(
                    self.id, node,
                    f"jitted `{fn.name}` reads `self.{node.attr}` — "
                    "object state freezes at trace time and keys no "
                    "cache entry (jit a pure function of its inputs)")

    def _check_jit_in_loop(self, ctx) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.Call) and \
                        astutil.is_jit_expr(node, ctx.aliases):
                    yield ctx.finding(
                        self.id, node,
                        "jax.jit constructed inside a loop — every "
                        "iteration builds a fresh cache (hoist the jit "
                        "out of the loop)")
