"""Networked property store tests: server, client, watches, ephemerals.

Parity: the ZooKeeper role in the reference — remote cluster-state store
with watch push and ephemeral-node liveness (docs/architecture.rst).
"""
import threading
import time

import pytest

from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.controller.store_client import (RemotePropertyStore,
                                               StoreClosedError)
from pinot_tpu.controller.store_server import PropertyStoreServer


@pytest.fixture()
def server():
    srv = PropertyStoreServer()
    srv.start()
    yield srv
    srv.stop()


def _client(server, **kw):
    return RemotePropertyStore("127.0.0.1", server.port, **kw)


def test_basic_ops_roundtrip(server):
    c = _client(server)
    try:
        assert c.get("/a") is None
        c.set("/a/b", {"x": 1})
        c.set("/a/c", {"y": [1, 2, {"z": "s"}]})
        assert c.get("/a/b") == {"x": 1}
        assert c.get("/a/c") == {"y": [1, 2, {"z": "s"}]}
        assert c.children("/a") == ["b", "c"]
        assert c.list_paths("/a") == ["/a/b", "/a/c"]
        assert c.remove("/a/b") is True
        assert c.remove("/a/b") is False
        assert c.get("/a/b") is None
    finally:
        c.close()


def test_update_cas_loop_under_contention(server):
    n_threads, n_incr = 4, 25
    clients = [_client(server) for _ in range(n_threads)]
    try:
        clients[0].set("/counter", {"n": 0})

        def bump(c):
            for _ in range(n_incr):
                c.update("/counter", lambda rec: {"n": (rec or {"n": 0})["n"]
                                                  + 1})

        threads = [threading.Thread(target=bump, args=(c,))
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert clients[0].get("/counter") == {"n": n_threads * n_incr}
    finally:
        for c in clients:
            c.close()


def test_watch_push_across_clients(server):
    a, b = _client(server), _client(server)
    try:
        events = []
        got = threading.Event()

        def cb(path, rec):
            events.append((path, rec))
            if len(events) >= 3:
                got.set()

        a.watch("/EXTERNALVIEW/", cb)
        b.set("/EXTERNALVIEW/t1", {"segments": {"s0": {"i0": "ONLINE"}}})
        b.set("/OTHER/t1", {"ignored": True})   # outside prefix: no event
        b.set("/EXTERNALVIEW/t2", {"segments": {}})
        b.remove("/EXTERNALVIEW/t1")
        assert got.wait(5), events
        assert events[0] == ("/EXTERNALVIEW/t1",
                             {"segments": {"s0": {"i0": "ONLINE"}}})
        assert events[1] == ("/EXTERNALVIEW/t2", {"segments": {}})
        assert events[2] == ("/EXTERNALVIEW/t1", None)
    finally:
        a.close()
        b.close()


def test_ephemeral_paths_vanish_on_disconnect(server):
    a, b = _client(server), _client(server)
    try:
        seen = []
        gone = threading.Event()

        def cb(path, rec):
            seen.append((path, rec))
            if rec is None:
                gone.set()

        b.watch("/LIVEINSTANCES/", cb)
        a.set("/LIVEINSTANCES/Server_9", {"tags": ["T"]}, ephemeral=True)
        a.set("/CONFIGS/stay", {"k": 1})          # persistent
        deadline = time.monotonic() + 5
        while b.get("/LIVEINSTANCES/Server_9") is None:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        a.close()                                  # session death
        assert gone.wait(5), seen
        assert b.get("/LIVEINSTANCES/Server_9") is None
        assert b.get("/CONFIGS/stay") == {"k": 1}  # persists
    finally:
        b.close()


def test_shared_store_with_inprocess_side(server):
    """The controller holds the in-process store; remote clients see the
    same tree (the deployment shape: store server runs in the controller)."""
    local: PropertyStore = server.store
    c = _client(server)
    try:
        local.set("/CONFIGS/TABLE/t", {"v": 1})
        assert c.get("/CONFIGS/TABLE/t") == {"v": 1}
        c.set("/CONFIGS/TABLE/u", {"v": 2})
        assert local.get("/CONFIGS/TABLE/u") == {"v": 2}
        # watches registered locally fire for remote writes
        fired = threading.Event()
        local.watch("/CONFIGS/", lambda p, r: fired.set())
        c.set("/CONFIGS/TABLE/w", {"v": 3})
        assert fired.wait(5)
    finally:
        c.close()


def test_client_errors(server):
    c = _client(server)
    try:
        with pytest.raises(ConnectionError):
            RemotePropertyStore("127.0.0.1", 1)    # nothing listening
    finally:
        c.close()
    with pytest.raises(StoreClosedError):
        c.get("/x")                                # after close


def test_local_cas_semantics():
    s = PropertyStore()
    assert s.cas("/p", None, {"v": 1}) is True
    assert s.cas("/p", None, {"v": 2}) is False
    assert s.cas("/p", {"v": 1}, {"v": 2}) is True
    assert s.get("/p") == {"v": 2}


def test_bind_conflict_raises_instead_of_hanging(server):
    s2 = PropertyStoreServer(port=server.port)
    with pytest.raises(OSError, match="cannot bind"):
        s2.start()


def test_watches_survive_peer_session_death_and_reconnect(server):
    """One client's session death must not tear down other clients'
    watches, and a reconnecting client re-registers its watches and
    receives subsequent events."""
    observer, writer = _client(server), _client(server)
    try:
        events = []
        got = threading.Event()

        def cb(path, rec):
            events.append((path, rec))
            got.set()

        observer.watch("/SEGMENTS/", cb)
        writer.set("/SEGMENTS/t/s0", {"i": 0})
        assert got.wait(5)
        # the writer's session dies; the observer's watch must survive
        writer.close()
        got.clear()
        writer2 = _client(server)
        writer2.set("/SEGMENTS/t/s1", {"i": 1})
        assert got.wait(5), "watch died with an unrelated session"
        assert ("/SEGMENTS/t/s1", {"i": 1}) in events
        # the observer reconnects: a fresh session re-registers the
        # watch and receives events again
        observer.close()
        observer2 = _client(server)
        events2 = []
        got2 = threading.Event()
        observer2.watch("/SEGMENTS/", lambda p, r: (events2.append((p, r)),
                                                    got2.set()))
        writer2.set("/SEGMENTS/t/s2", {"i": 2})
        assert got2.wait(5)
        assert events2[-1] == ("/SEGMENTS/t/s2", {"i": 2})
        observer2.close()
        writer2.close()
    finally:
        pass


def test_session_death_mid_update_applies_at_most_once(server):
    """The mutation lands but the confirmation is lost (connection dies
    between the server applying a CAS and the response frame): the
    client's update() must RAISE — never silently retry into a double
    apply — and a reconnected session sees exactly one application."""
    c = _client(server)
    c.set("/counter", {"n": 0})
    orig_cas = server.store.cas

    def killing_cas(path, expected, record, ephemeral=False):
        applied = orig_cas(path, expected, record, ephemeral=ephemeral)
        # runs on the server's event-loop thread: abort the transport
        # before the response can be written
        for conn in list(server.connections):
            conn.writer.transport.abort()
        return applied

    server.store.cas = killing_cas
    try:
        with pytest.raises((StoreClosedError, RuntimeError, OSError)):
            c.update("/counter",
                     lambda rec: {"n": (rec or {"n": 0})["n"] + 1})
    finally:
        server.store.cas = orig_cas
    c.close()
    # a fresh session observes the mutation applied exactly once, and an
    # explicit caller-level retry applies exactly once more
    c2 = _client(server)
    try:
        assert c2.get("/counter") == {"n": 1}
        c2.update("/counter", lambda rec: {"n": rec["n"] + 1})
        assert c2.get("/counter") == {"n": 2}
    finally:
        c2.close()


def test_ephemeral_set_then_durable_set_keeps_durability(tmp_path):
    """A durable set over a path previously written ephemeral makes the
    record durable again (and vice versa the ephemeral shadow is not
    replayed) — the journaling follows the LATEST write's class."""
    d = str(tmp_path / "store")
    s = PropertyStore(data_dir=d)
    s.set("/FLAGS/x", {"v": 1}, ephemeral=True)
    s.set("/FLAGS/x", {"v": 2})              # now durable
    s.set("/FLAGS/y", {"v": 3})
    s.set("/FLAGS/y", {"v": 4}, ephemeral=True)   # durable shadowed
    # update() and cas() follow the same latest-write-wins class rules
    s.set("/FLAGS/u", {"v": 5}, ephemeral=True)
    s.update("/FLAGS/u", lambda old: {"v": 6})    # durable again
    s.set("/FLAGS/c", {"v": 7}, ephemeral=True)
    assert s.cas("/FLAGS/c", {"v": 7}, {"v": 8})  # durable again
    s.set("/FLAGS/cz", {"v": 9})
    assert s.cas("/FLAGS/cz", {"v": 9}, {"v": 10},
                 ephemeral=True)                  # durable shadowed
    s.close()
    r = PropertyStore(data_dir=d)
    assert r.get("/FLAGS/x") == {"v": 2}
    assert r.get("/FLAGS/y") is None
    assert r.get("/FLAGS/u") == {"v": 6}
    assert r.get("/FLAGS/c") == {"v": 8}
    assert r.get("/FLAGS/cz") is None
    r.close()


def test_malformed_frame_keeps_connection_alive(server):
    import json
    import socket
    import struct

    sock = socket.create_connection(("127.0.0.1", server.port))
    try:
        bad = b"not json"
        sock.sendall(struct.pack(">I", len(bad)) + bad)
        n = struct.unpack(">I", sock.recv(4))[0]
        resp = json.loads(sock.recv(n))
        assert resp["ok"] is False and resp["id"] is None
        good = json.dumps({"id": 7, "op": "ping"}).encode()
        sock.sendall(struct.pack(">I", len(good)) + good)
        n = struct.unpack(">I", sock.recv(4))[0]
        assert json.loads(sock.recv(n)) == {"id": 7, "ok": True}
    finally:
        sock.close()
