"""Server-side query executor.

Parity: pinot-core/.../query/executor/ServerQueryExecutorV1Impl.java:100-267 —
acquire segments → prune → plan → execute per segment → combine → result
block with execution stats. Device-unsupported query shapes fall back to the
host (numpy) executor per segment, the way the reference falls back from
index-based to scan-based operators.

Per-segment execution fans out on the scheduler's query-worker pool
(CombineOperator parity: per-segment plans on an ExecutorService,
CombineOperator.java:27). Device dispatches serialize on the chip anyway,
so the workers overlap host-side planning/decoding/finishing with device
work — the win the reference gets from planNodes.parallelStream().
"""
from __future__ import annotations

import concurrent.futures
import time
from typing import List, Optional, Tuple

from pinot_tpu.common.metrics import ServerQueryPhase
from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.obs import profiler as obs_profiler
from pinot_tpu.obs.profiler import QueryProfile, obs_span
from pinot_tpu.obs.tracing import TraceContext, make_trace_context
from pinot_tpu.query.blocks import IntermediateResultsBlock
from pinot_tpu.query.combine import combine_blocks
from pinot_tpu.query import host_exec
from pinot_tpu.query.plan import (GroupsLimitExceeded, InstancePlanMaker,
                                  UnsupportedOnDevice)
from pinot_tpu.query.pruner import SegmentPrunerService
from pinot_tpu.segment.loader import ImmutableSegment


class ServerQueryExecutor:
    def __init__(self, plan_maker: Optional[InstancePlanMaker] = None,
                 pruner: Optional[SegmentPrunerService] = None,
                 use_device: bool = True,
                 segment_executor: Optional[
                     concurrent.futures.Executor] = None):
        self.plan_maker = plan_maker or InstancePlanMaker()
        self.pruner = pruner or SegmentPrunerService()
        self.use_device = use_device
        # the scheduler's query-worker pool; None → sequential loop
        self.segment_executor = segment_executor
        # residency gates (server/residency_manager.py): device_gate
        # routes host/disk-tier segments through host_exec instead of
        # the device kernels; mutable_gate blocks frozen-snapshot
        # uploads under HBM pressure. None (the default) keeps the
        # ungated device-first behavior.
        self.device_gate = None
        self.mutable_gate = None

    def execute(self, request: BrokerRequest,
                segments: List[ImmutableSegment],
                trace: Optional[TraceContext] = None,
                deadline: Optional[float] = None
                ) -> IntermediateResultsBlock:
        """`deadline`: absolute time.monotonic() instant; the
        per-segment fan-out stops (with an honest truncation exception)
        once it passes — a deadline-expired query must not keep a
        worker pinned computing rows its broker stopped listening for."""
        trace = trace if trace is not None else make_trace_context(False)
        # keep whatever ambient profile the instance layer activated;
        # direct callers (engine, tests) get a private throwaway so the
        # per-dispatch accounting hooks always have a target
        ambient = obs_profiler.current()
        profile = ambient[0] if ambient is not None else \
            QueryProfile(request.table_name)
        with obs_profiler.active(profile, trace):
            return self._execute(request, segments, trace, deadline)

    def _execute(self, request: BrokerRequest,
                 segments: List[ImmutableSegment],
                 trace: TraceContext,
                 deadline: Optional[float]) -> IntermediateResultsBlock:
        t0 = time.perf_counter()
        from pinot_tpu.query.plan import preprocess_request
        # FASTHLL derived rewrite — returns a copy when it rewrites, so
        # the broker's shared request never changes under our feet
        request = preprocess_request(segments, request)
        with trace.span(ServerQueryPhase.SEGMENT_PRUNING):
            selected = self.pruner.prune(segments, request)
        num_pruned = len(segments) - len(selected)

        from pinot_tpu.query.plan import upsert_mask_active
        if request.is_aggregation and not request.is_selection and \
                len(selected) > 1 and \
                not any(upsert_mask_active(s) for s in selected) and \
                all(getattr(s, "star_trees", None) for s in selected):
            from pinot_tpu.startree.executor import \
                try_star_tree_execute_multi
            blk = try_star_tree_execute_multi(selected, request)
            if blk is not None:
                obs_profiler.count_path("cube", len(selected))
                blk.stats.num_segments_pruned = num_pruned
                blk.stats.time_used_ms = (time.perf_counter() - t0) * 1e3
                return blk

        with trace.span(ServerQueryPhase.SEGMENT_EXECUTION):
            if self.segment_executor is not None and len(selected) > 1:
                blocks, extra_parts, extra_matched, executed = \
                    self._run_parallel(selected, request, deadline, trace)
            else:
                blocks, extra_parts, extra_matched, executed = \
                    self._run_sequential(selected, request, deadline)
        truncated = executed < len(selected)

        if not blocks:
            blk = IntermediateResultsBlock()
            if request.is_group_by:
                blk.group_map = {}
            elif request.is_aggregation:
                blk.agg_intermediates = None
            if request.is_selection:
                blk.selection_rows = []
                blk.selection_columns = list(request.selection.columns)
                if request.vector is not None:
                    from pinot_tpu.common.request import \
                        VECTOR_RESULT_COLUMNS
                    blk.selection_columns += list(VECTOR_RESULT_COLUMNS)
        else:
            blk = combine_blocks(request, blocks)
        if truncated:
            blk.exceptions.append(
                "DeadlineExceededError: segment execution truncated at "
                f"{executed}/{len(selected)} segments (budget "
                "expired mid-query)")
        if extra_parts:
            # frozen+tail pairs are ONE logical consuming segment: both
            # processed always, matched only when both halves matched
            blk.stats.num_segments_processed -= extra_parts
            blk.stats.num_segments_matched -= extra_matched
        # realtime freshness over the consuming segments this query saw
        # (parity: ServerQueryExecutorV1Impl minConsumingFreshness)
        consuming_ts = [int(s_.last_indexed_time_ms) for s_ in selected
                        if getattr(s_, "is_mutable", False) and
                        hasattr(s_, "last_indexed_time_ms")]
        blk.stats.num_consuming_segments_processed = len(consuming_ts)
        if consuming_ts:
            blk.stats.min_consuming_freshness_ms = min(consuming_ts)
        blk.stats.num_segments_pruned = num_pruned
        blk.stats.time_used_ms = (time.perf_counter() - t0) * 1e3
        return blk

    # -- per-segment work ---------------------------------------------------
    def _segment_work(self, seg, request: BrokerRequest
                      ) -> Tuple[List[IntermediateResultsBlock], int, int]:
        """Execute ONE logical segment; returns (blocks, extra_parts,
        extra_matched) — a consuming segment's frozen+tail pair yields
        two blocks that stay paired for stats accounting."""
        with obs_span("segment",
                      segment=getattr(seg, "segment_name", "?")):
            return self._segment_work_inner(seg, request)

    def _segment_work_inner(self, seg, request: BrokerRequest
                            ) -> Tuple[List[IntermediateResultsBlock],
                                       int, int]:
        if self.use_device and getattr(seg, "is_mutable", False) and \
                hasattr(seg, "device_view") and \
                (self.mutable_gate is None or self.mutable_gate(seg)):
            # consuming segment: the periodic sorted snapshot serves the
            # frozen prefix on the DEVICE kernels and the post-freeze
            # tail host-side; the two parts combine like any other pair
            # of segments (reference: consuming segments are first-class
            # engine targets, MutableSegmentImpl.java:64-198)
            frozen, tail = seg.device_view()
            blocks: List[IntermediateResultsBlock] = []
            fb = tb = None
            if frozen is not None:
                fb = self._execute_segment(frozen, request)
                blocks.append(fb)
            if tail.num_docs > 0 or frozen is None:
                tb = self._execute_segment(tail, request)
                blocks.append(tb)
            if fb is not None and tb is not None:
                matched = 1 if (fb.stats.num_segments_matched and
                                tb.stats.num_segments_matched) else 0
                return blocks, 1, matched
            return blocks, 0, 0
        if getattr(seg, "is_mutable", False) and \
                hasattr(seg, "snapshot_view"):
            # consuming segment: freeze (num_docs, cardinalities) so the
            # filter mask and every column lane agree while the consumer
            # thread keeps appending
            seg = seg.snapshot_view()
        return [self._execute_segment(seg, request)], 0, 0

    def _run_sequential(self, selected, request: BrokerRequest,
                        deadline: Optional[float]):
        blocks: List[IntermediateResultsBlock] = []
        extra_parts = extra_matched = 0
        executed = 0
        for seg in selected:
            if deadline is not None and time.monotonic() >= deadline:
                break
            segment_blocks, parts, matched = self._segment_work(seg,
                                                                request)
            blocks.extend(segment_blocks)
            extra_parts += parts
            extra_matched += matched
            executed += 1
        return blocks, extra_parts, extra_matched, executed

    def _run_parallel(self, selected, request: BrokerRequest,
                      deadline: Optional[float],
                      trace: Optional[TraceContext] = None):
        """CombineOperator parity: every segment plan runs as a task on
        the scheduler's query-worker pool while this (runner) thread
        gathers. Deadline truncation: tasks not yet started when the
        budget expires return unexecuted (the pool's queue order makes
        "stop submitting" and "reject on pick-up" equivalent), and the
        gather abandons stragglers instead of waiting past the deadline.
        """
        # worker threads don't inherit the runner's ambient profile or
        # its span stack — capture both here, re-establish per task so
        # per-segment spans parent under segmentExecution and dispatch
        # accounting lands on the right query's profile
        ambient = obs_profiler.current()
        parent_id = trace.current_span_id() if trace is not None else None

        def work(seg):
            if deadline is not None and time.monotonic() >= deadline:
                return None                 # budget gone before start
            with obs_profiler.reactivate(ambient):
                if trace is not None and trace.enabled:
                    with trace.attach(parent_id):
                        return self._segment_work(seg, request)
                return self._segment_work(seg, request)

        futures = [self.segment_executor.submit(work, seg)
                   for seg in selected]
        results: List[Optional[tuple]] = [None] * len(selected)
        abandoned = False
        for i, fut in enumerate(futures):
            if abandoned:
                fut.cancel()
                continue
            budget = None if deadline is None else \
                deadline - time.monotonic()
            try:
                results[i] = fut.result(
                    timeout=None if budget is None else max(budget, 0.0))
            except concurrent.futures.TimeoutError:
                # budget expired mid-gather: abandon this straggler and
                # cancel everything not yet started; whatever already
                # finished still counts (drain-what's-done semantics)
                abandoned = True
                fut.cancel()
        if abandoned:
            for i, fut in enumerate(futures):
                if results[i] is None and fut.done() and \
                        not fut.cancelled():
                    try:
                        results[i] = fut.result(timeout=0)
                    except (concurrent.futures.TimeoutError,
                            concurrent.futures.CancelledError):
                        pass
        blocks: List[IntermediateResultsBlock] = []
        extra_parts = extra_matched = 0
        executed = 0
        for res in results:
            if res is None:
                continue
            segment_blocks, parts, matched = res
            blocks.extend(segment_blocks)
            extra_parts += parts
            extra_matched += matched
            executed += 1
        return blocks, extra_parts, extra_matched, executed

    def _execute_segment(self, segment: ImmutableSegment,
                         request: BrokerRequest) -> IntermediateResultsBlock:
        from pinot_tpu.query.plan import upsert_mask_active
        if request.is_aggregation and not request.is_selection and \
                not upsert_mask_active(segment) and \
                getattr(segment, "star_trees", None):
            from pinot_tpu.startree.executor import try_star_tree_execute
            blk = try_star_tree_execute(segment, request)
            if blk is not None:
                obs_profiler.count_path("cube")
                return blk
        if self.use_device and \
                (self.device_gate is None or self.device_gate(segment)):
            try:
                with obs_span(ServerQueryPhase.BUILD_QUERY_PLAN):
                    plan = self.plan_maker.make_segment_plan(segment,
                                                             request)
                with obs_span(ServerQueryPhase.QUERY_PLAN_EXECUTION):
                    blk = plan.execute()
                obs_profiler.count_path("scan")
                return blk
            except (GroupsLimitExceeded, UnsupportedOnDevice):
                pass
        obs_profiler.count_path("host")
        return host_exec.execute_host(segment, request)

    # -- cross-query batched execution --------------------------------------
    def execute_batch(self, requests: List[BrokerRequest],
                      segments: List[ImmutableSegment],
                      trace: Optional[TraceContext] = None,
                      deadline: Optional[float] = None
                      ) -> List[IntermediateResultsBlock]:
        """Execute N same-shape requests over one segment set, sharing
        device dispatches wherever their per-segment plans compile to
        equal specs (query/plan.py:batch_signature).

        The coalescer (server/scheduler.py) guarantees the members
        share a table, segment list, and plan-shape key; this layer
        still prunes/plans per member (literals steer pruning and can
        constant-fold a plan) and re-groups by COMPILED signature, so a
        key collision degrades to sequential execution, never to a
        wrong answer. Members that fall off the batchable path (star
        trees, mutable segments, host fallback, group-by) run exactly
        the sequential ladder. Returns blocks aligned with `requests`.
        """
        trace = trace if trace is not None else make_trace_context(False)
        ambient = obs_profiler.current()
        profile = ambient[0] if ambient is not None else \
            QueryProfile(requests[0].table_name if requests else "?")
        with obs_profiler.active(profile, trace):
            return self._execute_batch(requests, segments, deadline)

    def _execute_batch(self, requests, segments, deadline):
        t0 = time.perf_counter()
        from pinot_tpu.query.plan import (preprocess_request,
                                          upsert_mask_active)
        members = []
        for req in requests:
            req = preprocess_request(segments, req)
            selected = self.pruner.prune(segments, req)
            members.append(_BatchMember(req, selected, len(segments)))

        # per-member multi-segment star-tree fast path (mirrors
        # _execute; a member it answers never reaches the batch loop)
        for m in members:
            req, selected = m.request, m.selected
            if req.is_aggregation and not req.is_selection and \
                    len(selected) > 1 and \
                    not any(upsert_mask_active(s) for s in selected) and \
                    all(getattr(s, "star_trees", None) for s in selected):
                from pinot_tpu.startree.executor import \
                    try_star_tree_execute_multi
                blk = try_star_tree_execute_multi(selected, req)
                if blk is not None:
                    obs_profiler.count_path("cube", len(selected))
                    m.final = blk
        pending = [m for m in members if m.final is None]

        for seg in segments:
            if deadline is not None and time.monotonic() >= deadline:
                break
            takers = [m for m in pending if id(seg) in m.selected_ids]
            if not takers:
                continue
            self._batch_segment(seg, takers)
            for m in takers:
                m.executed += 1

        return [m.final if m.final is not None
                else m.finish(t0) for m in members]

    def _batch_segment(self, seg, takers) -> None:
        """One segment, many members: batch the plans whose compiled
        signatures agree, run everything else down the sequential
        ladder unchanged."""
        from pinot_tpu.query import execution
        from pinot_tpu.query.plan import batch_signature

        if getattr(seg, "is_mutable", False) or not self.use_device or \
                (self.device_gate is not None and
                 not self.device_gate(seg)):
            # consuming segments (frozen/tail or snapshot views) and
            # gated-off-device segments keep their per-member path
            for m in takers:
                m.add(*self._segment_work(seg, m.request))
            return

        groups: dict = {}
        for m in takers:
            blk = self._try_star_tree(seg, m.request)
            if blk is not None:
                m.add([blk], 0, 0)
                continue
            try:
                with obs_span(ServerQueryPhase.BUILD_QUERY_PLAN):
                    plan = self.plan_maker.make_segment_plan(seg,
                                                             m.request)
            except (GroupsLimitExceeded, UnsupportedOnDevice):
                obs_profiler.count_path("host")
                m.add([host_exec.execute_host(seg, m.request)], 0, 0)
                continue
            sig = batch_signature(plan)
            if sig is None:
                # fast-path / group-by plans execute per member
                try:
                    with obs_span(ServerQueryPhase.QUERY_PLAN_EXECUTION):
                        blk = plan.execute()
                    obs_profiler.count_path("scan")
                except (GroupsLimitExceeded, UnsupportedOnDevice):
                    obs_profiler.count_path("host")
                    blk = host_exec.execute_host(seg, m.request)
                m.add([blk], 0, 0)
                continue
            groups.setdefault(sig, []).append((m, plan))

        for group in groups.values():
            plans = [plan for _, plan in group]
            with obs_span(ServerQueryPhase.QUERY_PLAN_EXECUTION):
                blocks = execution.execute_segment_plans_batched(plans)
            obs_profiler.count_path("scan", len(group))
            for (m, _), blk in zip(group, blocks):
                m.add([blk], 0, 0)

    def _try_star_tree(self, segment, request):
        from pinot_tpu.query.plan import upsert_mask_active
        if request.is_aggregation and not request.is_selection and \
                not upsert_mask_active(segment) and \
                getattr(segment, "star_trees", None):
            from pinot_tpu.startree.executor import try_star_tree_execute
            blk = try_star_tree_execute(segment, request)
            if blk is not None:
                obs_profiler.count_path("cube")
                return blk
        return None


class _BatchMember:
    """Per-request accumulator for the batched execution loop."""
    __slots__ = ("request", "selected", "selected_ids", "num_pruned",
                 "blocks", "extra_parts", "extra_matched", "executed",
                 "final")

    def __init__(self, request, selected, num_total: int):
        self.request = request
        self.selected = selected
        self.selected_ids = {id(s) for s in selected}
        self.num_pruned = num_total - len(selected)
        self.blocks: List[IntermediateResultsBlock] = []
        self.extra_parts = 0
        self.extra_matched = 0
        self.executed = 0
        self.final: Optional[IntermediateResultsBlock] = None

    def add(self, blocks, parts: int, matched: int) -> None:
        self.blocks.extend(blocks)
        self.extra_parts += parts
        self.extra_matched += matched

    def finish(self, t0: float) -> IntermediateResultsBlock:
        """Combine + stats, mirroring ServerQueryExecutor._execute's
        tail for one member."""
        request = self.request
        if not self.blocks:
            blk = IntermediateResultsBlock()
            if request.is_group_by:
                blk.group_map = {}
            elif request.is_aggregation:
                blk.agg_intermediates = None
            if request.is_selection:
                blk.selection_rows = []
                blk.selection_columns = list(request.selection.columns)
                if request.vector is not None:
                    from pinot_tpu.common.request import \
                        VECTOR_RESULT_COLUMNS
                    blk.selection_columns += list(VECTOR_RESULT_COLUMNS)
        else:
            blk = combine_blocks(request, self.blocks)
        if self.executed < len(self.selected):
            blk.exceptions.append(
                "DeadlineExceededError: segment execution truncated at "
                f"{self.executed}/{len(self.selected)} segments (budget "
                "expired mid-query)")
        if self.extra_parts:
            blk.stats.num_segments_processed -= self.extra_parts
            blk.stats.num_segments_matched -= self.extra_matched
        consuming_ts = [int(s_.last_indexed_time_ms)
                        for s_ in self.selected
                        if getattr(s_, "is_mutable", False) and
                        hasattr(s_, "last_indexed_time_ms")]
        blk.stats.num_consuming_segments_processed = len(consuming_ts)
        if consuming_ts:
            blk.stats.min_consuming_freshness_ms = min(consuming_ts)
        blk.stats.num_segments_pruned = self.num_pruned
        blk.stats.time_used_ms = (time.perf_counter() - t0) * 1e3
        return blk
