"""Segment pruners: skip segments that cannot match a query.

Parity: pinot-core/.../query/pruner/ — ColumnValueSegmentPruner
(min/max range rejection on EQ/RANGE + bloom-filter rejection,
ColumnValueSegmentPruner.java:58-63), DataSchemaSegmentPruner,
ValidSegmentPruner; orchestrated by SegmentPrunerService.
"""
from __future__ import annotations

from typing import List, Optional

from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.segment.loader import ImmutableSegment


class SegmentPrunerService:
    def __init__(self, pruners: Optional[List] = None):
        self.pruners = pruners if pruners is not None else [
            ValidSegmentPruner(), DataSchemaSegmentPruner(),
            ColumnValueSegmentPruner(), PartitionSegmentPruner()]

    def prune(self, segments: List[ImmutableSegment], request: BrokerRequest
              ) -> List[ImmutableSegment]:
        out = segments
        for p in self.pruners:
            out = [s for s in out if not p.prune(s, request)]
        return out


class ValidSegmentPruner:
    def prune(self, segment: ImmutableSegment, request: BrokerRequest) -> bool:
        return segment.num_docs == 0


class DataSchemaSegmentPruner:
    def prune(self, segment: ImmutableSegment, request: BrokerRequest) -> bool:
        for col in request.referenced_columns():
            if not segment.has_column(col):
                return True
        return False


def _bloom_key(cm, literal: str):
    """Coerce a query literal to the column's numpy dtype before hashing so
    it str()-normalizes identically to the values the builder added (e.g.
    '5' on a FLOAT column must hash as '5.0', not '5')."""
    dt = cm.data_type.np_dtype
    try:
        if dt.kind == "f":
            return dt.type(float(literal))
        if dt.kind in "iu":
            return dt.type(int(str(literal)))
    except (ValueError, OverflowError):
        pass
    return literal


class ColumnValueSegmentPruner:
    def prune(self, segment: ImmutableSegment, request: BrokerRequest) -> bool:
        return self._prune_node(segment, request.filter)

    def _prune_node(self, segment: ImmutableSegment,
                    node: Optional[FilterQueryTree]) -> bool:
        if node is None:
            return False
        if node.operator == FilterOperator.AND:
            return any(self._prune_node(segment, c) for c in node.children)
        if node.operator == FilterOperator.OR:
            return all(self._prune_node(segment, c) for c in node.children)
        if node.operator not in (FilterOperator.EQUALITY, FilterOperator.RANGE):
            return False
        from pinot_tpu.common.expression import is_expression
        if is_expression(node.column):
            return False    # no min/max metadata for transformed values
        ds = segment.data_source(node.column)
        cm = ds.metadata
        if cm.min_value is None or cm.max_value is None or \
                not cm.data_type.is_numeric:
            if node.operator == FilterOperator.EQUALITY and \
                    ds.bloom_filter is not None:
                return not ds.bloom_filter.might_contain(
                    _bloom_key(cm, node.values[0]))
            return False
        mn, mx = float(cm.min_value), float(cm.max_value)
        if node.operator == FilterOperator.EQUALITY:
            try:
                v = float(node.values[0])
            except ValueError:
                return False
            if v < mn or v > mx:
                return True
            if ds.bloom_filter is not None:
                return not ds.bloom_filter.might_contain(
                    _bloom_key(cm, node.values[0]))
            return False
        # RANGE: prune when the query interval is disjoint from [min, max]
        if node.lower is not None:
            lo = float(node.lower)
            if lo > mx or (lo == mx and not node.lower_inclusive):
                return True
        if node.upper is not None:
            hi = float(node.upper)
            if hi < mn or (hi == mn and not node.upper_inclusive):
                return True
        return False


class PartitionSegmentPruner:
    """Prune segments whose partition-id set cannot contain an EQ literal.

    Parity: core/query/pruner/PartitionSegmentPruner — the segment's
    column metadata records the partition function + ids present; an
    equality predicate on a partitioned column maps the literal to its
    partition and skips segments that never stored that partition.
    """

    def prune(self, segment: ImmutableSegment,
              request: BrokerRequest) -> bool:
        return self._prune_node(segment, request.filter)

    def _prune_node(self, segment: ImmutableSegment,
                    node: Optional[FilterQueryTree]) -> bool:
        if node is None:
            return False
        if node.operator == FilterOperator.AND:
            return any(self._prune_node(segment, c) for c in node.children)
        if node.operator == FilterOperator.OR:
            return all(self._prune_node(segment, c) for c in node.children)
        if node.operator != FilterOperator.EQUALITY:
            return False
        from pinot_tpu.common.expression import is_expression
        if is_expression(node.column) or not segment.has_column(node.column):
            return False
        cm = segment.data_source(node.column).metadata
        if not cm.partition_function or not cm.partitions:
            return False
        from pinot_tpu.common.partition import partition_of_value
        try:
            p = partition_of_value(cm.partition_function,
                                   cm.num_partitions,
                                   cm.data_type.np_dtype, node.values[0])
        except Exception:  # noqa: BLE001 — unknown function/bad metadata:
            return False   # fail open (never wrongly drop a segment)
        return p not in set(cm.partitions)
