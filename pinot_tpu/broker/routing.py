"""Broker routing: external view → pre-computed routing tables.

Parity: pinot-broker/.../routing/ — HelixExternalViewBasedRouting.java:70
(rebuild on external-view change) + builder/BaseRoutingTableBuilder
(N pre-computed routing tables, random pick per query) +
BalancedRandomRoutingTableBuilder.java:36 and the partition-aware variants
(PartitionAwareOfflineRoutingTableBuilder.java:69 — replica-group style
server selection per query instead of per segment).
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from pinot_tpu.common.cluster_state import CONSUMING, ONLINE, TableView

RoutingTable = Dict[str, List[str]]          # server -> segments


class RoutingError(Exception):
    pass


class RoutingTableBuilder:
    def build(self, view: TableView, rng: random.Random
              ) -> List[RoutingTable]:
        raise NotImplementedError


class BalancedRandomRoutingTableBuilder(RoutingTableBuilder):
    """Per segment, pick a random live replica; balance by least-loaded
    among a random sample. N tables are pre-computed; queries pick one."""

    def __init__(self, num_tables: int = 10):
        self.num_tables = num_tables

    def build(self, view: TableView, rng: random.Random
              ) -> List[RoutingTable]:
        tables: List[RoutingTable] = []
        for _ in range(self.num_tables):
            rt: RoutingTable = {}
            for segment in view.segments():
                servers = view.servers_for(segment, states=(ONLINE,
                                                            CONSUMING))
                if not servers:
                    continue         # no live replica: skip segment
                candidates = rng.sample(servers, min(2, len(servers)))
                best = min(candidates, key=lambda s: len(rt.get(s, [])))
                rt.setdefault(best, []).append(segment)
            tables.append(rt)
        return tables


class ReplicaGroupRoutingTableBuilder(RoutingTableBuilder):
    """Confine each routing table to one 'replica group': every segment is
    served by the same replica index where possible (reference's
    partition-aware/replica-group builders reduce fan-out variance)."""

    def __init__(self, num_tables: int = 10):
        self.num_tables = num_tables

    def build(self, view: TableView, rng: random.Random
              ) -> List[RoutingTable]:
        max_replicas = max((len(view.servers_for(s))
                            for s in view.segments()), default=1)
        tables: List[RoutingTable] = []
        for i in range(self.num_tables):
            replica = i % max(max_replicas, 1)
            rt: RoutingTable = {}
            for segment in view.segments():
                servers = view.servers_for(segment)
                if not servers:
                    continue
                server = servers[replica % len(servers)]
                rt.setdefault(server, []).append(segment)
            tables.append(rt)
        return tables


class PartitionAwareRoutingTableBuilder(RoutingTableBuilder):
    """True partition-aware routing (parity:
    PartitionAwareOfflineRoutingTableBuilder.java:69).

    Segments are grouped by their recorded partition-id set and each
    group is assigned to the FEWEST live servers that can host it
    (greedy max-coverage over replicas). With partition-pure segments
    this lands every partition on one server per routing table, so after
    the broker's partition pruner empties non-matching servers the
    scatter contacts exactly the servers hosting matching partitions —
    fan-out reduction at ROUTING time, not just segment elimination.
    Unpartitioned segments fall back to least-loaded balancing.
    `partition_lookup(segment) -> iterable of partition ids | None` is
    wired by the broker's cluster watcher from segment ZK metadata.
    """

    def __init__(self, partition_lookup, num_tables: int = 10):
        self.partition_lookup = partition_lookup
        self.num_tables = num_tables

    def build(self, view: TableView, rng: random.Random
              ) -> List[RoutingTable]:
        groups: Dict[tuple, List[str]] = {}
        loose: List[str] = []
        for s in view.segments():
            try:
                p = self.partition_lookup(s)
            except Exception:  # noqa: BLE001 — metadata issues fail open
                p = None
            if p:
                groups.setdefault(tuple(sorted(p)), []).append(s)
            else:
                loose.append(s)
        tables: List[RoutingTable] = []
        for _ in range(self.num_tables):
            rt: RoutingTable = {}
            for _pids, group in sorted(groups.items()):
                remaining = set(group)
                while remaining:
                    cover: Dict[str, List[str]] = {}
                    for s in remaining:
                        for srv in view.servers_for(
                                s, states=(ONLINE, CONSUMING)):
                            cover.setdefault(srv, []).append(s)
                    if not cover:
                        break            # no live replica for the rest
                    best_n = max(len(v) for v in cover.values())
                    # random tie-break spreads partitions over replicas
                    # across the N pre-computed tables
                    best = rng.choice(sorted(
                        srv for srv, v in cover.items()
                        if len(v) == best_n))
                    rt.setdefault(best, []).extend(sorted(cover[best]))
                    remaining -= set(cover[best])
            for s in loose:
                servers = view.servers_for(s, states=(ONLINE, CONSUMING))
                if not servers:
                    continue
                candidates = rng.sample(servers, min(2, len(servers)))
                best = min(candidates, key=lambda x: len(rt.get(x, [])))
                rt.setdefault(best, []).append(s)
            tables.append(rt)
        return tables


class LargeClusterRoutingTableBuilder(RoutingTableBuilder):
    """Cap each routing table to a bounded server subset.

    Parity: LargeClusterRoutingTableBuilder.java — on clusters with many
    servers, fanning every query out to all of them makes tail latency
    the max over the fleet; instead each pre-computed table routes over a
    random `target_num_servers` subset that still covers every segment
    (servers hosting otherwise-uncovered segments are added back)."""

    def __init__(self, target_num_servers: int = 20, num_tables: int = 10):
        self.target = target_num_servers
        self.num_tables = num_tables

    def build(self, view: TableView, rng: random.Random
              ) -> List[RoutingTable]:
        all_servers = sorted({s for seg in view.segments()
                              for s in view.servers_for(
                                  seg, states=(ONLINE, CONSUMING))})
        tables: List[RoutingTable] = []
        for _ in range(self.num_tables):
            subset = set(rng.sample(
                all_servers, min(self.target, len(all_servers))))
            rt: RoutingTable = {}
            for segment in view.segments():
                servers = view.servers_for(segment, states=(ONLINE,
                                                            CONSUMING))
                if not servers:
                    continue
                usable = [s for s in servers if s in subset]
                if not usable:
                    # coverage first: pull a replica back in
                    pick = rng.choice(servers)
                    subset.add(pick)
                    usable = [pick]
                best = min(usable, key=lambda s: len(rt.get(s, [])))
                rt.setdefault(best, []).append(segment)
            tables.append(rt)
        return tables


def make_routing_builder(name: Optional[str],
                         options: Optional[Dict[str, str]] = None,
                         partition_lookup=None
                         ) -> Optional[RoutingTableBuilder]:
    """Resolve a table config's routingTableBuilderName (parity:
    RoutingTableBuilderFactory). None/unknown -> broker default."""
    opts = options or {}
    key = (name or "").lower().replace("routingtablebuilder", "")
    if key in ("balanced", "balancedrandom", "defaultoffline",
               "defaultrealtime"):
        return BalancedRandomRoutingTableBuilder()
    if key in ("partitionawareoffline", "partitionawarerealtime") and \
            partition_lookup is not None:
        return PartitionAwareRoutingTableBuilder(partition_lookup)
    if key in ("replicagroup", "partitionawareoffline",
               "partitionawarerealtime"):
        return ReplicaGroupRoutingTableBuilder()
    if key == "largecluster":
        try:
            target = int(opts.get("targetNumServers", "20"))
        except ValueError:
            # a malformed option must not break the view-watcher callback
            # chain (nothing validates configs at upload time) — fall
            # back to the default fan-out cap
            target = 20
        return LargeClusterRoutingTableBuilder(
            target_num_servers=max(1, target))
    return None


class RoutingManager:
    """Holds current routing tables per physical table; rebuilds on
    external-view changes (parity: processExternalViewChange :418)."""

    # how long a segment whose replicas are ALL transiently non-serving
    # keeps routing to its last-known serving replica (covers the
    # reload/rebalance bounce windows where the view briefly shows no
    # ONLINE replica; a genuinely deleted segment leaves the view
    # entirely and gets no grace)
    UNSERVABLE_GRACE_S = 10.0

    def __init__(self, builder: Optional[RoutingTableBuilder] = None,
                 seed: int = 0):
        self.builder = builder or BalancedRandomRoutingTableBuilder()
        self._table_builders: Dict[str, RoutingTableBuilder] = {}
        self._tables: Dict[str, List[RoutingTable]] = {}
        self._views: Dict[str, TableView] = {}
        self._last_serving: Dict[str, Dict[str, tuple]] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def table_builder(self, table_name: str) -> RoutingTableBuilder:
        with self._lock:
            return self._table_builders.get(table_name, self.builder)

    def set_table_builder(self, table_name: str,
                          builder: Optional[RoutingTableBuilder],
                          rebuild: bool = True) -> None:
        """Per-table builder override (parity: per-table
        routingTableBuilderName); rebuilds the held view unless the
        caller is about to push one anyway."""
        with self._lock:
            if builder is None:
                self._table_builders.pop(table_name, None)
            else:
                self._table_builders[table_name] = builder
            view = self._views.get(table_name)
        if rebuild and view is not None:
            self.update_view(view)

    def update_view(self, view: TableView) -> None:
        import time as _time

        now = _time.monotonic()
        view = view.copy()
        builder = self.table_builder(view.table_name)
        with self._lock:
            # grace bookkeeping under the same lock as the table swap:
            # concurrent update_view calls for one table must not
            # interleave last-serving writes with an older view's
            last = self._last_serving.setdefault(view.table_name, {})
            for seg in list(view.segment_states):
                servers = view.servers_for(seg, states=(ONLINE,
                                                        CONSUMING))
                if servers:
                    # remember ONE serving replica for the grace fallback
                    last[seg] = (servers[0],
                                 now + self.UNSERVABLE_GRACE_S)
                else:
                    held = last.get(seg)
                    if held is not None and held[1] > now:
                        # transient all-replicas-bouncing window: keep
                        # the segment routable at its last server (a
                        # wrong guess surfaces as SegmentMissingError
                        # and goes through the broker's re-dispatch,
                        # never silent row loss)
                        view.segment_states[seg] = {held[0]: ONLINE}
            for seg in [s for s in last
                        if s not in view.segment_states]:
                del last[seg]          # segment left the view: no grace
            tables = builder.build(view, self._rng)
            self._views[view.table_name] = view.copy()
            self._tables[view.table_name] = tables

    def remove_table(self, table_name: str) -> None:
        with self._lock:
            self._tables.pop(table_name, None)
            self._views.pop(table_name, None)
            self._last_serving.pop(table_name, None)
            # drop the builder override too: a recreated table must start
            # from the broker default until its own config is applied
            self._table_builders.pop(table_name, None)

    def has_table(self, table_name: str) -> bool:
        with self._lock:
            return bool(self._tables.get(table_name))

    def route(self, table_name: str) -> RoutingTable:
        with self._lock:
            tables = self._tables.get(table_name)
            if not tables:
                raise RoutingError(f"no routing table for {table_name}")
            return self._rng.choice(tables)

    def view(self, table_name: str) -> Optional[TableView]:
        with self._lock:
            v = self._views.get(table_name)
            return v.copy() if v is not None else None
