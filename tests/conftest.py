"""Test config: CPU backend with 8 virtual devices + x64 for exact oracles.

Must run before jax is imported anywhere.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
