"""Distributed deployment wiring: each plane in its own process.

Parity: the reference's production shape — StartControllerCommand /
StartServerCommand / StartBrokerCommand processes joined through
ZooKeeper (tools/admin/command/).  Here the store server
(controller/store_server.py) plays ZK: the controller hosts it; servers
and brokers connect with RemotePropertyStore and coordinate through
watches and ephemeral records only.  The deep store is a shared
filesystem path (PinotFS), as in the reference's NFS/HDFS deployments.

These classes are the process entrypoints; `tools/admin.py` exposes them
as start-controller / start-server / start-broker commands, and the
distributed integration tests drive them in-process over real TCP.
"""
from __future__ import annotations

import os
import uuid
from typing import Optional

from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
from pinot_tpu.broker.request_handler import (BrokerRequestHandler,
                                              TcpTransport)
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.controller.controller import Controller
from pinot_tpu.controller.manager import ResourceManager
from pinot_tpu.controller.property_store import PropertyStore
from pinot_tpu.controller.state_machine import (LIVE, ClusterCoordinator,
                                                ViewComposer)
from pinot_tpu.controller.store_client import (RemotePropertyStore,
                                               StoreClosedError)
from pinot_tpu.controller.store_server import PropertyStoreServer
from pinot_tpu.server.agent import ParticipantAgent
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.participant import ServerParticipant


class StandaloneStore:
    """A property-store server in its own right — the ZooKeeper role.

    HA controller deployments need the cluster store to OUTLIVE any one
    controller (a lead controller hosting the store would take the whole
    cluster down with it); this wrapper hosts a durable PropertyStore
    behind the TCP store server with nothing else attached. Controllers,
    servers and brokers all connect as clients."""

    def __init__(self, work_dir: str, port: int = 0, durable: bool = True):
        self.store = PropertyStore(
            data_dir=os.path.join(work_dir, "store") if durable else None)
        self.server = PropertyStoreServer(self.store, port=port)
        self.port = self.server.start()

    def stop(self) -> None:
        self.server.stop()
        self.store.close()


class DistributedController:
    """Controller process: resource manager + view composer (+ optional
    admin HTTP). Hosts the store server itself by default; with
    `store_addr` it joins an EXTERNAL store (StandaloneStore) instead —
    the HA shape where a lead and one or more `standby=True` controllers
    run hot against the same durable store, the lease (TTL + fencing
    token) decides who leads, and a dead leader is succeeded within one
    lease period."""

    def __init__(self, work_dir: str, store_port: int = 0,
                 http: bool = False, periodic: bool = False,
                 durable: bool = True, download_base: Optional[str] = None,
                 store_addr: Optional[tuple] = None,
                 standby: bool = False,
                 instance_id: Optional[str] = None,
                 lease_s: Optional[float] = None):
        """`durable`: journal cluster state under <work_dir>/store (WAL
        + snapshots) so a controller restarted over the same work_dir
        recovers every table, ideal state and segment record.
        `download_base="http"` (requires http=True): advertise segment
        downloadPaths through the controller's /deepstore endpoints —
        the no-shared-filesystem deployment where servers download and
        cache artifacts locally.
        `store_addr`: (host, port) of an external store — enables the
        HA shape (fenced mutations, lease heartbeat, endpoint
        publication on takeover). `standby=True` marks a hot standby:
        identical wiring, it simply won't win the lease until the
        current one expires."""
        if standby and store_addr is None:
            raise ValueError("standby=True needs store_addr: a standby "
                             "must share the lead controller's store")
        self.work_dir = work_dir
        self.standby = standby
        self._download_base = download_base
        ha = store_addr is not None
        if ha:
            self.store = RemotePropertyStore(store_addr[0],
                                             int(store_addr[1]))
            self.store_server = None
            self.store_port = int(store_addr[1])
        else:
            self.store = PropertyStore(
                data_dir=os.path.join(work_dir, "store")
                if durable else None)
        if instance_id is None:
            instance_id = f"Controller_{uuid.uuid4().hex[:8]}" if ha \
                else "Controller_0"
        self.instance_id = instance_id
        self.controller = Controller(os.path.join(work_dir, "deepstore"),
                                     store=self.store,
                                     instance_id=instance_id,
                                     ha=ha, lease_s=lease_s)
        # with peers over one store, only the LEADER composes views;
        # a promoted standby catches up on the events its gate dropped
        self.composer = ViewComposer(
            self.store,
            gate=self.controller.leadership.is_leader if ha else None)
        if not ha:
            self.store_server = PropertyStoreServer(self.store,
                                                    port=store_port)
            self.store_port = self.store_server.start()
        self.http_api = None
        self.http_port: Optional[int] = None
        if http:
            from pinot_tpu.controller.http_api import ControllerApiServer
            self.http_api = ControllerApiServer(self.controller)
            self.http_port = self.http_api.start()
            if download_base == "http" and not ha:
                # advertise downloadPath through /deepstore so servers
                # without a shared filesystem fetch over HTTP; the
                # CURRENT endpoint is also published so servers re-base
                # durable records stamped by a previous controller
                # incarnation (a restart may land on a new port)
                self._publish_endpoints()
        if ha:
            # publish this controller's endpoints the moment it becomes
            # leader (boot for the lead, takeover for a standby): the
            # active completion/deepstore endpoint always names the
            # living leader. Registered BEFORE the lease is first
            # claimed in controller.start().
            def on_leader(leader: bool) -> None:
                if leader:
                    self.composer.recompose_all()
                    # broker membership may have changed while this
                    # controller's live watcher was fenced out (lead
                    # dead, standby not yet promoted): replay the
                    # /BROKERRESOURCE refresh the fence dropped, or
                    # dynamic selectors keep routing at dead brokers
                    # until an unrelated live event
                    try:
                        self.controller.manager \
                            .refresh_all_broker_resources()
                    except Exception:  # noqa: BLE001 — store racing
                        pass           # shutdown; next event retries
                    self._publish_endpoints()
            self.controller.leadership.add_listener(on_leader)
        if periodic or ha:
            self.controller.start()

    def _publish_endpoints(self) -> None:
        """Publish the ACTIVE controller's HTTP base for servers to
        (re-)resolve: the completion protocol endpoint and — when this
        deployment serves artifacts over HTTP — the deep-store base."""
        if self.http_port is None:
            return
        base = f"http://127.0.0.1:{self.http_port}"
        # raw store on purpose: the listener fires exactly on the
        # leadership transition, and publishing must not race the
        # fence's own bookkeeping
        self.store.set("/CONTROLLER/ENDPOINT", {"base": base})
        if self._download_base == "http":
            self.controller.manager.download_base = base
            self.store.set("/CONTROLLER/DEEPSTORE_BASE", {"base": base})

    def is_leader(self) -> bool:
        return self.controller.leadership.is_leader()

    @property
    def deep_store_dir(self) -> str:
        return self.controller.manager.deep_store_dir

    def stop(self) -> None:
        if self.http_api is not None:
            self.http_api.stop()
        self.controller.stop()
        self.composer.close()
        if self.store_server is not None:
            self.store_server.stop()
        self.store.close()

    def kill(self) -> None:
        """Crash simulation: sockets die, nothing is drained or
        resigned — the leader lease is left to EXPIRE on its TTL, and
        recovery must come from the store's WAL/snapshots and the deep
        store alone."""
        # silence this incarnation's background threads without any
        # store writes (a real kill stops them too; in-process they'd
        # otherwise spam the shared store with post-mortem activity)
        self.controller.periodic.stop()
        self.controller.leadership.abort()
        if self.http_api is not None:
            self.http_api.stop()
        if self.store_server is not None:
            self.store_server.stop()
        # the WAL handle is NOT fsync'd/closed gracefully on a real
        # crash either; close() only releases the fd so a successor
        # process (same test) can reopen the files
        self.store.close()


class DistributedServer:
    """Server process: query service + participant agent over a remote
    store."""

    def __init__(self, instance_id: str, store_host: str, store_port: int,
                 deep_store_dir: str, work_dir: Optional[str] = None,
                 port: int = 0, scheduler: str = "fcfs", mesh=None,
                 host: str = "127.0.0.1",
                 controller_http: Optional[str] = None):
        """`controller_http`: host:port of the controller REST API —
        enables realtime tables (the LLC completion protocol goes over
        HTTP, as the reference's ServerSegmentCompletionProtocolHandler
        does)."""
        self.store = RemotePropertyStore(store_host, store_port)
        coordinator = ClusterCoordinator(self.store)
        self.manager = ResourceManager(coordinator, deep_store_dir,
                                       maintain_broker_resource=False)
        self.server = ServerInstance(instance_id, scheduler=scheduler,
                                     mesh=mesh)
        self.port = self.server.start(port=port)
        completion = None
        if controller_http is not None:
            from pinot_tpu.realtime.http_completion import \
                HttpSegmentCompletionClient
            # "auto": resolve the ACTIVE controller purely from the
            # published /CONTROLLER/ENDPOINT record (HA deployments —
            # the store also lets the client re-resolve after failover)
            completion = HttpSegmentCompletionClient(
                None if controller_http == "auto" else controller_http,
                store=self.store)
        self.participant = ServerParticipant(self.server, self.manager,
                                             completion=completion,
                                             work_dir=work_dir)
        # cold-start recovery: validate the local artifact cache before
        # re-entering assigned transitions — verified segments reload
        # from disk, corrupt ones are quarantined and re-downloaded
        self.recovery_report = self.participant.scan_local_artifacts()
        self.agent = ParticipantAgent(self.store, instance_id,
                                      self.participant,
                                      endpoint=(host, self.port))
        self.agent.start()

    def stop(self) -> None:
        """Graceful shutdown: deregister, then stop serving."""
        self.agent.stop()
        self.participant.shutdown()
        self.server.stop()
        self.store.close()

    def drain(self, seal_timeout_s: float = 20.0,
              settle_s: float = 10.0) -> bool:
        """SIGTERM path — planned, errorless departure:

        1. seal consuming segments where possible (commit through the
           completion protocol — a planned restart leaves no unsealed
           rows to re-consume),
        2. deregister (live record + current states drop in one watch
           chain; brokers stop routing NEW queries here),
        3. keep serving until the external view no longer names this
           instance and in-flight queries drained (bounded), then stop.

        Returns whether every sealable consumer sealed. Distinguishes a
        planned restart (zero client-visible errors) from kill -9 chaos
        (masked by broker failover, healed by the controller).

        Sealing runs BEFORE deregistration on purpose: the committed
        rows stay queryable on this server through the whole window
        (deregister-first would drop them from results until repair).
        The cost is that commit_end assigns the successor consuming
        segment back to this still-registered server; it departs with 0
        rows and the takeover path re-places it within one grace window
        — a bounded ingestion pause, never data loss or wrong answers."""
        import time as _time
        try:
            sealed = self.participant.seal_consuming(seal_timeout_s)
        except Exception:  # noqa: BLE001 — seal is best-effort
            sealed = False
        inst = self.agent.instance_id
        self.agent.stop()
        deadline = _time.monotonic() + settle_s

        def view_clear() -> bool:
            try:
                for table in self.manager.coordinator.tables():
                    states = self.manager.coordinator.external_view(
                        table).segment_states
                    if any(inst in s for s in states.values()):
                        return False
                return True
            except Exception:  # noqa: BLE001 — store racing shutdown
                return True
        while _time.monotonic() < deadline and not view_clear():
            _time.sleep(0.02)
        # the brokers' own watch dispatch lags the controller's view
        # write by a network hop: one fixed beat before draining
        _time.sleep(min(0.25, settle_s))
        # brokers' watch dispatch + already-scattered queries: serve
        # until the admission queue drains (bounded by the same budget)
        while _time.monotonic() < deadline and \
                self.server.admission.depth() > 0:
            _time.sleep(0.02)
        self.participant.shutdown()
        self.server.stop()
        self.store.close()
        return sealed

    def kill(self) -> None:
        """Crash simulation: the store session dies with the process —
        ephemeral live-instance/current-state records must vanish without
        any deregistration call (ZK session-expiry semantics)."""
        self.store.close()
        self.server.stop()


class DistributedBroker:
    """Broker process: spectator over a remote store + TCP data plane with
    endpoints learned from live-instance records. Registers itself as an
    ephemeral live instance carrying its broker tenant tag + HTTP
    endpoint, so tenant-aware broker resources and dynamic client
    selectors see it (parity: HelixBrokerStarter registering the broker
    participant under its tenant tag)."""

    def __init__(self, store_host: str, store_port: int,
                 deep_store_dir: str, http: bool = False,
                 instance_id: Optional[str] = None,
                 broker_tenant: str = "DefaultTenant",
                 host: str = "127.0.0.1",
                 faults: Optional[bool] = None):
        self.store = RemotePropertyStore(store_host, store_port)
        coordinator = ClusterCoordinator(self.store)
        manager = ResourceManager(coordinator, deep_store_dir,
                                  maintain_broker_resource=False)
        self.transport = TcpTransport({})
        # chaos plane (PINOT_TPU_BROKER_FAULTS=1, or faults=True): the
        # data plane runs through a FaultInjectingTransport so the soak
        # coordinator can arm latency/drop windows over the broker's
        # /debug/faults endpoints. Endpoint updates still target the
        # inner TcpTransport (self.transport); only dispatch is wrapped.
        data_transport = self.transport
        if faults is None:
            faults = os.environ.get("PINOT_TPU_BROKER_FAULTS",
                                    "0") != "0"
        if faults:
            from pinot_tpu.common.faults import FaultInjectingTransport
            data_transport = FaultInjectingTransport(
                self.transport,
                seed=int(os.environ.get(
                    "PINOT_TPU_BROKER_FAULTS_SEED", "0")))
        # live *_BROKER ids maintained from the watch stream so
        # _num_live_brokers is O(1): it runs inside _apply_quota_config
        # on EVERY external-view event, and a children+get-per-instance
        # store scan there delayed routing updates long enough to turn
        # reload-bounce windows into real misroutes
        self._live_broker_ids: set = set()
        self._live_watcher = self._on_live
        self.store.watch(LIVE + "/", self._live_watcher)
        for inst in self.store.children(LIVE):
            self._on_live(f"{LIVE}/{inst}", self.store.get(f"{LIVE}/{inst}"))
        # quota convergence across brokers: the watcher re-reads table
        # quotaConfig on every external-view change AND on every live-
        # instance change (_on_live → reapply_quotas) and divides the
        # cluster-wide rate by the number of live brokers (counted from
        # the same ephemeral live-instance records that advertise HTTP
        # endpoints), so a broker joining or dying rebalances every
        # broker's share immediately, not on the next segment churn
        from pinot_tpu.broker.quota import QueryQuotaManager
        self.quota = QueryQuotaManager()
        self.watcher = BrokerClusterWatcher(
            coordinator, manager, quota=self.quota,
            num_brokers_fn=self._num_live_brokers)
        self.handler = BrokerRequestHandler(
            self.watcher.routing, data_transport,
            time_boundary=self.watcher.time_boundary,
            quota=self.quota,
            segment_pruner=self.watcher.partition_pruner)
        # segment lifecycle (upload/replace/drop) flushes the broker
        # result cache — the freshness bound only covers consuming-
        # ingestion staleness, not an offline backfill
        self.watcher.register_result_cache(self.handler.result_cache)
        # a deregistered server leaves the candidate ranking in ONE
        # watch event: breaker/health state forgotten, so a
        # reincarnation on the same host:port starts clean
        self.watcher.attach_fault_tolerance(self.handler.fault_tolerance)
        self.http_api = None
        self.http_port: Optional[int] = None
        self.instance_id = instance_id
        self._registered = False
        if http:
            from pinot_tpu.broker.http_api import BrokerApiServer
            self.http_api = BrokerApiServer(self.handler)
            self.http_port = self.http_api.start()
        # EVERY broker registers a live record, http or not: the
        # per-broker quota share is cluster rate / live *_BROKER
        # records, so an unregistered broker would be invisible to the
        # division and the cluster would admit above the configured
        # quota. Only HTTP brokers advertise an endpoint — selectors
        # and the controller proxy filter on "host" in record.
        from pinot_tpu.controller.tenants import broker_tenant_tag
        if self.instance_id is None:
            suffix = self.http_port if self.http_port is not None \
                else uuid.uuid4().hex[:8]
            self.instance_id = f"Broker_{host}_{suffix}"
        record = {"tags": [broker_tenant_tag(broker_tenant)]}
        if self.http_port is not None:
            record["host"] = host
            record["port"] = self.http_port
        # ephemeral: dies with this process's store session, so a
        # killed broker drops out of every selector automatically
        self.store.set(f"{LIVE}/{self.instance_id}", record,
                       ephemeral=True)
        self._registered = True
        # own-record watch delivery is async: count ourselves NOW and
        # reconverge synchronously, or the queries admitted before the
        # echo arrives would be admitted at rate/(N-1) — a 2-broker
        # cluster would briefly admit 1.5x the configured quota
        self._live_broker_ids.add(self.instance_id)
        self.watcher.reapply_quotas()

    def _on_live(self, path: str, record: Optional[dict]) -> None:
        inst = path[len(LIVE) + 1:]
        if record is not None and "host" in record:
            self.transport.set_endpoint(inst, record["host"],
                                        record["port"])
        # a removal record is tag-less, so discard unconditionally —
        # only ids that once carried a _BROKER tag are ever present
        changed = False
        if record is None:
            if inst in self._live_broker_ids:
                self._live_broker_ids.discard(inst)
                changed = True
        elif any(str(t).endswith("_BROKER")
                 for t in record.get("tags", ())):
            if inst not in self._live_broker_ids:
                self._live_broker_ids.add(inst)
                changed = True
        # BROKER membership changed: every broker's share of each table
        # quota changes with the live broker count, and no external-
        # view event fires for it. Server joins/deaths can't change the
        # share — skipping them keeps a rolling server restart from
        # hammering the watch-dispatch thread with per-table config
        # re-reads (the thread routing updates ride on). getattr: the
        # watch fires during __init__ before the watcher exists.
        watcher = getattr(self, "watcher", None)
        if watcher is not None and changed:
            try:
                watcher.reapply_quotas()
            except StoreClosedError:
                # session teardown: our own ephemeral removal (and any
                # trailing events) dispatch after close — nothing to
                # reconfigure on a dead session
                pass

    def _num_live_brokers(self) -> int:
        """Live brokers = live-instance records carrying a *_BROKER
        tenant tag (this broker's own record included). Served from the
        watch-maintained set — NO store round-trips; this runs on the
        hot view-event path."""
        return max(1, len(self._live_broker_ids))

    def query(self, pql: str) -> BrokerResponse:
        return self.handler.handle(pql)

    def stop(self) -> None:
        if self._registered:          # only the record THIS broker wrote
            try:
                self.store.remove(f"{LIVE}/{self.instance_id}")
            except Exception:  # noqa: BLE001 — session may be dead
                pass
        if self.http_api is not None:
            self.http_api.stop()
        self.handler.close()
        self.store.close()

    def kill(self) -> None:
        """Crash simulation: the ephemeral live record must vanish with
        the store session, with no deregistration call."""
        self.store.close()
        if self.http_api is not None:
            self.http_api.stop()
        self.handler.close()


class DistributedMinion:
    """Minion process: a MinionWorker polling the cluster task queue
    over a remote store (parity: the reference's MinionStarter — a
    task-executor instance joining the cluster, pulling from the Helix
    task framework). Compaction/merge/retention tasks download
    artifacts through the shared deep store (or the controller's HTTP
    deepstore endpoints) and push swaps through the same intent-logged
    protocol the in-process minion tests model-check."""

    def __init__(self, instance_id: str, store_host: str, store_port: int,
                 deep_store_dir: str, work_dir: Optional[str] = None):
        self.store = RemotePropertyStore(store_host, store_port)
        coordinator = ClusterCoordinator(self.store)
        self.manager = ResourceManager(coordinator, deep_store_dir,
                                       maintain_broker_resource=False)
        self.instance_id = instance_id
        from pinot_tpu.minion.worker import MinionWorker
        self.worker = MinionWorker(self.manager, instance_id,
                                   work_dir=work_dir)
        self.worker.start()

    def stop(self) -> None:
        """Graceful: finish the in-flight task, then leave."""
        self.worker.stop()
        self.store.close()

    def kill(self) -> None:
        """Crash simulation: the store session dies mid-task; the task
        queue's lease/requeue machinery (and the swap protocol's intent
        log) must recover the work."""
        self.store.close()
