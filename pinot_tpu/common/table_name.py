"""Table naming: raw name <-> type-suffixed physical table names.

Parity: pinot-common TableNameBuilder / CommonConstants.Helix.TableType —
"myTable" resolves to physical tables "myTable_OFFLINE" / "myTable_REALTIME";
hybrid tables have both.
"""
from __future__ import annotations

OFFLINE_SUFFIX = "_OFFLINE"
REALTIME_SUFFIX = "_REALTIME"


def offline_table(raw: str) -> str:
    return raw if raw.endswith(OFFLINE_SUFFIX) else raw + OFFLINE_SUFFIX


def realtime_table(raw: str) -> str:
    return raw if raw.endswith(REALTIME_SUFFIX) else raw + REALTIME_SUFFIX


def raw_table(name: str) -> str:
    for sfx in (OFFLINE_SUFFIX, REALTIME_SUFFIX):
        if name.endswith(sfx):
            return name[: -len(sfx)]
    return name


def table_type(name: str) -> str:
    if name.endswith(OFFLINE_SUFFIX):
        return "OFFLINE"
    if name.endswith(REALTIME_SUFFIX):
        return "REALTIME"
    return "NONE"
