#!/usr/bin/env bash
# tpulint over the tree (or explicit paths), gated on the committed
# baseline. Run from anywhere; executes at the repo root so finding
# keys match tpulint.baseline.json.
#
#   scripts/lint.sh              fast tier (AST rule families)
#   scripts/lint.sh --deep       + jaxpr kernel contracts + wire-schema
#   scripts/lint.sh --deep --protocol
#                                + durability order, crash coverage,
#                                  metrics contract, and the exhaustive
#                                  crash-interleaving model checker
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pinot_tpu.analysis --strict-baseline "${@:-pinot_tpu/}"
