"""Mesh-sharded multi-segment query execution (segment data parallelism).

Parity: the reference's two combine layers — CombineOperator /
CombineGroupByOperator (pinot-core/.../operator/CombineOperator.java:27,
CombineGroupByOperator.java:107-156: per-segment plans on an ExecutorService,
merged into a shared ConcurrentHashMap) and the broker's scatter-gather
(SURVEY.md §2.18 #1/#2) — rebuilt the TPU way:

- Homogeneous segments (same schema, same padded doc count) are stacked
  onto a leading `seg` axis and sharded over a `jax.sharding.Mesh` with
  `shard_map`.
- Each device vmaps the single-segment kernel over its local shard, reduces
  locally, then combines across devices with XLA collectives over ICI:
  `psum` for counts/sums/histograms/group tables, `pmin`/`pmax` for id- or
  value-domain extrema, `all_gather` for selection lanes.
- Cross-segment combine in the dictId domain is only sound in ONE shared id
  space. Segments built independently (the normal storage path) have
  per-segment dictionaries, so the stacker builds a UNION DICTIONARY per
  column — the sorted merge of every segment's values — and remaps each
  segment's id lanes into the union domain at stack time, before upload
  (a monotonic id map: sortedness and range-filter semantics survive).
  Queries then plan against a union view of segment 0 and combine on
  device exactly as in the shared case. This is the value-domain merge of
  the reference's CombineGroupByOperator
  (core/operator/CombineGroupByOperator.java:107-156) moved to stack time:
  pay the remap once per (segment-set, column), not per query.
  `NotShardable` remains only for genuinely un-stackable sets (mutable
  segments, differing padded sizes/shapes, raw-column range mismatches).

One jitted shard_map executable serves every query with the same static spec
(shapes pow2-bucketed), mirroring the single-segment plan cache.
"""
from __future__ import annotations

import collections
import functools
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pinot_tpu import compat
from pinot_tpu.analysis.runtime import debug_transfer_guard
from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.obs import residency
from pinot_tpu.obs.profiler import profiled_device_get
from pinot_tpu.query import combine as combine_mod
from pinot_tpu.query import execution
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock
from pinot_tpu.query.plan import InstancePlanMaker, SegmentPlan
from pinot_tpu.segment.loader import ImmutableSegment

SEG_AXIS = "seg"


class NotShardable(Exception):
    """Segments are not homogeneous enough for id-domain device combine."""


def make_mesh(devices: Optional[Sequence] = None,
              axis: str = SEG_AXIS) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


# ---------------------------------------------------------------------------
# Cross-segment combine rules, keyed by output name
# ---------------------------------------------------------------------------


def _combine_kind(key: str) -> str:
    if key.startswith("sel."):
        return "stack"          # per-segment; host merges selection rows
    if key.endswith((".parts", ".partsT", ".vsum", ".psums", ".csums")):
        return "stack"          # chunk partials: host combines in int64/f64
    if key.endswith((".rkeys", ".rcount", ".rpsums", ".rsum", ".rmin",
                     ".rmax")):
        return "stack"          # ranked group tables: per-segment rank
        #                         spaces; host merges by group key
    if key.endswith(".min"):
        return "min"
    if key.endswith((".max", ".hll")):
        return "max"            # HLL registers merge by elementwise max
    return "sum"                # counts, histograms, group tables


@functools.lru_cache(maxsize=256)
def get_sharded_kernel(mesh: Mesh, padded: int, filter_spec, agg_specs,
                       group_spec, select_spec, lane_keys: Tuple[str, ...]):
    """Jitted shard_map over the per-segment kernel with device combine.

    `lane_keys` is the static set of column-lane names; `.vals` lanes
    (shared dictionary value tables) are replicated, everything else is
    sharded over the `seg` axis.
    """
    from pinot_tpu.ops.kernels import build_segment_kernel
    kern = build_segment_kernel(padded, filter_spec, agg_specs, group_spec,
                                select_spec)
    # dictionary-scale tables (values, HLL idx/rank) are replicated;
    # row-scale lanes shard over the seg axis
    REPL = (".vals", ".hllidx", ".hllrank")
    col_specs = {k: P() if k.endswith(REPL) else P(SEG_AXIS)
                 for k in lane_keys}
    col_axes = {k: None if k.endswith(REPL) else 0 for k in lane_keys}

    def local(cols, params, num_docs):
        # cols leaves: [S_local, ...] (vals replicated); num_docs [S_local]
        outs = jax.vmap(lambda c, n: kern(c, params, n),
                        in_axes=(col_axes, 0))(cols, num_docs)
        combined = {}
        # per-segment matched counts (for numSegmentsMatched parity with
        # the sequential path), gathered alongside the global reduction
        per_seg = outs["stats.num_docs_matched"]
        combined["stats.seg_matched"] = jax.lax.all_gather(
            per_seg, SEG_AXIS).reshape(-1)
        for k, v in outs.items():
            kind = _combine_kind(k)
            if k.endswith(".cpsums"):
                # compacted int part sums: a straight int32 psum could
                # overflow past ~16.9M matched rows in one group, so split
                # each segment's table into 16-bit halves (each half's
                # cross-segment sum stays far inside int32) and let the
                # host recombine in int64
                flat = v.reshape((-1,) + v.shape[-2:])  # [S(*chunks), P, G]
                lo = (flat & 0xFFFF).sum(axis=0)
                hi = ((flat >> 16) & 0xFFFF).sum(axis=0)
                combined[f"{k}.lo"] = jax.lax.psum(lo, SEG_AXIS)
                combined[f"{k}.hi"] = jax.lax.psum(hi, SEG_AXIS)
                continue
            if kind == "sum":
                combined[k] = jax.lax.psum(v.sum(axis=0), SEG_AXIS)
            elif kind == "min":
                combined[k] = jax.lax.pmin(v.min(axis=0), SEG_AXIS)
            elif kind == "max":
                combined[k] = jax.lax.pmax(v.max(axis=0), SEG_AXIS)
            else:  # stack: gather all segments' lanes, restore global order
                g = jax.lax.all_gather(v, SEG_AXIS)      # [D, S_local, ...]
                combined[k] = g.reshape((-1,) + v.shape[1:])
        return combined

    # check_vma=False: outputs are replicated by construction (psum/pmin/
    # pmax/all_gather), but the static varying-axis check can't prove it
    # for the all_gather'd selection lanes. compat.shard_map resolves the
    # installed spelling (jax.shard_map vs jax.experimental.shard_map).
    fn = compat.shard_map(local, mesh=mesh,
                          in_specs=(col_specs, P(), P(SEG_AXIS)),
                          out_specs=P(), check_vma=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# Segment stacking
# ---------------------------------------------------------------------------


class _UnionColumn:
    """Union-dictionary remap artifacts for one column.

    values = sorted merge of every segment's dictionary values;
    remaps[s] maps segment s's local dictId (plus the local padding
    sentinel, id == local cardinality) into the union id domain (pad →
    union cardinality). The map is monotonic per segment, so range
    predicates and sorted-layout guarantees survive the remap.
    """

    def __init__(self, col: str, srcs):
        from pinot_tpu.segment.dictionary import Dictionary
        from pinot_tpu.segment.loader import (int_part_info_for,
                                              int_part_table,
                                              pad_dict_values)
        self.col = col
        per_seg = [np.asarray(s.dictionary.values) for s in srcs]
        union = np.unique(np.concatenate(per_seg))
        self.values = union
        self.cardinality = len(union)
        self.remaps = []
        for v in per_seg:
            r = np.searchsorted(union, v).astype(np.int32)
            self.remaps.append(
                np.concatenate([r, np.int32([self.cardinality])]))
        cm0 = srcs[0].metadata
        import dataclasses
        self.metadata = dataclasses.replace(
            cm0, cardinality=self.cardinality,
            min_value=union[0] if len(union) else cm0.min_value,
            max_value=union[-1] if len(union) else cm0.max_value,
            sorted=all(s.metadata.sorted for s in srcs),
            has_inverted_index=False, has_bloom_filter=False)
        self.dictionary = Dictionary(cm0.data_type, union)
        # segment-independent artifacts, built ONCE per union column
        self.padded_vals = pad_dict_values(union, cm0.data_type.np_dtype)
        self.part_info = int_part_info_for(union) \
            if cm0.data_type.np_dtype.kind in "iu" else None
        self.part_table = (int_part_table(union, *self.part_info)
                           if self.part_info is not None else None)
        self.f64_vals = np.concatenate(
            [np.asarray(union, dtype=np.float64), [0.0]]) \
            if cm0.data_type.is_numeric else None
        # HLL (idx, rank) tables in the union value domain, built lazily
        # (only DISTINCTCOUNTHLL queries pay)
        self.hll_tables = None


class _UnionDataSource:
    """Planning-time DataSource view in the union id domain.

    Everything a plan needs — metadata, literal→id binding, part
    encodings, decode tables — comes from the union dictionary; index
    structures that only exist per segment (inverted, bloom, sorted
    ranges) are absent so plans can't take per-segment fast paths."""

    def __init__(self, union: _UnionColumn):
        self.metadata = union.metadata
        self.dictionary = union.dictionary
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None
        self._union = union

    def int_part_info(self) -> tuple:
        return self._union.part_info

    def host_operand(self, kind: str) -> np.ndarray:
        if kind == "vals":
            return self._union.padded_vals
        raise ValueError(
            f"union data source serves plans, not '{kind}' lanes")


class _UnionViewSegment:
    """Segment 0 with union-dictionary columns swapped in — the object
    queries plan against (and decode group/selection results with) when
    a stack spans per-segment dictionaries."""

    def __init__(self, stack: "StackedSegments"):
        self._stack = stack
        self._base = stack.segments[0]
        self._sources: Dict[str, object] = {}  # tpulint: disable=cache-bound -- bounded by the table's column count; dies with the stack (executor LRU)

    @property
    def metadata(self):
        return self._base.metadata

    @property
    def segment_name(self) -> str:
        return self._base.segment_name

    @property
    def num_docs(self) -> int:
        return self._base.num_docs

    @property
    def padded_docs(self) -> int:
        return self._base.padded_docs

    @property
    def column_names(self):
        return self._base.column_names

    @property
    def star_trees(self):
        # star-tree cubes are per-segment id-domain artifacts; the
        # sharded path never serves them (fast paths go sequential)
        return []

    def has_column(self, column: str) -> bool:
        return self._base.has_column(column)

    def data_source(self, column: str):
        ds = self._sources.get(column)
        if ds is None:
            base = self._base.data_source(column)
            union = self._stack.union_column(column) \
                if base.dictionary is not None else None
            ds = _UnionDataSource(union) if union is not None else base
            self._sources[column] = ds
        return ds


class StackedSegments:
    """Host-stacks homogeneous segments and caches sharded device arrays.

    The TPU-native replacement for the reference's per-segment mmap residency
    (PinotDataBuffer): column lanes live HBM-resident, sharded across the
    mesh, uploaded once and reused by every query.
    """

    def __init__(self, segments: Sequence[ImmutableSegment], mesh: Mesh):
        self.segments = list(segments)
        self.mesh = mesh
        n_dev = mesh.devices.size
        if not self.segments:
            raise NotShardable("no segments")
        if any(getattr(s, "is_mutable", False) for s in self.segments):
            raise NotShardable("mutable (consuming) segment in set")
        pads = {s.padded_docs for s in self.segments}
        if len(pads) != 1:
            raise NotShardable(f"padded doc counts differ: {sorted(pads)}")
        self.padded_docs = pads.pop()
        # pad segment count up to a mesh multiple with empty dummies
        self.n_real = len(self.segments)
        self.n_total = -(-self.n_real // n_dev) * n_dev
        self.num_docs = np.zeros(self.n_total, np.int32)
        self.num_docs[: self.n_real] = [s.num_docs for s in self.segments]
        self._dev_num_docs = None
        self._lanes: Dict[Tuple[str, str], object] = {}  # tpulint: disable=cache-bound -- bounded by columns x lane kinds; the whole stack is LRU-evicted by ShardedQueryExecutor (max_stacks)
        # upsert validDocIds lane: keyed by every segment's bitmap
        # version so invalidations landing after the stack was cached
        # re-upload a fresh [S, P] mask (other lanes are immutable);
        # the host array persists so only CHANGED segments' rows are
        # recomputed, and the lock keeps concurrent queries from
        # mutating it mid-upload
        self._vdoc_cache: Optional[Tuple[tuple, object]] = None
        self._vdoc_host: Optional[np.ndarray] = None
        # guards every cache publish on this stack (lanes, union
        # columns, plan segment, vdoc): queries build lanes from
        # concurrent scheduler workers; heavy builds happen OUTSIDE the
        # lock (first-writer-wins publish), only the vdoc rebuild holds
        # it (in-place host-array mutation)
        self._cache_lock = threading.Lock()
        # col -> None (dictionaries shared) | _UnionColumn (remap needed)
        self._union: Dict[str, Optional["_UnionColumn"]] = {}  # tpulint: disable=cache-bound -- bounded by the table's column count; dies with the stack (executor LRU)
        self._plan_segment = None
        # residency: one ledger prefix per stack. Eviction only drops
        # the executor's dict ref — in-flight queries keep the device
        # lanes alive — so release rides GC via the finalizer, which
        # tracks the actual HBM lifetime.
        self._ledger_prefix = f"stack:{id(self)}:"
        self._ledger_table = self.segments[0].metadata.table_name or ""
        self._ledger_seg = f"stack[{self.n_real}]"
        weakref.finalize(self, residency.LEDGER.release_prefix,
                         self._ledger_prefix)

    #: lane kind → residency ledger kind (everything else is a stacked
    #: scan lane)
    _LEDGER_KINDS = {"vec": "vector", "hllidx": "hll", "hllrank": "hll",
                     "ivfa": "vector", "ivfc": "vector", "ivfv": "vector",
                     "vdoc": "vdoc"}

    def _ledgered_put(self, host, owner_suffix: str, lane_kind: str,
                      sharding):
        return residency.ledgered_put(
            host, owner=self._ledger_prefix + owner_suffix,
            table=self._ledger_table, segment=self._ledger_seg,
            kind=self._LEDGER_KINDS.get(lane_kind, "stack"),
            sharding=sharding)

    def union_column(self, col: str) -> Optional["_UnionColumn"]:
        """None when every segment shares the column's dictionary; else
        the union-dictionary remap artifacts (built once per column).
        Racing builders duplicate work; the first published wins."""
        with self._cache_lock:
            if col in self._union:
                return self._union[col]
        srcs = [s.data_source(col) for s in self.segments]
        d0 = srcs[0].dictionary
        if d0 is None:
            union = None                  # raw column: no id domain
        elif all(np.array_equal(s.dictionary.values, d0.values)
                 for s in srcs[1:]):
            union = None
        else:
            union = _UnionColumn(col, srcs)
        with self._cache_lock:
            return self._union.setdefault(col, union)

    def plan_segment(self) -> ImmutableSegment:
        """Segment view queries plan against: segment 0 with every
        differing-dictionary column replaced by its union view, so
        literal→id binding, part encodings and group decode tables all
        live in the union id domain the stacked lanes use."""
        with self._cache_lock:
            if self._plan_segment is None:
                self._plan_segment = _UnionViewSegment(self)
            return self._plan_segment

    def device_num_docs(self):
        with self._cache_lock:
            if self._dev_num_docs is None:
                self._dev_num_docs = self._ledgered_put(
                    self.num_docs, "num_docs", "stack",
                    NamedSharding(self.mesh, P(SEG_AXIS)))
            return self._dev_num_docs

    def lane(self, col: str, kind: str):
        """Sharded [n_total, ...] device array for one column lane.
        Heavy stack/upload work runs outside the cache lock; racing
        builders duplicate the upload and the first published wins."""
        key = (col, kind)
        with self._cache_lock:
            if key in self._lanes:
                return self._lanes[key]
        union = self.union_column(col) \
            if kind in ("ids", "mv", "vals", "parts", "vlane",
                        "hllidx", "hllrank") else None
        if union is not None:
            arrs = [self._union_operand(union, i, kind)
                    for i in range(self.n_real)]
            card = union.cardinality
        else:
            arrs = [s.data_source(col).host_operand(kind)
                    for s in self.segments]
            card = self.segments[0].data_source(col).metadata.cardinality
        if kind in ("vals", "hllidx", "hllrank"):
            # dictionary-scale tables are identical (or the union
            # table); replicate instead of sharding
            out = self._ledgered_put(arrs[0], f"{col}.{kind}", kind,
                                     NamedSharding(self.mesh, P()))
            with self._cache_lock:
                return self._lanes.setdefault(key, out)
        if kind == "mv":
            w = max(a.shape[1] for a in arrs)
            arrs = [np.pad(a, ((0, 0), (0, w - a.shape[1])),
                           constant_values=card) for a in arrs]
        shapes = {a.shape for a in arrs}
        if len(shapes) != 1:
            raise NotShardable(f"column '{col}' lane shapes differ: {shapes}")
        stacked = np.stack(arrs)
        if self.n_total > self.n_real:
            pad_val = stacked.flat[0] * 0
            if kind in ("ids", "mv"):
                pad_val = card
            filler = np.full((self.n_total - self.n_real,) + stacked.shape[1:],
                             pad_val, stacked.dtype)
            stacked = np.concatenate([stacked, filler])
        out = self._ledgered_put(stacked, f"{col}.{kind}", kind,
                                 NamedSharding(self.mesh, P(SEG_AXIS)))
        with self._cache_lock:
            return self._lanes.setdefault(key, out)

    def _union_operand(self, union: _UnionColumn, i: int,
                       kind: str) -> np.ndarray:
        """Segment i's lane remapped into the union id domain (built
        host-side at stack time — the one-time cost that buys id-domain
        device combine for independently built segments)."""
        from pinot_tpu.segment.loader import min_id_dtype
        ds = self.segments[i].data_source(union.col)
        remap = union.remaps[i]
        if kind == "vals":
            return union.padded_vals
        if kind in ("hllidx", "hllrank"):
            from pinot_tpu.segment.loader import hll_tables_padded
            if union.hll_tables is None:
                union.hll_tables = hll_tables_padded(union.values)
            return union.hll_tables[0 if kind == "hllidx" else 1]
        if kind == "ids":
            local = ds.host_operand("ids")
            return remap[local.astype(np.int64)].astype(
                min_id_dtype(union.cardinality))
        if kind == "mv":
            local = ds.host_operand("mv")
            return remap[local.astype(np.int64)].astype(np.int32)
        if kind == "parts":
            # 7-bit part planes in the UNION encoding (offsets from the
            # union min) so every segment's parts add exactly
            ids = remap[ds.host_operand("ids").astype(np.int64)]
            return union.part_table[:, ids]
        if kind == "vlane":
            return union.f64_vals[
                remap[ds.host_operand("ids").astype(np.int64)]]
        raise ValueError(kind)

    def vdoc_lane(self):
        """Sharded [n_total, padded] bool upsert liveness lane; segments
        without a bitmap (or with none invalid) contribute all-True.
        Incremental: only segments whose bitmap version moved since the
        last build have their row recomputed (steady upserts bump ONE
        segment per batch; an O(S*P) rebuild per query would dwarf the
        mask's benefit)."""
        versions = tuple(
            vd.version if (vd := getattr(s, "valid_doc_ids", None))
            is not None else -1
            for s in self.segments)
        cached = self._vdoc_cache
        if cached is not None and cached[0] == versions:
            return cached[1]
        with self._cache_lock:
            cached = self._vdoc_cache
            if cached is not None and cached[0] == versions:
                return cached[1]
            old = cached[0] if cached is not None else None
            host = self._vdoc_host
            if host is None:
                host = np.zeros((self.n_total, self.padded_docs),
                                dtype=bool)
                old = None
            for i, s in enumerate(self.segments):
                if old is not None and old[i] == versions[i]:
                    continue
                vd = getattr(s, "valid_doc_ids", None)
                row = host[i]
                row[:] = False
                if vd is None:
                    row[: s.num_docs] = True
                else:
                    row[: s.num_docs] = vd.valid_mask(0, s.num_docs)
            # upload a COPY: newer jax CPU backends may zero-copy numpy
            # input, and the next incremental rebuild mutates `host` in
            # place — aliasing would corrupt the cached device lane
            out = self._ledgered_put(host.copy(), "vdoc", "vdoc",
                                     NamedSharding(self.mesh, P(SEG_AXIS)))
            self._vdoc_host = host
            self._vdoc_cache = (versions, out)
            return out

    def gather(self, needed_cols) -> Dict[str, object]:
        # lane keys are "<col>.<kind>" — the same names the kernels read
        cols: Dict[str, object] = {}
        for col, kind in needed_cols:
            if kind == "vdoc":
                cols[f"{col}.vdoc"] = self.vdoc_lane()
            else:
                cols[f"{col}.{kind}"] = self.lane(col, kind)
        return cols


# ---------------------------------------------------------------------------
# Sharded executor
# ---------------------------------------------------------------------------


class ShardedQueryExecutor:
    """Executes one BrokerRequest across all segments on a device mesh.

    Plans once against segment 0 (homogeneity is verified by the stacker),
    runs the sharded kernel, and finishes results host-side with the same
    code the single-segment path uses (shared dictionaries make segment 0's
    decode tables valid for the combined partials).
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 plan_maker: Optional[InstancePlanMaker] = None,
                 max_stacks: int = 4):
        self.mesh = mesh or make_mesh()
        self.plan_maker = plan_maker or InstancePlanMaker()
        # Bounded LRU keyed on the canonical (sorted) name tuple: with
        # randomized routing each server sees many orderings/subsets of the
        # same segment set; sorting collapses orderings to one stack and the
        # LRU bound caps HBM duplication across subsets. A hit additionally
        # requires segment object identity so a refreshed segment (same
        # name, new object) rebuilds instead of serving stale lanes.
        self.max_stacks = max_stacks
        self._stacks: "collections.OrderedDict[Tuple[str, ...], StackedSegments]" = \
            collections.OrderedDict()
        # Queries run on scheduler worker threads while evict_segment fires
        # from segment-transition threads; the lock guards the OrderedDict
        # and the generation counter closes the build/evict race (a stack
        # built concurrently with an eviction is served but never cached).
        self._lock = threading.Lock()
        self._evict_gen = 0

    def stack_for(self, segments: Sequence[ImmutableSegment]
                  ) -> StackedSegments:
        ordered = sorted(segments, key=lambda s: s.segment_name)
        key = tuple(s.segment_name for s in ordered)
        with self._lock:
            st = self._stacks.get(key)
            if st is not None and len(st.segments) == len(ordered) and \
                    all(a is b for a, b in zip(st.segments, ordered)):
                self._stacks.move_to_end(key)
                return st
            gen = self._evict_gen
        st = StackedSegments(ordered, self.mesh)
        with self._lock:
            if self._evict_gen == gen:
                self._stacks[key] = st
                self._stacks.move_to_end(key)
                while len(self._stacks) > self.max_stacks:
                    self._stacks.popitem(last=False)
        return st

    def evict_segment(self, segment_name: str) -> None:
        """Drop every cached stack containing `segment_name`.

        Wired as a segment-removal listener by the server data manager so a
        refreshed/deleted segment's HBM lanes are released promptly instead
        of lingering until LRU pressure.
        """
        with self._lock:
            self._evict_gen += 1
            for key in [k for k in self._stacks if segment_name in k]:
                del self._stacks[key]

    def evict_all(self) -> None:
        """Drop every cached stack. Wired as a residency-manager
        pressure hook: under device-budget pressure the duplicated
        stack lanes are the cheapest HBM to reclaim (stacks rebuild
        from retained host arrays on the next homogeneous query)."""
        with self._lock:
            self._evict_gen += 1
            self._stacks.clear()

    def execute(self, request: BrokerRequest,
                segments: Sequence[ImmutableSegment]
                ) -> IntermediateResultsBlock:
        # debug complement to tpulint host-sync: implicit device→host
        # pulls raise under PINOT_TPU_DEBUG_TRANSFERS=1
        with debug_transfer_guard():
            return self._execute(request, segments)

    def _execute(self, request: BrokerRequest,
                 segments: Sequence[ImmutableSegment]
                 ) -> IntermediateResultsBlock:
        t0 = time.perf_counter()
        from pinot_tpu.query.plan import preprocess_request
        # FASTHLL derived rewrite — on a copy; the shared request must
        # not change under concurrently planning executors
        request = preprocess_request(segments, request)
        stack = self.stack_for(segments)
        # Fast paths (star-tree cubes, metadata/dictionary answers) are
        # per-segment host work in each segment's OWN id domain — probe
        # them against segment 0 directly and let the sequential
        # executor serve them (it re-plans per segment).
        plan0 = self.plan_maker.make_segment_plan(stack.segments[0],
                                                  request)
        if plan0.fast_path_result is not None:
            raise NotShardable("fast-path plan; no device work to shard")
        # Plan against the union view: every dictionary-encoded column the
        # request references resolves to the union id domain the stacked
        # lanes use — including predicates that constant-fold to
        # MATCH_ALL/EMPTY (folding against the union dictionary is valid
        # for every segment, which folding against segment 0 alone was
        # not). Fully shared-dictionary stacks reuse plan0 — the union
        # view would produce the identical plan, so don't plan twice.
        needs_union = any(
            stack.union_column(col) is not None
            for col in request.referenced_columns()
            if stack.segments[0].has_column(col) and
            stack.segments[0].data_source(col).dictionary is not None)
        seg0 = stack.plan_segment() if needs_union else stack.segments[0]
        if request.is_group_by:
            # raw group keys bin by segment 0's min/max — every segment
            # must share that range or rows would clip into wrong bins
            for col in request.group_by.columns:
                if not seg0.has_column(col):
                    continue
                cm0 = seg0.data_source(col).metadata
                if cm0.has_dictionary:
                    continue
                for s in stack.segments[1:]:
                    cm = s.data_source(col).metadata
                    if (cm.min_value, cm.max_value) != (cm0.min_value,
                                                        cm0.max_value):
                        raise NotShardable(
                            f"raw group column '{col}' min/max differ "
                            "across segments")
        plan = plan0 if not needs_union else \
            self.plan_maker.make_segment_plan(seg0, request)

        # ANN probe homogeneity: the shared plan (built against segment
        # 0) either carries the ivf_probe pred for EVERY stacked segment
        # or for none. A mixed stack would diverge from the sequential
        # path's per-segment index-vs-exact decision, so fall back; lane
        # shape disagreements (different padded codebooks) are caught by
        # the stacker's shape check during gather.
        vec = request.vector
        if vec is not None and int(getattr(vec, "nprobe", 0) or 0) > 0:
            presence = {
                getattr(s.data_source(vec.column), "ivf_centroids", None)
                is not None
                for s in stack.segments}
            if len(presence) > 1:
                raise NotShardable(
                    "stacked segments disagree on IVF index presence")

        # upsert validDocIds: if ANY stacked segment has superseded rows
        # the mask predicate must cover the WHOLE stack (planning against
        # segment 0 alone would miss other segments' masks). The wrap is
        # param-free, so plan params/strides are untouched; plans that
        # already carry the pred (segment 0 itself masked) pass through.
        from pinot_tpu.query.plan import (upsert_mask_active,
                                          with_valid_doc_mask,
                                          VALID_DOC_COLUMN)
        if any(upsert_mask_active(s) for s in stack.segments) and \
                plan.filter_spec is not None:
            import copy as _copy
            plan = _copy.copy(plan)
            plan.filter_spec = with_valid_doc_mask(plan.filter_spec)
            if (VALID_DOC_COLUMN, "vdoc") not in plan.needed_cols:
                plan.needed_cols = plan.needed_cols + (
                    (VALID_DOC_COLUMN, "vdoc"),)

        cols = stack.gather(plan.needed_cols)
        lane_keys = tuple(sorted(cols.keys()))

        def run(agg_specs, group_spec, extra_params=()):
            # returns DEVICE outs; drivers batch the device→host pull
            # into one explicit jax.device_get per dispatch
            fn = get_sharded_kernel(
                self.mesh, stack.padded_docs, plan.filter_spec,
                tuple(agg_specs or ()), group_spec, plan.select_spec,
                lane_keys)
            return fn(cols, tuple(plan.params) + tuple(extra_params),
                      stack.device_num_docs())

        from pinot_tpu.query.plan import (drive_group_execution,
                                          set_group_kmax)
        blk = IntermediateResultsBlock()
        if plan.group_spec is not None:
            spec0 = set_group_kmax(plan.group_spec, stack.padded_docs)
            outs, spec_used = drive_group_execution(
                run, spec0, stack.padded_docs, int(stack.num_docs.sum()))
            if spec_used is None:
                blk.group_map = {}
            else:
                execution._finish_group_by(
                    execution._with_group_spec(plan, spec_used), outs, blk)
        else:
            outs = profiled_device_get(run(plan.agg_specs, None, ()))
            if plan.agg_specs:
                execution._finish_aggregation(plan, outs, blk)
        matched = int(outs["stats.num_docs_matched"])
        if plan.select_spec is not None:
            self._finish_selection(request, plan, stack, outs, blk)

        n_leaves = execution._count_filter_leaves(plan.filter_spec)
        n_project = len({c for c, _ in plan.needed_cols})
        total_docs = int(stack.num_docs.sum())
        seg_matched = np.asarray(outs["stats.seg_matched"])[: stack.n_real]
        blk.stats = ExecutionStats(
            num_docs_scanned=matched,
            num_entries_scanned_in_filter=n_leaves * total_docs,
            num_entries_scanned_post_filter=matched * max(
                n_project - n_leaves, 0),
            num_segments_processed=stack.n_real,
            num_segments_matched=int((seg_matched > 0).sum()),
            total_docs=total_docs,
            time_used_ms=(time.perf_counter() - t0) * 1e3)
        return blk

    def _finish_selection(self, request, plan, stack, outs, blk) -> None:
        """Per-segment selection finish + host top-k merge.

        Parity: CombineService selection merge — each segment returns its
        own (already ordered/limited) rows; the combiner re-sorts and trims.
        """
        if plan.select_spec[0] == "vector":
            self._finish_vector(request, plan, stack, outs, blk)
            return
        rows_all: List[tuple] = []
        columns = None
        seg_matched = np.asarray(outs["stats.seg_matched"])
        decode_seg = stack.plan_segment()   # union-domain decode tables
        for i, seg in enumerate(stack.segments):
            sub = {k: v[i] for k, v in outs.items() if k.startswith("sel.")}
            seg_plan = SegmentPlan(
                segment=decode_seg, request=request,
                select_spec=plan.select_spec, needed_cols=plan.needed_cols,
                select_display=plan.select_display)
            seg_blk = IntermediateResultsBlock()
            execution._finish_selection(seg_plan, sub, seg_blk,
                                        int(seg_matched[i]))
            columns = seg_blk.selection_columns
            if rows_all and seg_blk.selection_rows:
                # merge_selection_rows re-sorts (when ordered) and trims to
                # offset+size — the limit is enforced here
                rows_all = combine_mod.merge_selection_rows(
                    request, columns, rows_all, seg_blk.selection_rows)
            elif seg_blk.selection_rows:
                rows_all = seg_blk.selection_rows
        sel = request.selection
        blk.selection_rows = rows_all[: sel.offset + sel.size]
        blk.selection_columns = columns
        blk.selection_display_cols = plan.select_display

    def _finish_vector(self, request, plan, stack, outs, blk) -> None:
        """Per-shard local top-k → exact global merge by score.

        Each stacked segment's kernel lane already holds its own exact
        top-k (the per-shard local top-k); the global k is the score-
        ordered merge — identity (segment name, docid) comes from the
        REAL segment, while dictionary decode of ride-along columns goes
        through the union view (the stacked lanes' id domain)."""
        from pinot_tpu.common.request import VECTOR_RESULT_COLUMNS
        decode_seg = stack.plan_segment()
        columns = [c for c, _ in plan.select_spec[3]] + \
            list(VECTOR_RESULT_COLUMNS)
        rows_all: List[tuple] = []
        for i, seg in enumerate(stack.segments):
            sub = {k: v[i] for k, v in outs.items()
                   if k.startswith("sel.")}
            name, base = execution.vector_segment_identity(seg)
            rows = execution.vector_result_rows(
                decode_seg, plan.select_spec, sub, name, base)
            if rows_all and rows:
                rows_all = combine_mod.merge_selection_rows(
                    request, columns, rows_all, rows)
            elif rows:
                rows_all = rows
        sel = request.selection
        blk.selection_rows = rows_all[: sel.offset + sel.size]
        blk.selection_columns = columns
        blk.selection_display_cols = None
