"""Broker-side two-stage orchestration.

A join query runs: stage 1 — the dim-side scan (dim WHERE conjuncts,
join key + referenced dim columns) dispatched to the dim table's
servers with a ``publish_exchange`` tag, each returning a small ack
(exchange id/key, row count, partition tags); stage 2 — the normal fact
scatter (hedges/failover intact, via QueryRouter) with the ack-derived
source descriptors stamped into every InstanceRequest so fact servers
fetch the dim blocks server↔server over the data plane.

A window query runs: stage 1 — the scan (display + window input
columns) published by every routed fact server; stage 2 — ONE
coordinator server (deterministically the first of the routed set)
fetches all blocks and runs the window kernel.

Stage-compile failures (unknown dim table, dim side over the broadcast
cap, typed server-side stage errors) surface as errorCode-tagged
entries the request handler maps to 4xx responses — never crashes, and
never the generic 425 fault class clients would retry.
"""
from __future__ import annotations

import asyncio
import copy
import json
import time
from typing import List, Optional

from pinot_tpu.common.datatable import DataTable, STAGE_ERROR_KEY
from pinot_tpu.common.request import (BrokerRequest, InstanceRequest,
                                      Selection)
from pinot_tpu.common.serde import instance_request_to_bytes
from pinot_tpu.common.table_name import raw_table
from pinot_tpu.query.stages.errors import STAGE_COMPILE_ERROR_CODE
from pinot_tpu.query.stages.join import DIM_CAP
from pinot_tpu.query.stages.window import WINDOW_CAP, scan_columns


def _stage_error(server: str, message: str, code: int) -> dict:
    return {"server": server, "message": message, "recovered": False,
            "errorCode": code}


def _busy_error(server: str, dt: DataTable, what: str):
    """Typed server-busy classification for stage dispatches: an
    admission shed must keep its 503/Retry-After surface (the
    busyCause/retryAfterMs markers _finish keys on), never degrade to
    a retriable 425 fault or reduce as an empty success."""
    from pinot_tpu.common.datatable import (RETRY_AFTER_MS_KEY,
                                            SERVER_BUSY_KEY)
    cause = dt.metadata.get(SERVER_BUSY_KEY)
    if cause is None:
        return None
    err = _stage_error(
        server, f"ServerBusyError: {what} shed ({cause})", 0)
    err.pop("errorCode")        # _finish derives 503 from busyCause
    err["busyCause"] = cause
    try:
        err["retryAfterMs"] = float(
            dt.metadata.get(RETRY_AFTER_MS_KEY, "0"))
    except (TypeError, ValueError):
        err["retryAfterMs"] = 0.0
    return err


def dim_scan_request(request: BrokerRequest) -> BrokerRequest:
    """The stage-1 dim scan: dim-side WHERE + (key, referenced columns)
    selection, capped at the broadcast window (the publish ack fails
    loudly when the filtered dim side exceeds it)."""
    join = request.join
    cols = [join.dim_key] + [c for c in join.dim_columns
                             if c != join.dim_key]
    return BrokerRequest(
        table_name=join.dim_table, filter=join.dim_filter,
        selection=Selection(columns=cols, order_by=[], offset=0,
                            size=DIM_CAP),
        limit=DIM_CAP)


def window_scan_request(sub: BrokerRequest,
                        request: BrokerRequest) -> BrokerRequest:
    """The stage-1 window scan for one physical sub-request: same table
    and (time-boundary-attached) filter, selecting display + window
    input columns, no windows."""
    scan = copy.copy(sub)
    scan.windows = []
    scan.selection = Selection(columns=scan_columns(request), order_by=[],
                               offset=0, size=WINDOW_CAP)
    scan.limit = WINDOW_CAP
    return scan


async def _publish_unit(handler, sub: BrokerRequest, server: str,
                        segments, xid: str, key_column: str,
                        request_id: int, deadline: float,
                        workload: Optional[str]):
    """One stage-1 publish dispatch → (source descriptor | None, error
    dict | None)."""
    transport = handler.router.transport
    budget = deadline - time.monotonic()
    if budget <= 0:
        return None, _stage_error(
            server, "DeadlineExceededError: no budget left for the "
            "stage-1 scan", 408)
    payload = instance_request_to_bytes(InstanceRequest(
        request_id=request_id, query=sub, search_segments=segments,
        broker_id=handler.router.broker_id,
        deadline_budget_ms=budget * 1e3, workload=workload,
        publish_exchange={"id": xid, "keyColumn": key_column}))
    try:
        raw = await asyncio.wait_for(
            transport.query(server, payload, budget), budget)
        from pinot_tpu.transport.shm import datatable_from_reply
        dt = datatable_from_reply(raw)
    except Exception as e:  # noqa: BLE001 — transport-class failure
        return None, _stage_error(
            server, f"ExchangeStageError: stage-1 publish to {server} "
            f"failed: {type(e).__name__}: {e}", 0)
    busy = _busy_error(server, dt, "stage-1 scan")
    if busy is not None:
        return None, busy
    kind = dt.metadata.get(STAGE_ERROR_KEY)
    if kind is not None:
        msg = dt.exceptions[0] if dt.exceptions else kind
        return None, _stage_error(server, str(msg),
                                  STAGE_COMPILE_ERROR_CODE)
    if dt.exceptions:
        return None, _stage_error(
            server, f"ExchangeStageError: stage-1 scan on {server} "
            f"failed: {dt.exceptions[0]}", 0)
    endpoints = getattr(transport, "endpoints", None) or {}
    host, port = endpoints.get(server, (None, None))
    source = {"server": server, "id": xid,
              "xkey": dt.metadata.get("exchangeKey"),
              "host": host, "port": port,
              "rows": int(dt.metadata.get("exchangeRows", "0"))}
    parts = dt.metadata.get("exchangePartitions")
    if parts is not None:
        try:
            source["partitions"] = json.loads(parts)
            source["partitionFunction"] = dt.metadata.get(
                "partitionFunction")
            source["numPartitions"] = int(dt.metadata.get(
                "numPartitions", "0"))
        except (ValueError, TypeError):
            pass
    return source, None


async def _publish_stage(handler, scan_routes, key_column: str,
                         request_id: int, deadline: float,
                         workload: Optional[str]):
    """Dispatch every (sub, server, segments) stage-1 unit → (sources,
    errors, queried). Sources is None when any unit failed (a join/
    window over a PARTIAL dim/scan side would be silently wrong)."""
    units = []
    for sub, routing in scan_routes:
        for server, segments in sorted(routing.items()):
            xid = f"x{request_id}.{len(units)}"
            units.append((sub, server, segments, xid))
    results = await asyncio.gather(
        *(_publish_unit(handler, sub, server, segments, xid, key_column,
                        request_id, deadline, workload)
          for sub, server, segments, xid in units))
    sources, errors = [], []
    for src, err in results:
        if err is not None:
            errors.append(err)
        elif src is not None:
            sources.append(src)
    if errors:
        return None, errors, len(units)
    return sources, [], len(units)


async def scatter_stages(handler, request: BrokerRequest, routes,
                         timeout_s: float, deadline: float, trace,
                         workload: Optional[str], request_id: int):
    """Multi-stage scatter → the same (tables, queried, responded,
    errors) contract as QueryRouter.submit."""
    if request.join is not None:
        return await _scatter_join(handler, request, routes, deadline,
                                   trace, workload, request_id)
    return await _scatter_window(handler, request, routes, deadline,
                                 trace, workload, request_id)


async def _scatter_join(handler, request, routes, deadline, trace,
                        workload, request_id: int):
    join = request.join
    dim_proto = dim_scan_request(request)
    dim_routes, err = handler._resolve_routes(dim_proto,
                                              raw_table(join.dim_table))
    if err is not None:
        # unknown dim table and friends — reuse the resolver's typed
        # response (190 TableDoesNotExist / RoutingError)
        exc = err.exceptions[0]
        return [], 0, 0, [_stage_error("broker", exc["message"],
                                       exc["errorCode"])]
    sources, errors, queried1 = await _publish_stage(
        handler, dim_routes, join.dim_key, request_id, deadline,
        workload)
    if sources is None:
        return [], queried1, 0, errors
    total_rows = sum(s["rows"] for s in sources)
    if total_rows > DIM_CAP:
        return [], queried1, queried1, [_stage_error(
            "broker", f"JoinCapacityError: dim side has {total_rows} "
            f"rows after filtering > broadcast cap {DIM_CAP} — narrow "
            "the dim-side WHERE", STAGE_COMPILE_ERROR_CODE)]
    budget = max(deadline - time.monotonic(), 0.0)
    tables, queried2, responded, errors2 = await handler.router.submit(
        request_id, routes, budget,
        enable_trace=request.query_options.trace, deadline=deadline,
        trace=trace, workload=workload, exchange_sources=sources)
    # same moved-segment tolerance as the single-stage scatter: one
    # re-dispatch against the current view (retried InstanceRequests
    # carry the SAME exchange sources — the dim side is already
    # published and any replica can fetch it)
    tables, rq, rr, retry_errors = await handler._retry_missing_segments(
        routes, tables, deadline,
        enable_trace=request.query_options.trace, trace=trace,
        workload=workload, exchange_sources=sources)
    return (tables, queried1 + queried2 + rq,
            queried1 + responded + rr, errors2 + retry_errors)


async def _scatter_window(handler, request, routes, deadline, trace,
                          workload, request_id: int):
    scan_routes = [(window_scan_request(sub, request), routing)
                   for sub, routing in routes]
    sources, errors, queried1 = await _publish_stage(
        handler, scan_routes, "", request_id, deadline, workload)
    if sources is None:
        return [], queried1, 0, errors
    servers = sorted({server for _sub, routing in routes
                      for server in routing})
    if not servers:
        return [], queried1, queried1, []
    coordinator = servers[0]
    budget = max(deadline - time.monotonic(), 0.01)
    payload = instance_request_to_bytes(InstanceRequest(
        request_id=request_id, query=request, search_segments=[],
        broker_id=handler.router.broker_id,
        deadline_budget_ms=budget * 1e3, workload=workload,
        exchange_sources=sources))
    try:
        raw = await asyncio.wait_for(
            handler.router.transport.query(coordinator, payload, budget),
            budget)
        from pinot_tpu.transport.shm import datatable_from_reply
        dt = datatable_from_reply(raw)
    except Exception as e:  # noqa: BLE001 — transport-class failure
        return [], queried1 + 1, queried1, [_stage_error(
            coordinator, f"ExchangeStageError: window stage 2 on "
            f"{coordinator} failed: {type(e).__name__}: {e}", 0)]
    busy = _busy_error(coordinator, dt, "window stage 2")
    if busy is not None:
        return [], queried1 + 1, queried1, [busy]
    dt.metadata.setdefault("serverName", coordinator)
    return [dt], queried1 + 1, queried1 + 1, []
