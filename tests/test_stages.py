"""Multi-stage query engine: joins, windows, HLL kernels, exchange plane.

Parity philosophy matches the rest of the suite: every new kernel has a
host-oracle twin and the tests pin BIT-identical results across host,
device and sharded paths — including under upsert masking — plus typed
4xx negative paths and the exchange plane's unit semantics.
"""
import json
import os
import tempfile

import numpy as np
import pytest

from pinot_tpu.common.request import BrokerRequest, JoinSpec
from pinot_tpu.common.serde import (instance_request_from_bytes,
                                    instance_request_to_bytes,
                                    request_from_json, request_to_json)
from pinot_tpu.common.request import InstanceRequest
from pinot_tpu.pql.parser import PqlSyntaxError, compile_pql
from pinot_tpu.query.stages import exchange as xmod
from pinot_tpu.query.stages import join as jmod
from pinot_tpu.query.stages import window as wmod
from pinot_tpu.query.stages.errors import StageCompileError
from pinot_tpu.tools.datagen import (build_join_table_dirs,
                                     fact_join_schema, join_oracle,
                                     join_table_configs, part_dim_schema)


# ---------------------------------------------------------------------------
# PQL + serde
# ---------------------------------------------------------------------------


def test_join_parse_and_serde_roundtrip():
    q = ("SELECT SUM(f.lo_revenue), COUNT(*) FROM f JOIN part "
         "ON f.lo_partkey = part.p_partkey "
         "WHERE part.p_category = 'MFGR#12' AND f.lo_quantity < 25 "
         "GROUP BY part.p_brand1, f.d_year TOP 7")
    r = compile_pql(q)
    j = r.join
    assert (j.dim_table, j.fact_key, j.dim_key) == \
        ("part", "lo_partkey", "p_partkey")
    assert j.dim_columns == ["p_brand1"]
    assert j.dim_filter.column == "p_category"      # dim conjunct split
    assert r.filter.column == "lo_quantity"         # fact conjunct stays
    assert r.group_by.columns == ["part.p_brand1", "d_year"]
    assert [a.column for a in r.aggregations] == ["lo_revenue", "*"]
    r2 = request_from_json(request_to_json(r))
    assert r2.join == j
    assert r2.group_by.columns == r.group_by.columns
    # dim-qualified names never leak into fact-side column resolution
    assert "part.p_brand1" not in r.referenced_columns()
    assert "lo_partkey" in r.referenced_columns()


def test_window_parse_and_serde_roundtrip():
    q = ("SELECT d_year, lo_revenue, "
         "ROW_NUMBER() OVER (PARTITION BY d_year ORDER BY lo_revenue "
         "DESC), SUM(lo_quantity) OVER (PARTITION BY d_year ORDER BY "
         "lo_revenue DESC) FROM t WHERE lo_quantity < 9 LIMIT 20")
    r = compile_pql(q)
    assert [w.function for w in r.windows] == ["ROW_NUMBER", "SUM"]
    assert r.windows[1].column == "lo_quantity"
    assert r.windows[0].partition_by == ["d_year"]
    assert not r.windows[0].order_by[0].ascending
    assert r.selection.columns == ["d_year", "lo_revenue"]
    r2 = request_from_json(request_to_json(r))
    assert r2.windows == r.windows
    assert sorted(r.referenced_columns()) == \
        ["d_year", "lo_quantity", "lo_revenue"]


def test_instance_request_stage_keys_roundtrip():
    req = InstanceRequest(
        request_id=7, query=compile_pql("SELECT COUNT(*) FROM t"),
        publish_exchange={"id": "x7.0", "keyColumn": "k"},
        exchange_sources=[{"server": "s", "xkey": "u", "id": "x7.0",
                           "host": None, "port": None, "rows": 3}])
    r2 = instance_request_from_bytes(instance_request_to_bytes(req))
    assert r2.publish_exchange == req.publish_exchange
    assert r2.exchange_sources == req.exchange_sources


@pytest.mark.parametrize("bad", [
    # malformed JOIN
    "SELECT COUNT(*) FROM f JOIN d",
    "SELECT COUNT(*) FROM f JOIN d ON f.k < d.j",
    "SELECT COUNT(*) FROM f JOIN d ON f.k = f.j",
    "SELECT COUNT(*) FROM f JOIN f ON f.k = f.k",
    "SELECT COUNT(*) FROM f JOIN d ON k = d.j",
    # unsupported join shapes (typed, never a crash)
    "SELECT f.a FROM f JOIN d ON f.k = d.j",
    "SELECT SUM(d.m) FROM f JOIN d ON f.k = d.j",
    "SELECT COUNT(*) FROM f JOIN d ON f.k = d.j WHERE f.a = 1 OR d.b = 2",
    "SELECT COUNT(*) FROM f JOIN d ON f.k = d.j GROUP BY x",
    # malformed OVER
    "SELECT ROW_NUMBER() OVER (PARTITION BY a) FROM t",
    "SELECT ROW_NUMBER() FROM t",
    "SELECT AVG(x) OVER (ORDER BY y) FROM t",
    "SELECT SUM(x) OVER (ORDER BY y), COUNT(*) FROM t",
    "SELECT ROW_NUMBER() OVER (ORDER BY y) FROM t ORDER BY y",
    "SELECT * , ROW_NUMBER() OVER (ORDER BY y) FROM t",
    # malformed HLL
    "SELECT DISTINCTCOUNTHLL() FROM t",
    "SELECT DISTINCTCOUNTHLL(a, b) FROM t",
])
def test_pql_negative_paths_are_typed(bad):
    with pytest.raises(PqlSyntaxError):
        compile_pql(bad)


# ---------------------------------------------------------------------------
# Exchange plane
# ---------------------------------------------------------------------------


def test_exchange_manager_put_get_ttl_and_capacity():
    clock = [0.0]
    m = xmod.ExchangeManager(ttl_s=10.0, max_bytes=100,
                             clock=lambda: clock[0])
    try:
        m.put("a", b"x" * 60)
        assert m.get("a") == b"x" * 60
        with pytest.raises(Exception):          # over the byte budget
            m.put("b", b"y" * 60)
        clock[0] = 11.0                          # TTL expiry frees space
        assert m.get("a") is None
        m.put("b", b"y" * 60)
        assert m.get("b") == b"y" * 60
    finally:
        m.close()


def test_exchange_frame_fetch_and_miss():
    m = xmod.ExchangeManager()
    try:
        from pinot_tpu.common.datatable import DataTable
        dt = DataTable()
        dt.metadata["k"] = "v"
        m.put("x1.0", dt.to_bytes())
        reply = m.handle_frame(xmod.fetch_frame("x1.0"))
        assert DataTable.from_bytes(reply).metadata["k"] == "v"
        miss = DataTable.from_bytes(m.handle_frame(xmod.fetch_frame("no")))
        assert any("ExchangeMissError" in e for e in miss.exceptions)
        # local-registry fetch path
        got = xmod.fetch_block({"server": "s", "xkey": m.xkey,
                                "id": "x1.0"}, 1.0)
        assert got.metadata["k"] == "v"
        with pytest.raises(xmod.ExchangeError):
            xmod.fetch_block({"server": "s", "xkey": m.xkey,
                              "id": "gone"}, 1.0)
    finally:
        m.close()


def test_filter_sources_copartitioned():
    sources = [
        {"server": "a", "id": "x1", "partitions": [0],
         "partitionFunction": "Modulo", "numPartitions": 2},
        {"server": "b", "id": "x2", "partitions": [1],
         "partitionFunction": "Modulo", "numPartitions": 2},
        {"server": "c", "id": "x3"},                     # untagged
        {"server": "d", "id": "x4", "partitions": [1],
         "partitionFunction": "Murmur", "numPartitions": 2},  # fn differs
    ]
    kept, skipped = jmod.filter_sources(sources, ("Modulo", 2, {0}))
    assert [s["server"] for s in kept] == ["a", "c", "d"]
    assert skipped == 1
    # unknown fact partitions → fetch everything (superset is correct)
    kept, skipped = jmod.filter_sources(sources, None)
    assert len(kept) == 4 and skipped == 0


# ---------------------------------------------------------------------------
# Join parity: host vs device vs sharded, dict and raw keys
# ---------------------------------------------------------------------------


def _load_segments(dirs):
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    return [ImmutableSegmentLoader.load(d) for d in dirs]


def _join_ctx(spec, dim):
    cols = {c: dim[c] for c in spec.dim_columns}
    return jmod.JoinContext(spec, dim[spec.dim_key].astype(np.int64),
                            cols)


def _attach(request, ctx):
    import copy
    out = copy.copy(request)
    out._join_ctx = ctx
    return out


def _reduce(request, block):
    from pinot_tpu.query.reduce import BrokerReduceService
    return BrokerReduceService().reduce(request, [block]).to_json()


@pytest.fixture(scope="module")
def join_fixture(tmp_path_factory):
    base = str(tmp_path_factory.mktemp("join"))
    fact_dirs, dim_dirs, dim, fact = build_join_table_dirs(
        base, fact_rows=12000, num_fact_segments=3, dim_rows=400, seed=5)
    return _load_segments(fact_dirs), dim, fact


def test_join_parity_host_device_sharded(join_fixture):
    segments, dim, fact = join_fixture
    q = ("SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM lineorderj "
         "JOIN part ON lineorderj.lo_partkey = part.p_partkey "
         "WHERE part.p_mfgr = 'MFGR#2' AND lineorderj.lo_quantity < 30 "
         "GROUP BY part.p_brand1, lineorderj.d_year TOP 5000")
    request = compile_pql(q)
    mask = lambda d: d["p_mfgr"] == "MFGR#2"  # noqa: E731
    dmask = np.asarray(mask(dim))
    spec = request.join
    ctx = _join_ctx(spec, {k: (v[dmask] if isinstance(v, np.ndarray)
                               else v) for k, v in dim.items()})
    req = _attach(request, ctx)

    from pinot_tpu.query.executor import ServerQueryExecutor
    host = _reduce(request, ServerQueryExecutor(use_device=False)
                   .execute(req, segments))
    dev = _reduce(request, ServerQueryExecutor(use_device=True)
                  .execute(req, segments))
    from pinot_tpu.parallel.sharded import ShardedQueryExecutor, make_mesh
    sh = _reduce(request, ShardedQueryExecutor(mesh=make_mesh())
                 .execute(req, segments))

    def as_dict(r, fi):
        # (group → value) map: top-N TIE order legitimately differs by
        # path (insertion order breaks ties); the VALUES must be exact
        return {tuple(g["group"]): g["value"]
                for g in r["aggregationResults"][fi]["groupByResult"]}

    for fi in range(2):
        assert as_dict(host, fi) == as_dict(dev, fi)
        assert as_dict(host, fi) == as_dict(sh, fi)

    # and all three equal the independent numpy oracle
    fq = fact["lo_quantity"] < 30
    o = join_oracle(dim, {k: (v[fq] if isinstance(v, np.ndarray) else v)
                          for k, v in fact.items()},
                    dim_filter=mask,
                    group_cols=["part.p_brand1", "lineorderj.d_year"])
    got = {k: float(v) for k, v in as_dict(host, 0).items()}
    exp = {(k[0], int(k[1])): float(v[0]) for k, v in o["groups"].items()}
    assert got == exp


def test_join_raw_key_parity(tmp_path):
    """Raw (no-dictionary) fact key: the device-built sorted probe
    (join_raw/jraw) agrees bit-for-bit with the host twin."""
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.datagen import make_join_rows
    dim, fact = make_join_rows(6000, dim_rows=250, seed=9)
    cfg = TableConfig("lineorderj", indexing_config=IndexingConfig(
        no_dictionary_columns=["lo_partkey"]))
    d = str(tmp_path / "seg0")
    SegmentCreator(fact_join_schema(), cfg,
                   segment_name="rawk_0").build(fact, d)
    segments = _load_segments([d])
    q = ("SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM lineorderj "
         "JOIN part ON lineorderj.lo_partkey = part.p_partkey "
         "GROUP BY part.p_category TOP 100")
    request = compile_pql(q)
    ctx = _join_ctx(request.join, dim)
    req = _attach(request, ctx)
    from pinot_tpu.query.executor import ServerQueryExecutor
    host = _reduce(request, ServerQueryExecutor(use_device=False)
                   .execute(req, segments))
    dev = _reduce(request, ServerQueryExecutor(use_device=True)
                  .execute(req, segments))
    assert host["aggregationResults"] == dev["aggregationResults"]
    o = join_oracle(dim, fact, group_cols=["part.p_category"])
    got = {g["group"][0]: float(g["value"])
           for g in dev["aggregationResults"][0]["groupByResult"]}
    assert got == {k[0]: float(v[0]) for k, v in o["groups"].items()}


def test_join_upsert_mask_never_leaks(join_fixture):
    """Invalidated (upsert-superseded) fact rows never reach a join
    side — host and device agree after the mask flips mid-sequence."""
    segments, dim, fact = join_fixture
    seg = segments[0]
    from pinot_tpu.realtime.upsert import ValidDocIds
    q = ("SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM lineorderj "
         "JOIN part ON lineorderj.lo_partkey = part.p_partkey")
    request = compile_pql(q)
    ctx = _join_ctx(request.join, dim)
    req = _attach(request, ctx)
    from pinot_tpu.query.executor import ServerQueryExecutor
    base_dev = _reduce(request, ServerQueryExecutor(use_device=True)
                       .execute(req, [seg]))
    vd = ValidDocIds()
    killed = [0, 5, 17, 100]
    for doc in killed:
        vd.invalidate(doc)
    seg.valid_doc_ids = vd
    try:
        host = _reduce(request, ServerQueryExecutor(use_device=False)
                       .execute(req, [seg]))
        dev = _reduce(request, ServerQueryExecutor(use_device=True)
                      .execute(req, [seg]))
        assert host["aggregationResults"] == dev["aggregationResults"]
        assert dev["aggregationResults"] != base_dev["aggregationResults"]
        # the masked rows' contribution is exactly absent
        n = seg.num_docs
        keys = np.sort(np.unique(dim["p_partkey"].astype(np.int64)))
        fk = fact["lo_partkey"][:n].astype(np.int64)
        pos = np.clip(np.searchsorted(keys, fk), 0, len(keys) - 1)
        hit = keys[pos] == fk
        alive = hit.copy()
        alive[killed] = False
        exp_count = int(alive.sum())
        got_count = int(float(
            dev["aggregationResults"][1]["value"]))
        assert got_count == exp_count
    finally:
        seg.valid_doc_ids = None


def test_join_empty_dim_side(join_fixture):
    segments, dim, _fact = join_fixture
    q = ("SELECT COUNT(*) FROM lineorderj JOIN part "
         "ON lineorderj.lo_partkey = part.p_partkey")
    request = compile_pql(q)
    ctx = jmod.JoinContext(request.join, np.zeros(0, np.int64), {})
    req = _attach(request, ctx)
    from pinot_tpu.query.executor import ServerQueryExecutor
    for dev in (False, True):
        out = _reduce(request, ServerQueryExecutor(use_device=dev)
                      .execute(req, segments))
        assert float(out["aggregationResults"][0]["value"]) == 0


def test_join_context_typed_errors():
    spec = JoinSpec(dim_table="part", fact_key="k", dim_key="pk")
    with pytest.raises(StageCompileError):      # duplicate dim keys
        jmod.JoinContext(spec, np.array([1, 2, 2], np.int64), {})
    with pytest.raises(StageCompileError):      # non-integer keys
        jmod.JoinContext(spec, np.array(["a", "b"], dtype=object), {})
    ctx = jmod.JoinContext(spec, np.array([3, 1, 7], np.int64), {})
    with pytest.raises(StageCompileError):      # unshipped dim column
        ctx.dim_values("missing")
    hit, dimrow = ctx.probe_values(np.array([1, 2, 7]))
    assert hit.tolist() == [True, False, True]
    assert dimrow[hit].tolist() == [1, 2]


# ---------------------------------------------------------------------------
# Window functions
# ---------------------------------------------------------------------------


def _window_request(sum_col="v"):
    return compile_pql(
        f"SELECT g, o, ROW_NUMBER() OVER (PARTITION BY g ORDER BY o), "
        f"SUM({sum_col}) OVER (PARTITION BY g ORDER BY o) FROM t "
        f"LIMIT 100000")


def test_window_parity_device_vs_host():
    rng = np.random.default_rng(11)
    n = 3000
    cols = {"g": rng.integers(0, 13, n).astype(np.int64),
            "o": rng.integers(0, 500, n).astype(np.int64),
            "v": rng.integers(-50, 50, n).astype(np.int64)}
    req = _window_request()
    dev = wmod.execute_window(req, dict(cols), n, use_device=True)
    host = wmod.execute_window(req, dict(cols), n, use_device=False)
    for a, b in zip(dev.selection_cols, host.selection_cols):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # semantic invariants vs a straightforward pandas-style oracle
    dcols = {c: np.asarray(v) for c, v in
             zip(dev.selection_columns, dev.selection_cols)}
    rn = dcols["row_number()_over"]
    run = dcols["sum(v)_over"]
    g, o, v = dcols["g"], dcols["o"], dcols["v"] if "v" in dcols else None
    # per-partition: rn is 1..count in order, running sum telescopes
    for gv in np.unique(g):
        rows = np.nonzero(g == gv)[0]
        assert rn[rows].tolist() == list(range(1, len(rows) + 1))
        assert (np.diff(o[rows]) >= 0).all()
    total = {gv: cols["v"][cols["g"] == gv].sum()
             for gv in np.unique(cols["g"])}
    for gv in np.unique(g):
        rows = np.nonzero(g == gv)[0]
        assert run[rows][-1] == total[gv]


def test_window_string_partition_and_desc_order():
    n = 500
    rng = np.random.default_rng(3)
    cols = {"g": np.array([f"t{int(i)}" for i in rng.integers(0, 4, n)],
                          dtype=object),
            "o": rng.integers(0, 50, n).astype(np.int64),
            "v": rng.integers(0, 9, n).astype(np.int64)}
    req = compile_pql(
        "SELECT g, o, ROW_NUMBER() OVER (PARTITION BY g ORDER BY o "
        "DESC), SUM(v) OVER (PARTITION BY g ORDER BY o DESC) FROM t "
        "LIMIT 100000")
    dev = wmod.execute_window(req, dict(cols), n, use_device=True)
    host = wmod.execute_window(req, dict(cols), n, use_device=False)
    for a, b in zip(dev.selection_cols, host.selection_cols):
        assert np.array_equal(np.asarray(a, dtype=object),
                              np.asarray(b, dtype=object))
    o = np.asarray(dev.selection_cols[1])
    g = np.asarray(dev.selection_cols[0], dtype=object)
    for gv in np.unique(g):
        assert (np.diff(o[g == gv]) <= 0).all()    # DESC within partition


def test_window_typed_errors():
    req = _window_request()
    # float sum argument
    cols = {"g": np.zeros(4, np.int64), "o": np.arange(4),
            "v": np.ones(4, np.float64)}
    with pytest.raises(StageCompileError):
        wmod.execute_window(req, cols, 4, use_device=False)
    # int32 overflow guard
    cols["v"] = np.full(4, 2 ** 40, dtype=np.int64)
    with pytest.raises(StageCompileError):
        wmod.execute_window(req, cols, 4, use_device=False)
    # mixed frames
    mixed = compile_pql(
        "SELECT g, ROW_NUMBER() OVER (PARTITION BY g ORDER BY o), "
        "SUM(v) OVER (ORDER BY o) FROM t LIMIT 10")
    with pytest.raises(StageCompileError):
        wmod.execute_window(mixed, {"g": np.zeros(1, np.int64),
                                    "o": np.zeros(1, np.int64),
                                    "v": np.zeros(1, np.int64)}, 1,
                            use_device=False)
    # row cap
    with pytest.raises(StageCompileError):
        wmod.execute_window(req, {}, wmod.WINDOW_CAP + 1,
                            use_device=False)


# ---------------------------------------------------------------------------
# HLL registers: host/device/sharded identity (the sketch contract)
# ---------------------------------------------------------------------------


def test_hll_registers_identical_and_associative():
    from pinot_tpu.common.sketches import HyperLogLog, hll_tables
    rng = np.random.default_rng(8)
    values = np.unique(rng.integers(0, 10_000, 2000))
    # device-kernel emulation: scatter-max of the shared tables over an
    # arbitrary subset == from_values of that subset, registers equal
    idx, rank = hll_tables(values)
    subset = np.zeros(len(values), dtype=bool)
    subset[rng.integers(0, len(values), 700)] = True
    regs = np.zeros(1 << 12, np.int32)
    np.maximum.at(regs, idx[subset], rank[subset])
    direct = HyperLogLog.from_values(values[subset])
    assert np.array_equal(regs.astype(np.uint8), direct.registers)
    # associativity: split-merge == whole
    half = len(values) // 2
    merged = HyperLogLog.from_values(values[:half]).merge(
        HyperLogLog.from_values(values[half:]))
    assert merged == HyperLogLog.from_values(values)


# ---------------------------------------------------------------------------
# End-to-end (embedded cluster): broadcast + co-partitioned joins,
# windows, cache bypass/invalidation, typed errors over the wire
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def join_cluster(tmp_path_factory):
    from pinot_tpu.tools.cluster import EmbeddedCluster
    base = str(tmp_path_factory.mktemp("jcluster"))
    fact_dirs, dim_dirs, dim, fact = build_join_table_dirs(
        os.path.join(base, "segs"), fact_rows=8000, num_fact_segments=2,
        dim_rows=300, seed=2)
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2)
    cluster.add_schema(fact_join_schema())
    cluster.add_schema(part_dim_schema())
    fc, dc = join_table_configs()
    cluster.add_table(fc)
    cluster.add_table(dc)
    for d in fact_dirs:
        cluster.upload_segment("lineorderj_OFFLINE", d)
    for d in dim_dirs:
        cluster.upload_segment("part_OFFLINE", d)
    yield cluster, dim, fact
    cluster.stop()


def test_e2e_broadcast_join_exact(join_cluster):
    cluster, dim, fact = join_cluster
    r = cluster.query(
        "SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM lineorderj "
        "JOIN part ON lineorderj.lo_partkey = part.p_partkey "
        "WHERE part.p_category = 'MFGR#11'")
    assert not r.exceptions
    o = join_oracle(dim, fact,
                    dim_filter=lambda d: d["p_category"] == "MFGR#11")
    assert float(r.aggregation_results[0].value) == float(o["sum_revenue"])
    assert int(float(r.aggregation_results[1].value)) == o["count"]


def test_e2e_window_deterministic(join_cluster):
    cluster, _dim, _fact = join_cluster
    q = ("SELECT d_year, lo_revenue, ROW_NUMBER() OVER (PARTITION BY "
         "d_year ORDER BY lo_revenue DESC), SUM(lo_revenue) OVER "
         "(PARTITION BY d_year ORDER BY lo_revenue DESC) "
         "FROM lineorderj WHERE lo_quantity = 2 LIMIT 50")
    r1 = cluster.query(q)
    r2 = cluster.query(q)
    assert not r1.exceptions
    assert r1.selection_results.results == r2.selection_results.results
    rows = r1.selection_results.results
    assert rows, "window query returned no rows"
    # rank restarts at 1 per partition, revenue descends within it
    seen = {}
    for year, rev, rn, run in rows:
        prev = seen.get(year)
        if prev is None:
            assert rn == 1 and run == rev
        else:
            assert rn == prev[0] + 1 and run == prev[1] + rev
            assert rev <= prev[2]
        seen[year] = (rn, run, rev)


def test_e2e_join_bypasses_result_caches(join_cluster, monkeypatch):
    """Multi-stage queries must never populate broker/server result
    caches (their fingerprints don't cover the dim side)."""
    cluster, _dim, _fact = join_cluster
    broker_cache = cluster.broker.result_cache
    q = ("SELECT COUNT(*) FROM lineorderj JOIN part "
         "ON lineorderj.lo_partkey = part.p_partkey")
    before = len(getattr(broker_cache, "_store", {}))
    r1 = cluster.query(q)
    r2 = cluster.query(q)
    assert r1.aggregation_results[0].value == \
        r2.aggregation_results[0].value
    assert len(getattr(broker_cache, "_store", {})) == before
    for server in cluster.servers.values():
        assert len(server.result_cache) == 0


def test_e2e_join_result_tracks_dim_changes(tmp_path):
    """The invalidation regression: a join answer must change when the
    DIM table changes, even with both result caches enabled."""
    from pinot_tpu.tools.cluster import EmbeddedCluster
    from pinot_tpu.tools.datagen import make_join_rows
    from pinot_tpu.segment.creator import SegmentCreator
    base = str(tmp_path)
    dim, fact = make_join_rows(3000, dim_rows=100, seed=4)
    fc, dc = join_table_configs()
    fdir = os.path.join(base, "f0")
    SegmentCreator(fact_join_schema(), fc,
                   segment_name="factj_0").build(fact, fdir)
    half = {c: v[:50] for c, v in dim.items()}
    ddir = os.path.join(base, "d0")
    SegmentCreator(part_dim_schema(), dc,
                   segment_name="partd_0").build(half, ddir)
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=1,
                              cache_freshness_ms=3600_000.0)
    try:
        cluster.broker.cache_offline = True     # broker cache armed
        cluster.add_schema(fact_join_schema())
        cluster.add_schema(part_dim_schema())
        cluster.add_table(fc)
        cluster.add_table(dc)
        cluster.upload_segment("lineorderj_OFFLINE", fdir)
        cluster.upload_segment("part_OFFLINE", ddir)
        q = ("SELECT COUNT(*) FROM lineorderj JOIN part "
             "ON lineorderj.lo_partkey = part.p_partkey")
        c1 = int(float(cluster.query(q).aggregation_results[0].value))
        o1 = join_oracle(half, fact)["count"]
        assert c1 == o1
        # grow the dim table: the join must see it on the NEXT query
        rest = {c: v[50:] for c, v in dim.items()}
        ddir2 = os.path.join(base, "d1")
        SegmentCreator(part_dim_schema(), dc,
                       segment_name="partd_1").build(rest, ddir2)
        cluster.upload_segment("part_OFFLINE", ddir2)
        c2 = int(float(cluster.query(q).aggregation_results[0].value))
        assert c2 == join_oracle(dim, fact)["count"]
        assert c2 > c1
    finally:
        cluster.stop()


def test_e2e_copartitioned_join_exact_and_filtered(tmp_path):
    """Partition-aligned tables: results stay exact AND the stage-2
    fetch provably skips disjoint-partition sources."""
    from pinot_tpu.tools.cluster import EmbeddedCluster
    base = str(tmp_path)
    fact_dirs, dim_dirs, dim, fact = build_join_table_dirs(
        os.path.join(base, "segs"), fact_rows=6000, num_fact_segments=4,
        dim_rows=200, seed=6, num_partitions=4)
    cluster = EmbeddedCluster(os.path.join(base, "c"), num_servers=2)
    try:
        cluster.add_schema(fact_join_schema())
        cluster.add_schema(part_dim_schema())
        fc, dc = join_table_configs(num_partitions=4)
        cluster.add_table(fc)
        cluster.add_table(dc)
        for d in fact_dirs:
            cluster.upload_segment("lineorderj_OFFLINE", d)
        for d in dim_dirs:
            cluster.upload_segment("part_OFFLINE", d)
        r = cluster.query(
            "SELECT SUM(lineorderj.lo_revenue), COUNT(*) FROM "
            "lineorderj JOIN part ON lineorderj.lo_partkey = "
            "part.p_partkey GROUP BY part.p_mfgr TOP 100")
        assert not r.exceptions
        o = join_oracle(dim, fact, group_cols=["part.p_mfgr"])
        got = {g["group"][0]: float(g["value"])
               for g in r.aggregation_results[0].group_by_result}
        assert got == {k[0]: float(v[0]) for k, v in o["groups"].items()}
        # the per-segment partition metadata is discriminating: a
        # single-partition fact server must skip disjoint dim sources
        segs = _load_segments([fact_dirs[0]])
        fp = jmod.fact_partition_info(segs, "lo_partkey")
        assert fp is not None and fp[0] == "Modulo" and fp[1] == 4
        sources = [{"server": "s", "id": f"x{p}", "partitions": [p],
                    "partitionFunction": "Modulo", "numPartitions": 4}
                   for p in range(4)]
        kept, skipped = jmod.filter_sources(sources, fp)
        assert skipped == 4 - len(fp[2])
        assert {s["partitions"][0] for s in kept} == fp[2]
    finally:
        cluster.stop()


@pytest.mark.parametrize("bad,code", [
    ("SELECT COUNT(*) FROM lineorderj JOIN ghost "
     "ON lineorderj.lo_partkey = ghost.k", 190),
    ("SELECT COUNT(*) FROM lineorderj JOIN part "
     "ON lineorderj.lo_partkey = part.p_brand1", 422),     # type mismatch
    ("SELECT COUNT(*) FROM lineorderj JOIN part "
     "ON lineorderj.p_partkey = part.lo_partkey", 422),    # swapped cols
])
def test_e2e_typed_stage_errors(join_cluster, bad, code):
    cluster, _dim, _fact = join_cluster
    r = cluster.query(bad)
    assert r.exceptions, "expected a typed error"
    assert r.exceptions[0]["errorCode"] == code
    assert r.aggregation_results in (None, [])


def test_e2e_unknown_dim_column_is_empty_not_crash(join_cluster):
    """An unknown dim column follows the engine's unknown-column
    semantics (schema pruner → empty scan → empty join) — never a
    broker crash."""
    cluster, _dim, _fact = join_cluster
    r = cluster.query(
        "SELECT COUNT(*) FROM lineorderj JOIN part "
        "ON lineorderj.lo_partkey = part.p_partkey "
        "GROUP BY part.nosuch TOP 10")
    assert r.aggregation_results[0].group_by_result in (None, [])


def test_e2e_dim_capacity_typed_error(join_cluster, monkeypatch):
    cluster, _dim, _fact = join_cluster
    from pinot_tpu.query.stages import broker as stages_broker
    monkeypatch.setattr(stages_broker, "DIM_CAP", 10)
    r = cluster.query(
        "SELECT COUNT(*) FROM lineorderj JOIN part "
        "ON lineorderj.lo_partkey = part.p_partkey")
    assert r.exceptions
    assert r.exceptions[0]["errorCode"] == 422


def test_raw_key_join_with_unrepresentable_dim_keys_is_empty(tmp_path):
    """Review regression: dim keys outside the raw fact dtype's range
    drop to an EMPTY join (the raw twin of the all-False member
    vector), never a TypeError on padded_keys() returning None."""
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.tools.datagen import make_join_rows
    _dim, fact = make_join_rows(500, dim_rows=50, seed=14)
    cfg = TableConfig("lineorderj", indexing_config=IndexingConfig(
        no_dictionary_columns=["lo_partkey"]))
    d = str(tmp_path / "seg0")
    SegmentCreator(fact_join_schema(), cfg,
                   segment_name="rawk2_0").build(fact, d)
    segments = _load_segments([d])
    request = compile_pql(
        "SELECT COUNT(*) FROM lineorderj JOIN part "
        "ON lineorderj.lo_partkey = part.p_partkey")
    huge = np.array([2 ** 40, 2 ** 41], dtype=np.int64)  # > int32 range
    ctx = jmod.JoinContext(request.join, huge, {})
    req = _attach(request, ctx)
    from pinot_tpu.query.executor import ServerQueryExecutor
    for dev in (False, True):
        out = _reduce(request, ServerQueryExecutor(use_device=dev)
                      .execute(req, segments))
        assert float(out["aggregationResults"][0]["value"]) == 0


def test_window_per_partition_overflow_bound():
    """Review regression: the int32 guard is PER PARTITION — a query
    whose global abs-sum exceeds 2^31 but whose partitions each fit
    must run (and stay host/device bit-identical)."""
    n = 2000
    rng = np.random.default_rng(5)
    cols = {"g": np.arange(n) % 100,          # 100 partitions
            "o": rng.integers(0, 9, n).astype(np.int64),
            "v": np.full(n, 2_000_000, dtype=np.int64)}
    assert int(np.abs(cols["v"]).sum()) >= 2 ** 31        # global over
    req = _window_request()
    dev = wmod.execute_window(req, dict(cols), n, use_device=True)
    host = wmod.execute_window(req, dict(cols), n, use_device=False)
    for a, b in zip(dev.selection_cols, host.selection_cols):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # but one partition over the bound still rejects
    cols["g"] = np.zeros(n, dtype=np.int64)
    with pytest.raises(StageCompileError):
        wmod.execute_window(req, dict(cols), n, use_device=False)


def test_exchange_put_ttl_tracks_query_deadline():
    clock = [0.0]
    m = xmod.ExchangeManager(ttl_s=120.0, clock=lambda: clock[0])
    try:
        m.put("short", b"x", ttl_s=5.0)
        m.put("default", b"y")
        clock[0] = 6.0
        assert m.get("short") is None          # expired with its query
        assert m.get("default") == b"y"        # manager default TTL
    finally:
        m.close()


def test_stage_busy_reply_keeps_503_classification():
    from pinot_tpu.query.stages.broker import _busy_error
    from pinot_tpu.server.admission import busy_datatable
    dt = busy_datatable(1, "overload", 250.0)
    err = _busy_error("srv", dt, "stage-1 scan")
    assert err is not None
    assert err["busyCause"] == "overload"
    assert err["retryAfterMs"] == 250.0
    assert "errorCode" not in err       # _finish derives 503 from cause
    from pinot_tpu.common.datatable import DataTable
    assert _busy_error("srv", DataTable(), "x") is None


def test_wire_schema_pins_exchange_frame():
    from pinot_tpu.analysis.contracts import wire_schema
    schema = wire_schema()
    assert schema["exchangeFrame"]["magic"] == "XCHG"
    assert schema["exchangeFrame"]["fetchKeys"] == ["id", "op"]
    assert "exchangePartitions" in \
        schema["exchangeFrame"]["ackMetadataKeys"]
    opt = schema["instanceRequest"]["optional"]
    assert "publishExchange" in opt and "exchangeSources" in opt


def test_fingerprint_covers_join_and_windows():
    from pinot_tpu.query.fingerprint import query_fingerprint
    a = compile_pql("SELECT COUNT(*) FROM f JOIN d ON f.k = d.j")
    b = compile_pql("SELECT COUNT(*) FROM f JOIN d ON f.k = d.j2")
    c = compile_pql("SELECT COUNT(*) FROM f")
    assert len({query_fingerprint(x) for x in (a, b, c)}) == 3
    w1 = compile_pql("SELECT a, ROW_NUMBER() OVER (ORDER BY b) FROM f "
                     "LIMIT 5")
    w2 = compile_pql("SELECT a, ROW_NUMBER() OVER (ORDER BY c) FROM f "
                     "LIMIT 5")
    assert query_fingerprint(w1) != query_fingerprint(w2)
