"""tpulint core: findings, rule registry, suppressions, baseline file.

Design notes
------------
- A Finding's baseline identity (`key()`) deliberately excludes the line
  number so unrelated edits above a grandfathered finding don't churn
  the baseline; identity is (path, rule, message) with multiplicity.
- Suppressions are trailing comments on the flagged line
  (``# tpulint: disable=RULE[,RULE...][ -- reason]``) or file-level
  (``# tpulint: disable-file=RULE``); ``all`` matches every rule.
  The ``-- reason`` tail is required style for hand-written
  suppressions (enforced by review, not by the tool).
"""
from __future__ import annotations

import dataclasses
import json
import re
from typing import Dict, Iterable, Iterator, List, Set, Tuple

# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str          # repo-relative, posix separators
    line: int          # 1-based
    rule: str
    message: str

    def key(self) -> str:
        """Baseline identity — line-number free (see module docstring)."""
        return f"{self.path}::{self.rule}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AnalysisConfig:
    """Scoping knobs: which parts of the tree each rule family watches."""

    # modules whose functions feed (or are) jitted kernels: a silent
    # device→host pull here stalls the pipeline per dispatch
    kernel_path_prefixes: Tuple[str, ...] = (
        "pinot_tpu/query/", "pinot_tpu/parallel/", "pinot_tpu/startree/",
        "pinot_tpu/ops/")
    # modules whose classes are touched by scheduler workers, consumer
    # threads and state-transition threads concurrently
    concurrency_prefixes: Tuple[str, ...] = (
        "pinot_tpu/server/", "pinot_tpu/realtime/", "pinot_tpu/segment/",
        "pinot_tpu/parallel/")


#: run-scoped knobs the CLI sets and global-tier rules read (the rule
#: registry holds singletons, so per-run configuration travels here)
OPTIONS: Dict[str, object] = {"max_states": 200_000}


class Rule:
    """One rule family. Subclasses set `id`/`description`, yield Findings.

    `tier` is "ast" (per-file, runs always), "lifecycle" (per-file,
    runs only under `--lifecycle`: device-upload ledger routing,
    query-path cache bounds — still `check(ctx)` rules, so suppressions
    and fixtures work exactly like the fast tier), "deep" (global, runs
    only under `--deep`: kernel tracing, wire schema), or "protocol"
    (global, runs only under `--protocol`: durability ordering, crash
    coverage, metrics contract, the crash-interleaving model checker).
    Global tiers implement `check_global()` instead of `check()`.
    """

    id: str = ""
    description: str = ""
    tier: str = "ast"

    def check(self, ctx) -> Iterator[Finding]:  # ctx: runner.FileContext
        raise NotImplementedError

    def check_global(self) -> List[Finding]:    # deep tier only
        return []


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and add to the global registry."""
    inst = cls()
    assert inst.id and inst.id not in _REGISTRY, inst.id
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    # importing the package registers every rule module
    from pinot_tpu.analysis import rules as _rules  # noqa: F401
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?P<scope>-file)?="
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """(line → suppressed rule ids, file-level rule ids). Lines 1-based."""
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",")}
        if m.group("scope"):
            per_file |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, per_file


def is_suppressed(finding: Finding, per_line: Dict[int, Set[str]],
                  per_file: Set[str]) -> bool:
    line_rules = per_line.get(finding.line, set())
    return ("all" in per_file or finding.rule in per_file or
            "all" in line_rules or finding.rule in line_rules)


# ---------------------------------------------------------------------------
# Baseline file
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def count_keys(findings: Iterable[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return {str(k): int(v) for k, v in data["findings"].items()}


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    data = {
        "version": BASELINE_VERSION,
        "comment": ("grandfathered tpulint findings; regenerate with "
                    "`python -m pinot_tpu.analysis pinot_tpu/ "
                    "--write-baseline` from the repo root"),
        "findings": dict(sorted(count_keys(findings).items())),
    }
    with open(path, "w") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")


def split_by_baseline(findings: List[Finding], baseline: Dict[str, int]
                      ) -> Tuple[List[Finding], List[str]]:
    """(new findings, stale baseline keys).

    Per key the first `baseline[key]` occurrences are grandfathered;
    occurrences beyond that are NEW. Baseline keys with fewer fresh
    occurrences than recorded are STALE (fixed code — prune them).
    """
    fresh = count_keys(findings)
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    for f in sorted(findings):
        n = seen.get(f.key(), 0)
        seen[f.key()] = n + 1
        if n >= baseline.get(f.key(), 0):
            new.append(f)
    stale = [k for k, v in sorted(baseline.items())
             if fresh.get(k, 0) < v]
    return new, stale
