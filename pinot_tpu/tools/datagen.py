"""Synthetic data generators: in-memory segments for benchmarks & dryruns.

Parity: the reference's data-generation tooling
(pinot-tools/.../tools/data/DataGenerator.java and the SSB/TPC-H style
pinot-druid-benchmark harness, SURVEY.md §6). Builds ImmutableSegment objects
directly from arrays — no file round-trip — so 100M-row benchmark tables
materialize in seconds. All segments of a table share one global dictionary
per column (the layout the mesh-sharded executor combines in the dictId
domain).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.loader import DataSource, ImmutableSegment
from pinot_tpu.segment.metadata import ColumnMetadata, SegmentMetadata


def _bits_for(card: int) -> int:
    return max(1, int(np.ceil(np.log2(max(card, 2)))))


def make_segment_from_arrays(
        name: str, table: str,
        dict_cols: Dict[str, Tuple[DataType, np.ndarray, np.ndarray]],
        raw_cols: Optional[Dict[str, Tuple[DataType, np.ndarray]]] = None,
        ) -> ImmutableSegment:
    """Build a queryable in-memory segment.

    dict_cols: col → (data_type, sorted_unique_values, dict_ids[int32])
    raw_cols:  col → (data_type, values)  (no-dictionary columns)
    """
    raw_cols = raw_cols or {}
    num_docs = None
    columns: Dict[str, ColumnMetadata] = {}
    sources: Dict[str, DataSource] = {}

    for col, (dt, values, ids) in dict_cols.items():
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        if num_docs is None:
            num_docs = len(ids)
        assert len(ids) == num_docs, f"column {col} length mismatch"
        card = len(values)
        cm = ColumnMetadata(
            name=col, data_type=dt, cardinality=card,
            bits_per_element=_bits_for(card), single_value=True,
            sorted=bool(np.all(ids[1:] >= ids[:-1])) if len(ids) else True,
            has_dictionary=True,
            min_value=values[0] if card else None,
            max_value=values[-1] if card else None,
            total_number_of_entries=num_docs)
        ds = DataSource(cm, None)
        ds.dictionary = Dictionary(dt, values)
        ds.dict_ids = ids
        columns[col] = cm
        sources[col] = ds

    for col, (dt, vals) in raw_cols.items():
        vals = np.ascontiguousarray(vals)
        if num_docs is None:
            num_docs = len(vals)
        assert len(vals) == num_docs, f"column {col} length mismatch"
        cm = ColumnMetadata(
            name=col, data_type=dt, cardinality=num_docs,
            bits_per_element=vals.dtype.itemsize * 8, single_value=True,
            sorted=False, has_dictionary=False,
            min_value=vals.min() if num_docs else None,
            max_value=vals.max() if num_docs else None,
            total_number_of_entries=num_docs)
        ds = DataSource(cm, None)
        ds.raw_values = vals
        columns[col] = cm
        sources[col] = ds

    meta = SegmentMetadata(segment_name=name, table_name=table,
                           total_docs=int(num_docs), columns=columns)
    seg = ImmutableSegment(meta, sources)
    for ds in sources.values():
        ds._segment = seg
    return seg


# ---------------------------------------------------------------------------
# SSB-style star-schema table (denormalized lineorder, the shape the
# pinot-druid benchmark queries — contrib/pinot-druid-benchmark)
# ---------------------------------------------------------------------------

SSB_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SSB_NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "CHINA", "EGYPT",
               "ETHIOPIA", "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN",
               "IRAQ", "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE",
               "PERU", "ROMANIA", "RUSSIA", "SAUDI ARABIA", "UNITED KINGDOM",
               "UNITED STATES", "VIETNAM"]


SSB_TYPES = {
    "lo_quantity": DataType.INT, "lo_discount": DataType.INT,
    "lo_revenue": DataType.LONG, "lo_supplycost": DataType.DOUBLE,
    "d_year": DataType.INT, "d_yearmonthnum": DataType.INT,
    "c_region": DataType.STRING, "s_nation": DataType.STRING,
    "p_brand": DataType.STRING,
}
SSB_RAW_COLS = {"lo_supplycost"}


def ssb_pools(seed: int = 0) -> Dict[str, np.ndarray]:
    """Sorted global value pools (== the shared dictionaries)."""
    rng = np.random.default_rng(seed + 10_007)
    revenue = np.unique((rng.integers(100, 10_000, 8192) * 100)
                        .astype(np.int64))
    ymn = np.array(sorted(y * 100 + m for y in range(1992, 1999)
                          for m in range(1, 13)), dtype=np.int64)
    return {
        "lo_quantity": np.arange(1, 51, dtype=np.int64),
        "lo_discount": np.arange(0, 11, dtype=np.int64),
        "lo_revenue": revenue,
        "d_year": np.arange(1992, 1999, dtype=np.int64),
        "d_yearmonthnum": ymn,
        "c_region": np.array(sorted(SSB_REGIONS), dtype=object),
        "s_nation": np.array(sorted(SSB_NATIONS), dtype=object),
        "p_brand": np.array([f"MFGR#{i:04d}" for i in range(1000)],
                            dtype=object),
    }


class SsbTable:
    """Generated table: segments + id-level host arrays for oracle math.

    Oracle checks run on the int32 id arrays (decode via `pools`) so 100M-row
    tables never materialize 100M python-object string columns host-side.
    """

    def __init__(self, segments, pools, ids, supplycost):
        self.segments = segments
        self.pools = pools            # col → sorted values (the dictionary)
        self.ids = ids                # col → int32 [total_rows]
        self.supplycost = supplycost  # raw float64 [total_rows]

    def id_of(self, col: str, value) -> int:
        i = int(np.searchsorted(self.pools[col], value))
        assert self.pools[col][i] == value
        return i

    def decoded(self, col: str) -> np.ndarray:
        if col == "lo_supplycost":
            return self.supplycost
        return self.pools[col][self.ids[col]]


def make_ssb_device_stack(total_rows: int, num_segments: int, mesh,
                          seed: int = 0):
    """Device-generated stacked SSB lanes for large-scale benchmarking.

    Host->device bandwidth can be the bottleneck for huge synthetic tables
    (notably through the test harness's TPU relay), so the column lanes are
    synthesized directly in HBM with jax PRNG — same pools/cardinalities/
    distributions as make_ssb_segments, different values. Returns
    (lanes, num_docs_sharded, plan_table) where `lanes` maps
    "col.ids"/"col.parts"/"col.raw" to [S, P] device arrays sharded over the
    mesh's `seg` axis, and `plan_table` is a tiny host SsbTable with the
    same dictionaries for building plans/params.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pinot_tpu.parallel.sharded import SEG_AXIS
    from pinot_tpu.segment.loader import padded_size

    pools = ssb_pools(seed)
    per = total_rows // num_segments
    padded = padded_size(per)
    shard = NamedSharding(mesh, P(SEG_AXIS))
    n_dev = mesh.devices.size
    s_total = -(-num_segments // n_dev) * n_dev

    key = jax.random.PRNGKey(seed)
    lanes = {}
    for c, pool in pools.items():
        key, sub = jax.random.split(key)
        arr = jax.random.randint(sub, (s_total, padded), 0, len(pool),
                                 dtype=jnp.int32)
        lanes[f"{c}.ids"] = jax.device_put(arr, shard)

    # bit-sliced part lanes for the integer SUM metric (lo_revenue)
    plan_table = make_ssb_segments(max(BLOCK_ROWS, 2 * padded_size(1)),
                                   1, seed=seed)
    ds = plan_table.segments[0].data_source("lo_revenue")
    n_parts, _ = ds.int_part_info()
    vals = np.asarray(ds.dictionary.values, dtype=np.int64)
    off = vals - int(vals[0])
    table = np.stack([(off >> (7 * k)) & 0x7F
                      for k in range(n_parts)]).astype(np.int8)
    table_dev = jnp.asarray(table)
    rev_ids = lanes["lo_revenue.ids"]
    parts = jax.jit(
        lambda ids: jnp.moveaxis(table_dev[:, ids], 1, 0),
        out_shardings=shard)(rev_ids)
    lanes["lo_revenue.parts"] = parts

    key, sub = jax.random.split(key)
    raw = jax.random.uniform(sub, (s_total, padded), jnp.float32) * 1e5
    lanes["lo_supplycost.raw"] = jax.device_put(raw, shard)

    num_docs = np.zeros(s_total, np.int32)
    num_docs[:num_segments] = per
    num_docs_dev = jax.device_put(num_docs, shard)
    return lanes, num_docs_dev, plan_table, padded


BLOCK_ROWS = 16384


def make_ssb_segments(total_rows: int, num_segments: int, seed: int = 0
                      ) -> SsbTable:
    """num_segments equal slices of an SSB table with GLOBAL dictionaries.

    DictIds are generated directly against pre-sorted pools (no
    unique/searchsorted pass over the full table — 100M rows materialize in
    seconds).
    """
    rng = np.random.default_rng(seed)
    pools = ssb_pools(seed)
    ids = {c: rng.integers(0, len(p), total_rows).astype(np.int32)
           for c, p in pools.items()}
    supplycost = (rng.random(total_rows) * 1e5).round(2)

    per = total_rows // num_segments
    segments = []
    for i in range(num_segments):
        lo, hi = i * per, (i + 1) * per if i < num_segments - 1 else total_rows
        dict_part = {c: (SSB_TYPES[c], pools[c], ids[c][lo:hi])
                     for c in pools}
        raw_part = {"lo_supplycost": (DataType.DOUBLE, supplycost[lo:hi])}
        segments.append(make_segment_from_arrays(
            f"ssb_{i}", "lineorder", dict_part, raw_part))
    return SsbTable(segments, pools, ids, supplycost)
