"""Controller periodic tasks: retention, validation, status checking.

Parity: pinot-controller/.../helix/core/periodictask/ControllerPeriodicTask
+ core/periodictask/PeriodicTaskScheduler — tables loop on an interval;
RetentionManager.java:50-81 (delete segments past time retention);
OfflineSegmentIntervalChecker / BrokerResourceValidationManager (replica
health). run_once() executes synchronously for tests; start() runs on a
daemon thread.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from pinot_tpu.common.timeutils import unit_ms
from pinot_tpu.controller.manager import ResourceManager

log = logging.getLogger(__name__)


class PeriodicTask:
    name = "task"
    interval_s = 3600.0

    def run(self, manager: ResourceManager) -> None:
        raise NotImplementedError


class RetentionManager(PeriodicTask):
    """Deletes segments whose time range is past the table's retention.

    Deletions are DELAYED: the artifact becomes a ``.trash`` tombstone
    the integrity scrubber reclaims after its grace window, so a
    fat-fingered retention config stays recoverable for the grace
    period. Consuming (not-yet-committed) segments are never touched —
    the realtime successor chain owns them. On upsert tables the
    record removal also triggers server-side key-map GC (the
    `upsertKeyMapSize` flatness story: an expired segment's keys leave
    the map with it)."""

    name = "RetentionManager"
    interval_s = 6 * 3600.0

    def __init__(self, now_ms_fn=None, metrics=None):
        self._now_ms = now_ms_fn or (lambda: int(time.time() * 1e3))
        self.metrics = metrics

    def run(self, manager: ResourceManager) -> None:
        from pinot_tpu.common.metrics import ControllerMeter
        for table in manager.table_names():
            config = manager.get_table_config(table)
            sc = config.segments_config if config else None
            if sc is None or not sc.retention_time_unit or \
                    not sc.retention_time_value:
                continue
            retention_ms = sc.retention_time_value * unit_ms(
                sc.retention_time_unit)
            cutoff_ms = self._now_ms() - retention_ms
            latest = self._latest_llc_sequences(manager, table)
            for seg in manager.segment_names(table):
                meta = manager.segment_metadata(table, seg) or {}
                if meta.get("status") == "IN_PROGRESS":
                    continue        # consuming: no artifact to expire
                if self._is_latest_llc(seg, latest):
                    # the newest committed sequence anchors the
                    # partition's restart offset (successor repair
                    # reads its endOffset) — never expire it
                    continue
                end, unit = meta.get("endTime"), meta.get("timeUnit")
                if end is None:
                    continue
                end_ms = int(end) * unit_ms(unit)
                if end_ms < cutoff_ms:
                    log.info("retention: deleting %s/%s (end %s < cutoff)",
                             table, seg, end_ms)
                    manager.delete_segment(table, seg,
                                           tombstone_artifact=True)
                    if self.metrics is not None:
                        self.metrics.meter(
                            ControllerMeter.RETENTION_SEGMENTS_DELETED
                        ).mark()

    @staticmethod
    def _latest_llc_sequences(manager: ResourceManager,
                              table: str) -> Dict[int, int]:
        from pinot_tpu.realtime.segment_name import latest_llc_sequences
        return latest_llc_sequences(manager.segment_names(table))

    @staticmethod
    def _is_latest_llc(seg: str, latest: Dict[int, int]) -> bool:
        from pinot_tpu.realtime.segment_name import LLCSegmentName
        if not LLCSegmentName.is_llc(seg):
            return False
        llc = LLCSegmentName.parse(seg)
        return latest.get(llc.partition) == llc.sequence


class SegmentIntegrityChecker(PeriodicTask):
    """Deep-store scrubber + replica repair.

    Three sweeps per run (parity: the reference's periodic controller
    validation tasks, extended with the CRC story of SURVEY §5.4 —
    "segments themselves are the durable artifacts in deep store"):

    1. **Artifact integrity**: every committed segment's deep-store
       artifact is CRC-verified against the durable record; a corrupt
       artifact is moved to ``<deep_store>/quarantine/`` (never served,
       kept for forensics) and surfaced via metrics/report. Serving
       replicas hold verified copies and keep serving; the quarantined
       record is reported for operator re-upload.
    2. **ERROR-replica repair**: replicas the external view shows in
       ERROR while the ideal state wants them serving are bounced
       through OFFLINE (→ re-download from the deep store); a replica
       that keeps failing is re-assigned to a healthy live instance.
    3. **Orphan sweep**: deep-store entries with no property-store
       record (upload/commit crash leftovers, leaked retention deletes)
       are removed — completing RetentionManager's storage story.
    """

    name = "SegmentIntegrityChecker"
    interval_s = 1800.0
    QUARANTINE_DIR = "quarantine"
    #: OFFLINE→ONLINE bounces per replica before giving up and moving
    #: the replica to a different healthy instance
    MAX_BOUNCES = 2
    #: an unrecorded deep-store entry younger than this is an in-flight
    #: upload (copy lands before the record is written), not an orphan
    ORPHAN_GRACE_S = 300.0
    #: ``.trash.<ms>`` delayed-delete tombstones (compaction swaps,
    #: retention) are reclaimed only after this grace — until then an
    #: interrupted swap's recovery (or an operator) can roll back
    DELAYED_DELETE_GRACE_S = 300.0

    def __init__(self, metrics=None, now_fn=None, rebalancer=None):
        """`rebalancer`: the controller's SegmentRebalancer — replicas
        whose host is no longer live are handed to it instead of being
        bounced (a bounce against a dead host heals nothing); built
        lazily from the manager when not wired."""
        self.metrics = metrics
        self._now = now_fn or time.time
        self.rebalancer = rebalancer
        self.last_report: Dict[str, Dict] = {}
        self._bounce_counts: Dict[tuple, int] = {}

    def _mark(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.meter(name).mark(n)

    def run(self, manager: ResourceManager) -> None:
        import os

        from pinot_tpu.common.metrics import ControllerMeter
        from pinot_tpu.segment.integrity import (SegmentIntegrityError,
                                                 quarantine_segment,
                                                 verify_segment)
        report: Dict[str, Dict] = {}
        quarantine_root = os.path.join(manager.deep_store_dir,
                                       self.QUARANTINE_DIR)
        for table in manager.table_names():
            entry = {"corrupt": [], "missingArtifact": [], "repaired": [],
                     "reassigned": [], "orphansDeleted": [],
                     "tombstonesDeleted": []}
            # segments mid compaction/merge swap (open /SWAPS intent):
            # artifact and record are updated in separate durable steps,
            # so a CRC sweep racing the protocol would quarantine a
            # HEALTHY artifact against the not-yet-updated record — the
            # swap's own recovery (SwapJanitor) owns these until the
            # intent clears. The protection covers the intent's OLD
            # segments too: a merge swap prunes the olds' records
            # mid-protocol, and their artifacts/tombstones must stay
            # recoverable until the intent resolves
            from pinot_tpu.controller.compaction import SWAPS_ROOT
            in_swap = set()
            for name in manager.store.children(f"{SWAPS_ROOT}/{table}"):
                in_swap.add(name)
                rec = manager.store.get(
                    f"{SWAPS_ROOT}/{table}/{name}") or {}
                in_swap.update(rec.get("olds") or ())
            # segments no replica bounce can heal (artifact quarantined
            # this run, or already gone from an earlier one): repair
            # would churn the ideal state forever against nothing
            unrepairable = set()
            known = set()
            for seg in manager.segment_names(table):
                known.add(seg)
                if seg in in_swap:
                    continue
                meta = manager.segment_metadata(table, seg) or {}
                path, crc = meta.get("downloadPath"), meta.get("crc")
                if path and "://" in path:
                    # HTTP-advertised paths resolve inside OUR deep store
                    path = manager.canonical_artifact_path(table, seg)
                if not path:
                    continue        # consuming: no artifact yet
                if not os.path.isdir(path):
                    unrepairable.add(seg)
                    entry["missingArtifact"].append(seg)
                    continue
                try:
                    verify_segment(path, crc)
                except SegmentIntegrityError:
                    quarantine_segment(path, quarantine_root)
                    entry["corrupt"].append(seg)
                    unrepairable.add(seg)
                    self._mark(ControllerMeter.CORRUPT_SEGMENTS)
                    log.error("integrity: quarantined corrupt deep-store "
                              "artifact %s/%s", table, seg)
            self._repair_error_replicas(manager, table, entry,
                                        skip=unrepairable | in_swap)
            self._sweep_orphans(manager, table, known, entry, in_swap)
            if any(entry.values()):
                report[table] = entry
        self.last_report = report

    # -- repair -------------------------------------------------------------
    def _repair_error_replicas(self, manager: ResourceManager, table: str,
                               entry: Dict, skip=()) -> None:
        """`skip`: segments whose deep-store artifact was just
        quarantined — bouncing/reassigning their replicas cannot heal
        anything (every load would fail against the missing artifact)
        and would only churn the ideal state."""
        from pinot_tpu.common.cluster_state import ERROR, OFFLINE, ONLINE
        from pinot_tpu.common.metrics import ControllerMeter
        ideal = manager.coordinator.ideal_state(table)
        view = manager.coordinator.external_view(table).segment_states
        live = set(manager.coordinator.live_instances())
        dead_holders = False
        for seg, wanted in ideal.items():
            if seg in skip:
                continue
            for inst, target in sorted(wanted.items()):
                if target != ONLINE:
                    continue
                if inst not in live:
                    # the replica's HOST is gone: bouncing a corpse
                    # through OFFLINE can never heal it — defer to the
                    # rebalancer's replica-count repair (one pass below,
                    # no bounce budget burned against a dead instance)
                    self._bounce_counts.pop((table, seg, inst), None)
                    dead_holders = True
                    continue
                if view.get(seg, {}).get(inst) != ERROR:
                    continue
                key = (table, seg, inst)
                bounces = self._bounce_counts.get(key, 0)
                healthy = sorted(live - set(wanted))
                if bounces >= self.MAX_BOUNCES and healthy:
                    # persistent failure on this instance: move the
                    # replica to a healthy live server
                    new_inst = healthy[0]

                    def reassign(segments, seg=seg, inst=inst,
                                 new_inst=new_inst):
                        states = dict(segments.get(seg, {}))
                        states.pop(inst, None)
                        states[new_inst] = ONLINE
                        segments[seg] = states
                        return segments

                    manager.coordinator.update_ideal_state(table, reassign)
                    self._bounce_counts.pop(key, None)
                    entry["reassigned"].append(f"{seg}:{inst}->{new_inst}")
                    self._mark(ControllerMeter.ERROR_REPLICAS_REPAIRED)
                    log.warning("integrity: reassigned %s/%s %s -> %s",
                                table, seg, inst, new_inst)
                    continue

                # bounce through OFFLINE so the load path re-runs (a
                # re-download repairs a quarantined/corrupt local copy)
                def offline(segments, seg=seg, inst=inst):
                    states = dict(segments.get(seg, {}))
                    if states.get(inst) == ONLINE:
                        states[inst] = OFFLINE
                        segments[seg] = states
                    return segments

                def online(segments, seg=seg, inst=inst):
                    states = dict(segments.get(seg, {}))
                    if states.get(inst) == OFFLINE:
                        states[inst] = ONLINE
                        segments[seg] = states
                    return segments

                manager.coordinator.update_ideal_state(table, offline)
                manager.coordinator.update_ideal_state(table, online)
                self._bounce_counts[key] = bounces + 1
                entry["repaired"].append(f"{seg}:{inst}")
                self._mark(ControllerMeter.ERROR_REPLICAS_REPAIRED)
        if dead_holders:
            from pinot_tpu.controller.rebalance import SegmentRebalancer
            if self.rebalancer is None:
                self.rebalancer = SegmentRebalancer(manager,
                                                    metrics=self.metrics)
            report = self.rebalancer.repair_table(table)
            for seg, insts in report["pruned"].items():
                adds = report["added"].get(seg, [])
                entry["reassigned"].extend(
                    f"{seg}:{inst}->{','.join(adds) or '(pruned)'}"
                    for inst in insts)
                self._mark(ControllerMeter.ERROR_REPLICAS_REPAIRED,
                           len(insts))

    # -- orphan sweep -------------------------------------------------------
    def _sweep_orphans(self, manager: ResourceManager, table: str,
                       known: set, entry: Dict,
                       in_swap: Optional[set] = None) -> None:
        import os

        from pinot_tpu.common.metrics import ControllerMeter
        from pinot_tpu.controller.compaction import (STAGING_SUFFIX,
                                                     TRASH_MARKER)
        in_swap = in_swap or set()
        tdir = os.path.join(manager.deep_store_dir, table)
        if not os.path.isdir(tdir):
            return
        for name in sorted(os.listdir(tdir)):
            if name in known or name in in_swap:
                continue
            path = os.path.join(tdir, name)
            try:
                age = self._now() - os.path.getmtime(path)
            except OSError:
                continue        # vanished under us
            if TRASH_MARKER in name:
                # delayed-delete tombstone (compaction swap, retention):
                # reclaim only past the grace window, and never while
                # the swap that wrote it is still in flight (its
                # recovery may roll back to this copy)
                base = name.split(TRASH_MARKER, 1)[0]
                if base in in_swap or age < self.DELAYED_DELETE_GRACE_S:
                    continue
                manager.fs.delete(path)
                entry["tombstonesDeleted"].append(name)
                self._mark(ControllerMeter.TOMBSTONES_DELETED)
                log.info("integrity: reclaimed delayed-delete tombstone "
                         "%s/%s", table, name)
                continue
            if ".staging." in name:
                # split-commit / swap staging copy: an OPEN swap intent
                # still needs its staging (recovery publishes from it);
                # a young one may be an in-flight commit; anything else
                # is a crash leftover whose intent was resolved — sweep
                base = name.split(".staging.", 1)[0]
                if name.endswith(STAGING_SUFFIX) and base in in_swap:
                    continue
                if age < self.ORPHAN_GRACE_S:
                    continue
                manager.fs.delete(path)
                entry["orphansDeleted"].append(name)
                self._mark(ControllerMeter.ORPHAN_ARTIFACTS_DELETED)
                log.info("integrity: deleted stale staging leftover "
                         "%s/%s", table, name)
                continue
            if age < self.ORPHAN_GRACE_S:
                continue        # in-flight upload: copy precedes record
            manager.fs.delete(path)
            entry["orphansDeleted"].append(name)
            self._mark(ControllerMeter.ORPHAN_ARTIFACTS_DELETED)
            log.info("integrity: deleted orphan deep-store artifact "
                     "%s/%s", table, name)


class SegmentStatusChecker(PeriodicTask):
    """Reports replica health per table (parity: SegmentStatusChecker /
    OfflineSegmentIntervalChecker metrics). Returns its findings so
    callers/tests can assert on them."""

    name = "SegmentStatusChecker"
    interval_s = 300.0

    def __init__(self):
        self.last_report: Dict[str, Dict] = {}

    def run(self, manager: ResourceManager) -> None:
        report: Dict[str, Dict] = {}
        for table in manager.coordinator.tables():
            ideal = manager.coordinator.ideal_state(table)
            view = manager.coordinator.external_view(table)
            missing, under = [], []
            for seg, wanted in ideal.items():
                live = view.servers_for(seg)
                if not live:
                    missing.append(seg)
                elif len(live) < len(wanted):
                    under.append(seg)
            report[table] = {"segments": len(ideal),
                             "missing": sorted(missing),
                             "underReplicated": sorted(under)}
        self.last_report = report


class RealtimeSegmentValidationManager(PeriodicTask):
    """Repairs realtime consumption: every stream partition must have a
    live consuming segment (parity: RealtimeSegmentValidationManager →
    PinotLLCRealtimeSegmentManager.ensureAllPartitionsConsuming:891)."""

    name = "RealtimeSegmentValidationManager"
    interval_s = 60.0

    def __init__(self, realtime_manager):
        self.realtime_manager = realtime_manager

    def run(self, manager: ResourceManager) -> None:
        self.realtime_manager.ensure_all_partitions_consuming()


class MinionTaskScheduler(PeriodicTask):
    """Lead-gated minion-plane heartbeat: requeue expired task claims
    (a kill -9'd minion's lease running out) and run the registered
    task generators over every table's taskConfig (parity:
    PinotTaskManager riding the ControllerPeriodicTask cadence)."""

    name = "MinionTaskScheduler"
    interval_s = 30.0

    def __init__(self, task_manager):
        self.task_manager = task_manager
        self.last_requeued: List[str] = []
        self.last_scheduled: List[str] = []

    def run(self, manager: ResourceManager) -> None:
        queue = self.task_manager.queue
        queue.prune_terminal()
        self.last_requeued = queue.requeue_expired()
        self.last_scheduled = self.task_manager.schedule_tasks()


class PeriodicTaskScheduler:
    def __init__(self, manager: ResourceManager,
                 tasks: Optional[List[PeriodicTask]] = None,
                 leadership=None, metrics=None):
        self.manager = manager
        self.tasks = tasks if tasks is not None else [
            RetentionManager(metrics=metrics), SegmentStatusChecker(),
            SegmentIntegrityChecker(metrics=metrics)]
        # parity: ControllerPeriodicTask lead-controller gating — with
        # multiple controllers, only the lease holder runs the tasks
        self.leadership = leadership
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def run_once(self) -> None:
        if self.leadership is not None and \
                not self.leadership.try_acquire():
            return
        for task in self.tasks:
            try:
                task.run(self.manager)
            except Exception:  # noqa: BLE001 — one task must not kill others
                log.exception("periodic task %s failed", task.name)

    def start(self) -> None:
        for task in self.tasks:
            t = threading.Thread(target=self._loop, args=(task,),
                                 daemon=True, name=f"periodic-{task.name}")
            t.start()
            self._threads.append(t)

    def _loop(self, task: PeriodicTask) -> None:
        while not self._stop.wait(task.interval_s):
            if self.leadership is not None and \
                    not self.leadership.try_acquire():
                continue
            try:
                task.run(self.manager)
            except Exception:  # noqa: BLE001
                log.exception("periodic task %s failed", task.name)

    def stop(self) -> None:
        self._stop.set()
