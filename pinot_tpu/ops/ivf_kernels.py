"""IVF training / assignment device kernels.

The two hot loops of IVF — the [n, c] distance matrix and the one-hot
recentering — are batched matmuls, so both kernels are MXU work by
construction (unlike the query-time scoring tree, which trades the MXU
for bit-exact cross-backend accumulation; training has no such
contract — the ARTIFACT it produces is what gets pinned, and the
seeded host loop makes that artifact reproducible per backend).

Shapes are static (pow2-padded rows/centroids/dim) with live counts as
runtime scalars, so Lloyd's whole fixed-iteration loop reuses one
compiled step. Builders are lru-cached and traced by the tpulint deep
tier through `kernels.extra_contract_cases` at both shape buckets.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pinot_tpu.ops import kernels


@functools.lru_cache(maxsize=64)
def build_ivf_assign_kernel(n_pad: int, c_pad: int, dim_pad: int):
    """kernel(data f32 [n_pad, dim_pad], centroids f32 [c_pad, dim_pad],
    n_rows i32, n_centroids i32) → {"ivf.assign": i32 [n_pad] nearest
    live centroid (ties → lower id), "ivf.dist": f32 [n_pad] squared L2
    to it (0 on padding rows)}."""

    def kernel(data, centroids, n_rows, n_centroids):
        row_n2 = kernels.vec_tree_sum(data * data)            # [n_pad]
        cen_n2 = kernels.vec_tree_sum(centroids * centroids)  # [c_pad]
        cross = data @ centroids.T                            # MXU [n, c]
        d2 = row_n2[:, None] - 2.0 * cross + cen_n2[None, :]
        cval = jnp.arange(c_pad, dtype=jnp.int32) < n_centroids
        d2 = jnp.where(cval[None, :], d2, jnp.float32(jnp.inf))
        assign = jnp.argmin(d2, axis=1).astype(jnp.int32)
        rval = jnp.arange(n_pad, dtype=jnp.int32) < n_rows
        # the matmul identity can go slightly negative — clamp, and
        # zero padding rows so block sums need no host-side masking
        dist = jnp.where(rval, jnp.maximum(jnp.min(d2, axis=1), 0.0),
                         jnp.float32(0)).astype(jnp.float32)
        return {"ivf.assign": assign, "ivf.dist": dist}

    return kernel


@functools.lru_cache(maxsize=64)
def build_ivf_train_kernel(n_pad: int, c_pad: int, dim_pad: int):
    """One Lloyd's step: assign + one-hot recentering. Empty clusters
    keep their prior centroid (deterministic — no reseeding). Returns
    {"ivf.centroids": f32 [c_pad, dim_pad], "ivf.counts": i32 [c_pad]}."""
    assign_k = build_ivf_assign_kernel(n_pad, c_pad, dim_pad)

    def kernel(data, centroids, n_rows, n_centroids):
        assign = assign_k(data, centroids, n_rows, n_centroids)["ivf.assign"]
        rval = jnp.arange(n_pad, dtype=jnp.int32) < n_rows
        oh = ((assign[:, None] == jnp.arange(c_pad, dtype=jnp.int32)) &
              rval[:, None]).astype(jnp.float32)              # [n, c]
        sums = oh.T @ data                                    # MXU [c, d]
        counts = kernels.vec_tree_sum(oh.T)                   # f32 [c_pad]
        new_c = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts[:, None], 1.0),
                          centroids).astype(jnp.float32)
        return {"ivf.centroids": new_c,
                "ivf.counts": counts.astype(jnp.int32)}

    return kernel


@functools.lru_cache(maxsize=64)
def build_ivf_probe_kernel(c_pad: int, dim_pad: int, nprobe: int,
                           metric: str):
    """Standalone probe-select (the same helper the fused "ivf_probe"
    filter pred calls): kernel(centroids f32 [c_pad, dim_pad], cvalid
    bool [c_pad], q f32 [dim_pad], q_norm f32) → {"ivf.probe": i32
    [nprobe] top-nprobe live centroid ids, "ivf.probe_ok": bool
    [nprobe] slot validity when fewer live centroids than nprobe}."""

    def kernel(centroids, cvalid, q, q_norm):
        probe, ok = kernels.ivf_select_probes(centroids, cvalid, q,
                                              q_norm, metric, nprobe)
        return {"ivf.probe": probe, "ivf.probe_ok": ok}

    return kernel


@functools.lru_cache(maxsize=64)
def get_ivf_assign_kernel(n_pad: int, c_pad: int, dim_pad: int):
    return jax.jit(build_ivf_assign_kernel(n_pad, c_pad, dim_pad))


@functools.lru_cache(maxsize=64)
def get_ivf_train_kernel(n_pad: int, c_pad: int, dim_pad: int):
    return jax.jit(build_ivf_train_kernel(n_pad, c_pad, dim_pad))
