"""Deterministic fault injection for broker↔server transports.

Chaos-engineering support (Basiri et al., "Chaos Engineering", IEEE
Software 2016): the only way to trust a fault-tolerance layer is to
inject the faults it claims to handle, deterministically, in CI.
`FaultInjectingTransport` wraps any object with the `ServerTransport`
shape (``async query(server, payload, timeout) -> bytes`` plus
``async close()``) and injects seeded, per-server faults:

- ``latency``  — await an injected sleep before forwarding (the sleep
  coroutine is injectable, so tier-1 tests use virtual delays)
- ``hang``     — never respond; the caller's deadline/hedge must save it
- ``drop``     — raise ConnectionError (dropped connection)
- ``error``    — raise an arbitrary injected exception
- ``corrupt``  — forward, then mangle the response bytes
- ``missing_segments`` — forward a request stripped of the victim
  segments and stamp the response with the server's honest
  missing-segment report (exactly what a server that unloaded the
  segment would return)

Faults are armed per server with an optional activation budget
(`times`) and probability (driven by one seeded RNG, so a run is fully
reproducible). The transport counts every activation in `.injected`
for test assertions.

This module deliberately avoids importing the broker package: it
duck-types the transport so common/ stays a leaf layer.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import random
import threading
from typing import Awaitable, Callable, Dict, List, Optional

from pinot_tpu.common.datatable import (DataTable, MISSING_SEGMENTS_KEY,
                                        SEGMENT_MISSING_EXC_PREFIX)
from pinot_tpu.common.serde import (instance_request_from_bytes,
                                    instance_request_to_bytes)

class InjectedCrash(RuntimeError):
    """Raised at an armed crash point: simulates the process dying at
    exactly this instruction. Crash-recovery tests arm a point, drive
    the component until the crash fires, abandon the component (its
    in-memory state is 'lost'), and restart a fresh one over the same
    durable state — the WAL/snapshot/deep-store files written up to the
    crash instant."""


class CrashPoints:
    """Seeded, deterministic crash-point registry.

    Production code calls ``crash_points.hit("name")`` at instrumented
    instructions (WAL append, commit metadata flip, artifact download).
    Unarmed points are free; an armed point raises InjectedCrash on its
    Nth hit (``skip`` earlier hits pass through), then disarms — a
    restarted component runs past the same point cleanly, exactly like
    a real crash-once scenario.
    """

    def __init__(self):
        self._armed: Dict[str, int] = {}          # name -> remaining skips
        self.fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    def arm(self, name: str, skip: int = 0) -> None:
        """Fire on the (skip+1)-th hit of `name`."""
        with self._lock:
            self._armed[name] = skip

    def clear(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._armed.clear()
            else:
                self._armed.pop(name, None)

    def consume(self, name: str) -> bool:
        """True exactly when the armed point fires (and disarms it)."""
        with self._lock:
            skips = self._armed.get(name)
            if skips is None:
                return False
            if skips > 0:
                self._armed[name] = skips - 1
                return False
            del self._armed[name]
            self.fired[name] = self.fired.get(name, 0) + 1
            return True

    def hit(self, name: str) -> None:
        if self.consume(name):
            raise InjectedCrash(name)


#: process-wide registry — components hit it, tests arm/clear it
crash_points = CrashPoints()


LATENCY = "latency"
HANG = "hang"
DROP = "drop"
ERROR = "error"
CORRUPT = "corrupt"
MISSING_SEGMENTS = "missing_segments"

_KINDS = (LATENCY, HANG, DROP, ERROR, CORRUPT, MISSING_SEGMENTS)


@dataclasses.dataclass
class FaultSpec:
    """One armed fault. Immutable; activation bookkeeping lives in the
    transport so a spec can be shared/re-armed freely."""
    kind: str
    latency_s: float = 0.0                    # LATENCY only
    error: Optional[BaseException] = None     # ERROR only
    segments: tuple = ()                      # MISSING_SEGMENTS only
    probability: float = 1.0                  # per-call activation chance
    times: Optional[int] = None               # max activations; None = ∞

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"known: {_KINDS}")


class _Armed:
    __slots__ = ("spec", "remaining")

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self.remaining = spec.times


def corrupt_bytes(raw) -> bytes:
    """Deterministically mangle a response frame so DataTable.from_bytes
    must fail (the version header is inverted, never silently valid)."""
    raw = bytes(raw)       # the mux hands replies as frame memoryviews
    head = bytes(b ^ 0xFF for b in raw[:8])
    return head + raw[8:]


class FaultInjectingTransport:
    """Wraps a ServerTransport-shaped object, injecting armed faults.

    `sleep` is the coroutine used for LATENCY faults — inject a virtual
    clock's sleep (or an instant recorder) to keep tier-1 tests free of
    wall-clock waits. `seed` drives the probability RNG.
    """

    def __init__(self, inner, seed: int = 0,
                 sleep: Callable[[float], Awaitable[None]] = asyncio.sleep):
        self.inner = inner
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._faults: Dict[str, List[_Armed]] = {}
        # (server, kind) -> activation count, for test assertions
        self.injected: Dict[tuple, int] = {}
        self._lock = threading.Lock()

    @property
    def endpoints(self):
        """Endpoint transparency: the multi-stage planner addresses
        exchange peers via ``transport.endpoints`` — a fault wrapper
        must not hide the inner TCP transport's map (faults perturb
        dispatch, never addressing)."""
        return getattr(self.inner, "endpoints", {})

    def set_endpoint(self, server: str, host: str, port: int) -> None:
        self.inner.set_endpoint(server, host, port)

    # -- arming ------------------------------------------------------------
    def inject(self, server: str, spec: FaultSpec) -> FaultSpec:
        with self._lock:
            self._faults.setdefault(server, []).append(_Armed(spec))
        return spec

    def clear(self, server: Optional[str] = None) -> None:
        with self._lock:
            if server is None:
                self._faults.clear()
            else:
                self._faults.pop(server, None)

    def injected_count(self, server: str, kind: str) -> int:
        with self._lock:
            return self.injected.get((server, kind), 0)

    def _activate(self, server: str) -> List[FaultSpec]:
        """Decide (seeded) which armed faults fire for this call."""
        fired: List[FaultSpec] = []
        with self._lock:
            for armed in self._faults.get(server, []):
                if armed.remaining is not None and armed.remaining <= 0:
                    continue
                if armed.spec.probability < 1.0 and \
                        self._rng.random() >= armed.spec.probability:
                    continue
                if armed.remaining is not None:
                    armed.remaining -= 1
                key = (server, armed.spec.kind)
                self.injected[key] = self.injected.get(key, 0) + 1
                fired.append(armed.spec)
        return fired

    # -- transport shape ---------------------------------------------------
    async def query(self, server: str, payload: bytes,
                    timeout: float) -> bytes:
        fired = self._activate(server)
        strip_segments: set = set()
        corrupt = False
        for spec in fired:
            if spec.kind == LATENCY:
                await self._sleep(spec.latency_s)
            elif spec.kind == HANG:
                # wait forever; only the caller's cancellation (deadline
                # or a winning hedge) ends this — no wall-clock involved
                await asyncio.Event().wait()
            elif spec.kind == DROP:
                raise ConnectionError(
                    f"injected connection drop to {server}")
            elif spec.kind == ERROR:
                raise spec.error if spec.error is not None else \
                    RuntimeError(f"injected server error on {server}")
            elif spec.kind == CORRUPT:
                corrupt = True
            elif spec.kind == MISSING_SEGMENTS:
                strip_segments.update(spec.segments)

        if strip_segments:
            payload, stripped = _strip_segments(payload, strip_segments)
        else:
            stripped = []

        raw = await self.inner.query(server, payload, timeout)

        if stripped:
            raw = _stamp_missing(raw, stripped)
        if corrupt:
            raw = corrupt_bytes(raw)
        return raw

    async def close(self) -> None:
        await self.inner.close()


def _strip_segments(payload: bytes, victims: set):
    """Remove victim segments from the request so the server neither
    computes nor returns their rows (matching a server that unloaded
    them); returns (new_payload, actually_stripped)."""
    request = instance_request_from_bytes(payload)
    if request.search_segments is None:
        return payload, []
    stripped = [s for s in request.search_segments if s in victims]
    if not stripped:
        return payload, []
    request.search_segments = [s for s in request.search_segments
                               if s not in victims]
    return instance_request_to_bytes(request), stripped


def _stamp_missing(raw: bytes, stripped: List[str]) -> bytes:
    """Merge the injected missing segments into the response's honest
    missing-segment report (metadata + human-facing exception)."""
    dt = DataTable.from_bytes(raw)
    prior = []
    prior_raw = dt.metadata.get(MISSING_SEGMENTS_KEY)
    if prior_raw:
        try:
            prior = json.loads(prior_raw)
        except ValueError:
            prior = []
    missing = sorted(set(prior) | set(stripped))
    dt.metadata[MISSING_SEGMENTS_KEY] = json.dumps(missing)
    dt.exceptions = [e for e in dt.exceptions
                     if not str(e).startswith(SEGMENT_MISSING_EXC_PREFIX)]
    dt.exceptions.append(f"{SEGMENT_MISSING_EXC_PREFIX} {missing}")
    return dt.to_bytes()
