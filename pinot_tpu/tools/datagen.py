"""Synthetic data generators: in-memory segments for benchmarks & dryruns.

Parity: the reference's data-generation tooling
(pinot-tools/.../tools/data/DataGenerator.java and the SSB/TPC-H style
pinot-druid-benchmark harness, SURVEY.md §6). Builds ImmutableSegment objects
directly from arrays — no file round-trip — so 100M-row benchmark tables
materialize in seconds. All segments of a table share one global dictionary
per column (the layout the mesh-sharded executor combines in the dictId
domain).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.loader import DataSource, ImmutableSegment
from pinot_tpu.segment.metadata import ColumnMetadata, SegmentMetadata


def _bits_for(card: int) -> int:
    return max(1, int(np.ceil(np.log2(max(card, 2)))))


def make_segment_from_arrays(
        name: str, table: str,
        dict_cols: Dict[str, Tuple[DataType, np.ndarray, np.ndarray]],
        raw_cols: Optional[Dict[str, Tuple[DataType, np.ndarray]]] = None,
        ) -> ImmutableSegment:
    """Build a queryable in-memory segment.

    dict_cols: col → (data_type, sorted_unique_values, dict_ids[int32])
    raw_cols:  col → (data_type, values)  (no-dictionary columns)
    """
    raw_cols = raw_cols or {}
    num_docs = None
    columns: Dict[str, ColumnMetadata] = {}
    sources: Dict[str, DataSource] = {}

    for col, (dt, values, ids) in dict_cols.items():
        ids = np.ascontiguousarray(ids, dtype=np.int32)
        if num_docs is None:
            num_docs = len(ids)
        assert len(ids) == num_docs, f"column {col} length mismatch"
        card = len(values)
        cm = ColumnMetadata(
            name=col, data_type=dt, cardinality=card,
            bits_per_element=_bits_for(card), single_value=True,
            sorted=bool(np.all(ids[1:] >= ids[:-1])) if len(ids) else True,
            has_dictionary=True,
            min_value=values[0] if card else None,
            max_value=values[-1] if card else None,
            total_number_of_entries=num_docs)
        ds = DataSource(cm, None)
        ds.dictionary = Dictionary(dt, values)
        ds.dict_ids = ids
        columns[col] = cm
        sources[col] = ds

    for col, (dt, vals) in raw_cols.items():
        vals = np.ascontiguousarray(vals)
        if num_docs is None:
            num_docs = len(vals)
        assert len(vals) == num_docs, f"column {col} length mismatch"
        cm = ColumnMetadata(
            name=col, data_type=dt, cardinality=num_docs,
            bits_per_element=vals.dtype.itemsize * 8, single_value=True,
            sorted=False, has_dictionary=False,
            min_value=vals.min() if num_docs else None,
            max_value=vals.max() if num_docs else None,
            total_number_of_entries=num_docs)
        ds = DataSource(cm, None)
        ds.raw_values = vals
        columns[col] = cm
        sources[col] = ds

    meta = SegmentMetadata(segment_name=name, table_name=table,
                           total_docs=int(num_docs), columns=columns)
    seg = ImmutableSegment(meta, sources)
    for ds in sources.values():
        ds._segment = seg
    return seg


# ---------------------------------------------------------------------------
# SSB star-schema table, denormalized (flat lineorder) — the layout the
# Star Schema Benchmark Q1.1–Q4.3 queries run against, and the shape the
# reference's contrib/pinot-druid-benchmark flattens TPC-H into.
# ---------------------------------------------------------------------------

SSB_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SSB_NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "CHINA", "EGYPT",
               "ETHIOPIA", "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN",
               "IRAQ", "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE",
               "PERU", "ROMANIA", "RUSSIA", "SAUDI ARABIA", "UNITED KINGDOM",
               "UNITED STATES", "VIETNAM"]
# TPC-H nation → region (SSB inherits it)
SSB_NATION_REGION = {
    "ALGERIA": "AFRICA", "ETHIOPIA": "AFRICA", "KENYA": "AFRICA",
    "MOROCCO": "AFRICA", "MOZAMBIQUE": "AFRICA",
    "ARGENTINA": "AMERICA", "BRAZIL": "AMERICA", "CANADA": "AMERICA",
    "PERU": "AMERICA", "UNITED STATES": "AMERICA",
    "CHINA": "ASIA", "INDIA": "ASIA", "INDONESIA": "ASIA", "JAPAN": "ASIA",
    "VIETNAM": "ASIA",
    "FRANCE": "EUROPE", "GERMANY": "EUROPE", "ROMANIA": "EUROPE",
    "RUSSIA": "EUROPE", "UNITED KINGDOM": "EUROPE",
    "EGYPT": "MIDDLE EAST", "IRAN": "MIDDLE EAST", "IRAQ": "MIDDLE EAST",
    "JORDAN": "MIDDLE EAST", "SAUDI ARABIA": "MIDDLE EAST",
}
_MONTHS = ["Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug", "Sep",
           "Oct", "Nov", "Dec"]


SSB_TYPES = {
    "lo_quantity": DataType.INT, "lo_discount": DataType.INT,
    "lo_revenue": DataType.LONG, "lo_supplycost": DataType.DOUBLE,
    "d_year": DataType.INT, "d_yearmonthnum": DataType.INT,
    "d_yearmonth": DataType.STRING, "d_weeknuminyear": DataType.INT,
    "c_region": DataType.STRING, "c_nation": DataType.STRING,
    "c_city": DataType.STRING,
    "s_region": DataType.STRING, "s_nation": DataType.STRING,
    "s_city": DataType.STRING,
    "p_mfgr": DataType.STRING, "p_category": DataType.STRING,
    "p_brand1": DataType.STRING,
}
SSB_RAW_COLS = {"lo_supplycost"}


def _city_pool() -> np.ndarray:
    """250 cities: nation name truncated to 9 chars + digit (SSB layout,
    e.g. 'UNITED KI1'). Nations sorted + fixed-width suffix ⇒ the pool is
    lexicographically sorted and city_id == nation_id * 10 + digit."""
    nations = sorted(SSB_NATIONS)
    return np.array([n[:9] + str(d) for n in nations for d in range(10)],
                    dtype=object)


def ssb_pools(seed: int = 0) -> Dict[str, np.ndarray]:
    """Sorted global value pools (== the shared dictionaries)."""
    rng = np.random.default_rng(seed + 10_007)
    revenue = np.unique((rng.integers(100, 10_000, 8192) * 100)
                        .astype(np.int64))
    ymn = np.array(sorted(y * 100 + m for y in range(1992, 1999)
                          for m in range(1, 13)), dtype=np.int64)
    yearmonth = np.array(sorted(f"{_MONTHS[m]}{y}" for y in range(1992, 1999)
                                for m in range(12)), dtype=object)
    nations = np.array(sorted(SSB_NATIONS), dtype=object)
    return {
        "lo_quantity": np.arange(1, 51, dtype=np.int64),
        "lo_discount": np.arange(0, 11, dtype=np.int64),
        "lo_revenue": revenue,
        "d_year": np.arange(1992, 1999, dtype=np.int64),
        "d_yearmonthnum": ymn,
        "d_yearmonth": yearmonth,
        "d_weeknuminyear": np.arange(1, 54, dtype=np.int64),
        "c_region": np.array(sorted(SSB_REGIONS), dtype=object),
        "c_nation": nations,
        "c_city": _city_pool(),
        "s_region": np.array(sorted(SSB_REGIONS), dtype=object),
        "s_nation": nations,
        "s_city": _city_pool(),
        "p_mfgr": np.array([f"MFGR#{m}" for m in range(1, 6)], dtype=object),
        "p_category": np.array([f"MFGR#{m}{c}" for m in range(1, 6)
                                for c in range(1, 6)], dtype=object),
        "p_brand1": np.array([f"MFGR#{m}{c}{b:02d}" for m in range(1, 6)
                              for c in range(1, 6)
                              for b in range(1, 41)], dtype=object),
    }


def ssb_derivation_tables(pools) -> Dict[str, np.ndarray]:
    """Id-domain derivation maps for the correlated dimensions."""
    nations = pools["c_nation"]
    regions = list(pools["c_region"])
    nation_region = np.array(
        [regions.index(SSB_NATION_REGION[n]) for n in nations],
        dtype=np.int32)
    # ymn id (chronological) → d_yearmonth id (lexicographically sorted pool)
    ym_sorted = list(pools["d_yearmonth"])
    ymn_to_ym = np.array(
        [ym_sorted.index(f"{_MONTHS[(int(v) % 100) - 1]}{int(v) // 100}")
         for v in pools["d_yearmonthnum"]], dtype=np.int32)
    return {"nation_region": nation_region, "ymn_to_ym": ymn_to_ym}


def make_ssb_ids(total_rows: int, seed: int = 0
                 ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Correlated id-domain SSB table: (ids per column, raw supplycost).

    Base draws are uniform; city→nation→region, ymn→year/yearmonth and
    brand→category→mfgr are derived exactly like the star schema's
    functional dependencies."""
    rng = np.random.default_rng(seed)
    pools = ssb_pools(seed)
    maps = ssb_derivation_tables(pools)
    n = total_rows

    def narrow(arr):
        # minimal id dtype: keeps a 100M-row table host-resident
        from pinot_tpu.segment.loader import min_id_dtype
        m = int(arr.max()) if len(arr) else 0
        return arr.astype(min_id_dtype(m))

    ids: Dict[str, np.ndarray] = {}
    ids["lo_quantity"] = narrow(rng.integers(0, 50, n))
    ids["lo_discount"] = narrow(rng.integers(0, 11, n))
    ids["lo_revenue"] = narrow(
        rng.integers(0, len(pools["lo_revenue"]), n))
    ymn = narrow(rng.integers(0, 84, n))
    ids["d_yearmonthnum"] = ymn
    ids["d_year"] = narrow(ymn // 12)
    ids["d_yearmonth"] = narrow(maps["ymn_to_ym"][ymn])
    ids["d_weeknuminyear"] = narrow(rng.integers(0, 53, n))
    for side in ("c", "s"):
        city = narrow(rng.integers(0, 250, n))
        nation = narrow(city // 10)
        ids[f"{side}_city"] = city
        ids[f"{side}_nation"] = nation
        ids[f"{side}_region"] = narrow(maps["nation_region"][nation])
    brand = narrow(rng.integers(0, 1000, n))
    ids["p_brand1"] = brand
    ids["p_category"] = narrow(brand // 40)
    ids["p_mfgr"] = narrow(brand // 200)
    supplycost = (rng.random(n) * 1e5).round(2)
    return ids, supplycost


def ssb_schema():
    """Schema for the flat lineorder table (creator/loader path)."""
    from pinot_tpu.common.schema import (Schema, dimension, metric)
    fields = []
    for col, dt in SSB_TYPES.items():
        if col.startswith("lo_"):
            fields.append(metric(col, dt))
        else:
            fields.append(dimension(col, dt))
    return Schema("lineorder", fields)


# Star-tree cube configs for the SSB query classes (parity: the reference
# benchmark's star-tree segment variant, contrib/pinot-druid-benchmark
# config/; functional dependencies — city→nation→region, brand→category→
# mfgr — keep the actual group counts far below the dimension product).
# Split orders put each query class's FILTER dims first: cube rows are
# sorted by split order, so the executor's prefix descent narrows to
# contiguous blocks by binary search (the classic split-order guidance —
# most-filtered dimensions first).
SSB_STAR_TREE_CONFIGS = [
    {"dimensionsSplitOrder": ["s_region", "p_brand1", "d_year",
                              "p_category"],
     "metrics": ["lo_revenue"]},                      # Q2.2-2.3
    # Q2.1's EQ pair (s_region, p_category) leads its own cube so the
    # prefix descent lands on tens of rows instead of a region-block
    # residual scan (the chooser ranks by prefix depth, so Q2.2/2.3
    # keep the brand1-leading cube above)
    {"dimensionsSplitOrder": ["s_region", "p_category", "p_brand1",
                              "d_year"],
     "metrics": ["lo_revenue"]},                      # Q2.1
    {"dimensionsSplitOrder": ["c_region", "s_region", "c_nation",
                              "s_nation", "d_year"],
     "metrics": ["lo_revenue"]},                      # Q3.1
    {"dimensionsSplitOrder": ["c_nation", "s_nation", "c_city", "s_city",
                              "d_year"],
     "metrics": ["lo_revenue"]},                      # Q3.2
    {"dimensionsSplitOrder": ["c_city", "s_city", "d_year"],
     "metrics": ["lo_revenue"]},                      # Q3.3
    {"dimensionsSplitOrder": ["c_region", "s_region", "p_mfgr", "d_year",
                              "c_nation"],
     "metrics": ["lo_revenue", "lo_supplycost"]},     # Q4.1
    {"dimensionsSplitOrder": ["c_region", "s_region", "p_mfgr", "d_year",
                              "s_nation", "p_category"],
     "metrics": ["lo_revenue", "lo_supplycost"]},     # Q4.2
    # Q3.4/Q4.3: cubes whose row counts approach the segment's — useless
    # for scans, but the exact-prefix descents (c_city IN / region+
    # nation+category EQ) touch only tens of rows; maxSize raised past
    # the default cap because the scan-payoff heuristic doesn't apply
    {"dimensionsSplitOrder": ["c_city", "s_city", "d_yearmonth",
                              "d_year"],
     "metrics": ["lo_revenue"], "maxSize": 8_000_000},        # Q3.4
    {"dimensionsSplitOrder": ["c_region", "s_nation", "p_category",
                              "d_year", "s_city", "p_brand1"],
     "metrics": ["lo_revenue", "lo_supplycost"],
     "maxSize": 12_000_000},                                  # Q4.3
]


def ssb_table_config(star_tree: bool = False):
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    return TableConfig("lineorder", indexing_config=IndexingConfig(
        no_dictionary_columns=sorted(SSB_RAW_COLS),
        star_tree_configs=list(SSB_STAR_TREE_CONFIGS) if star_tree
        else []))


def build_ssb_segment_dirs(base_dir: str, total_rows: int,
                           num_segments: int, seed: int = 0,
                           log=None, star_tree: bool = False,
                           shared_dictionaries: bool = False
                           ) -> Tuple[List[str], Dict, np.ndarray]:
    """Full storage path: rows → SegmentCreator → segment dirs on disk.

    Each segment builds its OWN dictionaries from its own rows — exactly
    what the reference's per-segment SegmentDictionaryCreator produces —
    and the sharded executor's stack-time union remap handles the
    differing id domains. `shared_dictionaries=True` restores the old
    engineered full-domain dictionaries (kept for A/B comparisons).
    Returns (segment_dirs, ids, supplycost) — ids feed the numpy oracle."""
    import os

    from pinot_tpu.segment.creator import SegmentCreator

    pools = ssb_pools(seed)
    ids, supplycost = make_ssb_ids(total_rows, seed)
    schema = ssb_schema()
    config = ssb_table_config(star_tree=star_tree)
    per = total_rows // num_segments
    dirs = []
    fixed = {c: pools[c] for c in SSB_TYPES if c not in SSB_RAW_COLS} \
        if shared_dictionaries else None
    for i in range(num_segments):
        lo = i * per
        hi = (i + 1) * per if i < num_segments - 1 else total_rows
        from pinot_tpu.segment.creator import DictionaryEncodedColumn
        cols = {}
        for c in SSB_TYPES:
            if c in SSB_RAW_COLS:
                cols[c] = supplycost[lo:hi]
            else:
                # dictionary-encoded columnar input: the creator still
                # builds a PER-SEGMENT dictionary of only this slice's
                # present values (byte-identical segments to the decoded
                # path) without hashing row-scale strings
                cols[c] = DictionaryEncodedColumn(pools[c], ids[c][lo:hi])
        d = os.path.join(base_dir, f"ssb_{i}")
        SegmentCreator(schema, config, segment_name=f"ssb_{i}",
                       fixed_dictionaries=fixed).build(cols, d)
        dirs.append(d)
        if log:
            log(f"datagen: built segment {i + 1}/{num_segments} "
                f"({hi - lo} rows) via SegmentCreator")
    return dirs, ids, supplycost


# ---------------------------------------------------------------------------
# Star-schema JOIN tables: a `part` dim table × a `lineorderj` fact table
# (the normalized shape the multi-stage join engine serves — the flat SSB
# table above is the 2019-era denormalized workaround).
# ---------------------------------------------------------------------------


def part_dim_schema():
    from pinot_tpu.common.schema import Schema, dimension
    return Schema("part", [
        dimension("p_partkey", DataType.INT),
        dimension("p_mfgr", DataType.STRING),
        dimension("p_category", DataType.STRING),
        dimension("p_brand1", DataType.STRING),
    ])


def fact_join_schema():
    from pinot_tpu.common.schema import Schema, dimension, metric
    return Schema("lineorderj", [
        dimension("lo_partkey", DataType.INT),
        dimension("d_year", DataType.INT),
        metric("lo_quantity", DataType.INT),
        metric("lo_revenue", DataType.LONG),
    ])


def join_table_configs(num_partitions: int = 0):
    """(fact config, dim config); `num_partitions` > 0 partitions BOTH
    tables on their join keys (Modulo) — the co-partitioned dispatch
    shape."""
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    part_cfg = {"functionName": "Modulo",
                "numPartitions": num_partitions}
    fact_idx = IndexingConfig(
        segment_partition_config={"lo_partkey": dict(part_cfg)}
        if num_partitions else {})
    dim_idx = IndexingConfig(
        segment_partition_config={"p_partkey": dict(part_cfg)}
        if num_partitions else {})
    return (TableConfig("lineorderj", indexing_config=fact_idx),
            TableConfig("part", indexing_config=dim_idx))


def make_join_rows(fact_rows: int, dim_rows: int = 800, seed: int = 0,
                   miss_rate: float = 0.1) -> Tuple[Dict, Dict]:
    """(dim columns, fact columns) as plain arrays (oracle-friendly).

    Dim keys are a NON-CONTIGUOUS sorted sample (probes must not
    degenerate to offsets) with SSB-style brand→category→mfgr
    functional dependencies; `miss_rate` of fact keys reference no dim
    row (inner-join drops them).
    """
    rng = np.random.default_rng(seed + 40_009)
    keys = np.sort(rng.choice(np.arange(1, dim_rows * 7, dtype=np.int64),
                              size=dim_rows, replace=False))
    brand_id = rng.integers(0, 1000, dim_rows)
    dim = {
        "p_partkey": keys.astype(np.int32),
        "p_brand1": np.array(
            [f"MFGR#{b // 200 + 1}{(b // 40) % 5 + 1}{b % 40 + 1:02d}"
             for b in brand_id], dtype=object),
        "p_category": np.array(
            [f"MFGR#{b // 200 + 1}{(b // 40) % 5 + 1}" for b in brand_id],
            dtype=object),
        "p_mfgr": np.array([f"MFGR#{b // 200 + 1}" for b in brand_id],
                           dtype=object),
    }
    n = fact_rows
    fact_key = keys[rng.integers(0, dim_rows, n)].astype(np.int64)
    miss = rng.random(n) < miss_rate
    # miss keys: values guaranteed absent from the dim key set
    fact_key[miss] = -fact_key[miss] - 1
    fact = {
        "lo_partkey": fact_key.astype(np.int32),
        "d_year": rng.integers(1992, 1999, n).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "lo_revenue": (rng.integers(100, 10_000, n) * 100).astype(
            np.int64),
    }
    return dim, fact


def build_join_table_dirs(base_dir: str, fact_rows: int,
                          num_fact_segments: int, dim_rows: int = 800,
                          num_dim_segments: int = 1, seed: int = 0,
                          num_partitions: int = 0
                          ) -> Tuple[List[str], List[str], Dict, Dict]:
    """Segment dirs for the join tables via the real storage path.

    With `num_partitions` > 0, rows are partition-aligned: each segment
    holds exactly one Modulo partition's rows (per-segment partition
    metadata becomes discriminating, the co-partitioned exchange shape).
    Returns (fact_dirs, dim_dirs, dim columns, fact columns).
    """
    import os

    from pinot_tpu.segment.creator import SegmentCreator

    dim, fact = make_join_rows(fact_rows, dim_rows, seed)
    fact_cfg, dim_cfg = join_table_configs(num_partitions)

    def build(schema, cfg, cols, key_col, n_segs, prefix):
        n = len(cols[key_col])
        if num_partitions:
            pids = np.abs(cols[key_col].astype(np.int64)) % num_partitions
            slices = [np.nonzero(pids == p)[0]
                      for p in range(num_partitions)]
        else:
            per = -(-n // n_segs)
            slices = [np.arange(i * per, min((i + 1) * per, n))
                      for i in range(n_segs)]
        dirs = []
        for i, rows in enumerate(slices):
            if not len(rows):
                continue
            d = os.path.join(base_dir, f"{prefix}_{i}")
            sub = {c: (v[rows] if isinstance(v, np.ndarray)
                       else [v[j] for j in rows])
                   for c, v in cols.items()}
            SegmentCreator(schema, cfg,
                           segment_name=f"{prefix}_{i}").build(sub, d)
            dirs.append(d)
        return dirs

    fact_dirs = build(fact_join_schema(), fact_cfg, fact, "lo_partkey",
                      num_fact_segments, "factj")
    dim_dirs = build(part_dim_schema(), dim_cfg, dim, "p_partkey",
                     num_dim_segments, "partd")
    return fact_dirs, dim_dirs, dim, fact


def join_oracle(dim: Dict, fact: Dict, dim_filter=None,
                group_cols: Sequence[str] = (),
                agg: str = "sum_revenue") -> Dict:
    """Independent numpy oracle for the join smoke/bench parity gates:
    inner-join fact×dim on the part key, optional dim-side row mask
    (callable dim→bool [D]), group by (qualified) columns, aggregate
    SUM(lo_revenue)+COUNT."""
    keys = dim["p_partkey"].astype(np.int64)
    order = np.argsort(keys, kind="stable")
    skeys = keys[order]
    fk = fact["lo_partkey"].astype(np.int64)
    pos = np.clip(np.searchsorted(skeys, fk), 0, max(len(skeys) - 1, 0))
    hit = skeys[pos] == fk if len(skeys) else np.zeros(len(fk), bool)
    dimrow = order[pos]
    if dim_filter is not None:
        hit = hit & dim_filter(dim)[dimrow]
    rows = np.nonzero(hit)[0]
    out: Dict = {"count": int(len(rows)),
                 "sum_revenue": int(fact["lo_revenue"][rows].sum())}
    if group_cols:
        lanes = []
        for c in group_cols:
            if c.startswith("part."):
                lanes.append(dim[c[5:]][dimrow[rows]])
            else:
                lanes.append(fact[c.split(".", 1)[-1]][rows])
        keyed: Dict[tuple, list] = {}
        for i in range(len(rows)):
            k = tuple(lane[i] for lane in lanes)
            e = keyed.setdefault(k, [0, 0])
            e[0] += int(fact["lo_revenue"][rows[i]])
            e[1] += 1
        out["groups"] = {k: tuple(v) for k, v in keyed.items()}
    return out


class SsbTable:
    """Generated table: segments + id-level host arrays for oracle math.

    Oracle checks run on the int32 id arrays (decode via `pools`) so 100M-row
    tables never materialize 100M python-object string columns host-side.
    """

    def __init__(self, segments, pools, ids, supplycost):
        self.segments = segments
        self.pools = pools            # col → sorted values (the dictionary)
        self.ids = ids                # col → int32 [total_rows]
        self.supplycost = supplycost  # raw float64 [total_rows]

    def id_of(self, col: str, value) -> int:
        i = int(np.searchsorted(self.pools[col], value))
        assert self.pools[col][i] == value
        return i

    def decoded(self, col: str) -> np.ndarray:
        if col == "lo_supplycost":
            return self.supplycost
        return self.pools[col][self.ids[col]]


def make_ssb_device_stack(total_rows: int, num_segments: int, mesh,
                          seed: int = 0):
    """Device-generated stacked SSB lanes for large-scale benchmarking.

    Host->device bandwidth can be the bottleneck for huge synthetic tables
    (notably through the test harness's TPU relay), so the column lanes are
    synthesized directly in HBM with jax PRNG — same pools/cardinalities/
    distributions as make_ssb_segments, different values. Returns
    (lanes, num_docs_sharded, plan_table) where `lanes` maps
    "col.ids"/"col.parts"/"col.raw" to [S, P] device arrays sharded over the
    mesh's `seg` axis, and `plan_table` is a tiny host SsbTable with the
    same dictionaries for building plans/params.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pinot_tpu.parallel.sharded import SEG_AXIS
    from pinot_tpu.segment.loader import padded_size

    pools = ssb_pools(seed)
    maps = ssb_derivation_tables(pools)
    per = total_rows // num_segments
    padded = padded_size(per)
    shard = NamedSharding(mesh, P(SEG_AXIS))
    n_dev = mesh.devices.size
    s_total = -(-num_segments // n_dev) * n_dev

    key = jax.random.PRNGKey(seed)
    lanes = {}

    def lane_dtype(card):
        # narrow id lanes, matching the loader's storage-path ladder
        from pinot_tpu.segment.loader import min_id_dtype
        return jnp.dtype(min_id_dtype(card))

    def uniform(card):
        nonlocal key
        key, sub = jax.random.split(key)
        arr = jax.random.randint(sub, (s_total, padded), 0, card,
                                 dtype=jnp.int32)
        return jax.device_put(arr.astype(lane_dtype(card)), shard)

    # base uniforms
    for c in ("lo_quantity", "lo_discount", "lo_revenue",
              "d_weeknuminyear"):
        lanes[f"{c}.ids"] = uniform(len(pools[c]))
    ymn = uniform(84)
    lanes["d_yearmonthnum.ids"] = ymn
    # derived dimensions: the same functional dependencies as the host
    # generator, applied with device gathers over tiny mapping tables
    ym_map = jnp.asarray(maps["ymn_to_ym"].astype(np.int8))
    region_map = jnp.asarray(maps["nation_region"].astype(np.int8))
    derive = jax.jit(lambda f, x: f(x), static_argnums=0,
                     out_shardings=shard)
    lanes["d_year.ids"] = derive(lambda y: (y // 12).astype(jnp.int8), ymn)
    lanes["d_yearmonth.ids"] = derive(lambda y: ym_map[y.astype(jnp.int32)],
                                      ymn)
    for side in ("c", "s"):
        city = uniform(250)
        lanes[f"{side}_city.ids"] = city
        nation = derive(lambda x: (x // 10).astype(jnp.int8), city)
        lanes[f"{side}_nation.ids"] = nation
        lanes[f"{side}_region.ids"] = derive(
            lambda x: region_map[x.astype(jnp.int32)], nation)
    brand = uniform(1000)
    lanes["p_brand1.ids"] = brand
    lanes["p_category.ids"] = derive(lambda b: (b // 40).astype(jnp.int8),
                                     brand)
    lanes["p_mfgr.ids"] = derive(lambda b: (b // 200).astype(jnp.int8),
                                 brand)

    # bit-sliced part lanes for the integer SUM metric (lo_revenue)
    plan_table = make_ssb_segments(max(BLOCK_ROWS, 2 * padded_size(1)),
                                   1, seed=seed)
    ds = plan_table.segments[0].data_source("lo_revenue")
    n_parts, _ = ds.int_part_info()
    vals = np.asarray(ds.dictionary.values, dtype=np.int64)
    off = vals - int(vals[0])
    table = np.stack([(off >> (7 * k)) & 0x7F
                      for k in range(n_parts)]).astype(np.int8)
    table_dev = jnp.asarray(table)
    rev_ids = lanes["lo_revenue.ids"]
    parts = jax.jit(
        lambda ids: jnp.moveaxis(table_dev[:, ids], 1, 0),
        out_shardings=shard)(rev_ids)
    lanes["lo_revenue.parts"] = parts

    key, sub = jax.random.split(key)
    raw = jax.random.uniform(sub, (s_total, padded), jnp.float32) * 1e5
    lanes["lo_supplycost.raw"] = jax.device_put(raw, shard)

    num_docs = np.zeros(s_total, np.int32)
    num_docs[:num_segments] = per
    num_docs_dev = jax.device_put(num_docs, shard)
    return lanes, num_docs_dev, plan_table, padded


BLOCK_ROWS = 16384


def make_ssb_segments(total_rows: int, num_segments: int, seed: int = 0
                      ) -> SsbTable:
    """num_segments equal slices of an SSB table with GLOBAL dictionaries.

    DictIds are generated directly against pre-sorted pools (no
    unique/searchsorted pass over the full table — 100M rows materialize in
    seconds). Same correlated distributions as the creator path
    (build_ssb_segment_dirs), no file round-trip.
    """
    pools = ssb_pools(seed)
    ids, supplycost = make_ssb_ids(total_rows, seed)

    per = total_rows // num_segments
    segments = []
    for i in range(num_segments):
        lo, hi = i * per, (i + 1) * per if i < num_segments - 1 else total_rows
        dict_part = {c: (SSB_TYPES[c], pools[c], ids[c][lo:hi])
                     for c in pools}
        raw_part = {"lo_supplycost": (DataType.DOUBLE, supplycost[lo:hi])}
        segments.append(make_segment_from_arrays(
            f"ssb_{i}", "lineorder", dict_part, raw_part))
    return SsbTable(segments, pools, ids, supplycost)
