#!/usr/bin/env python
"""Compaction / retention / upsert-GC soak gate (ISSUE 11).

Two phases over an embedded primary-key upsert cluster, identical
workload: a rotating-key stream at 2x steady churn (every window
publishes a fresh key cohort AND republishes the previous cohort with
new values, so every row is overwritten once in its lifetime).

- **Phase OFF** (no maintenance): masked-dead rows and the key map
  grow monotonically — the degradation ISSUE 11 exists to stop.
- **Phase ON** (maintenance each window: minion scheduler -> worker
  compaction swaps -> TTL retention with delayed delete -> swap
  janitor): scan p99, total committed docs and `upsertKeyMapSize` must
  stay FLAT, while every checkpoint keeps the exact-dedup invariant
  COUNT(*) == key-map size and zero query exceptions.

Mid-run, phase ON additionally kill -9s the maintenance plane at the
swap protocol's seeded crash points:

- `compact.staged`   — the MINION dies mid rewrite+swap; the claim
  lease expires, the queue requeues, a second worker converges.
- `compact.pre_swap` — the SWAP DRIVER dies with the durable intent
  record open (the controller-restart shape: in-memory state gone,
  store survives); a FRESH SwapJanitor over the same durable store
  resumes the swap. (True controller process kill -9 / restart is
  crash_restart_smoke.py's gate; the recovery surface — resume from
  the durable intent — is identical.)

Writes COMPACT_ARTIFACT (default COMPACT_r09.json). Exit 0 when every
gate holds. Env knobs:
  COMPACT_SMOKE_WINDOWS   churn windows per phase   (default 8)
  COMPACT_SMOKE_KEYS      fresh keys per window     (default 150)
  COMPACT_ARTIFACT        artifact path             (default COMPACT_r09.json)
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))

WINDOWS = int(os.environ.get("COMPACT_SMOKE_WINDOWS", "8"))
KEYS = int(os.environ.get("COMPACT_SMOKE_KEYS", "150"))
ARTIFACT = os.environ.get("COMPACT_ARTIFACT", "COMPACT_r09.json")
RT_TABLE = "baseballStats_REALTIME"
DAY_MS = 86_400_000
RETENTION_DAYS = 3
QUERIES_PER_CHECKPOINT = 30


def wait_for(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:  # noqa: BLE001 — still converging
            pass
        time.sleep(0.05)
    print(f"FAIL: timed out waiting for {what}", file=sys.stderr)
    return False


def window_rows(w, keys=KEYS):
    """Window w: fresh cohort K_w interleaved with a republish of
    K_{w-1} under new values — 2x churn. The interleave matters: each
    sealed segment ends up PARTIALLY dead (a compaction target), never
    cleanly 100% dead (which would be retention's job alone). yearID
    encodes the window so TTL retention expires whole cohorts."""
    def row(k, gen):
        return {"teamID": f"T{k % 7}", "league": "AL" if k % 2 else "NL",
                "playerName": f"key_{k}", "position": ["P"],
                "runs": 10 * gen + (k % 10), "hits": k % 5,
                "average": 0.25, "salary": 100.0, "yearID": w + 1}
    fresh = [row(k, 1) for k in range(w * keys, (w + 1) * keys)]
    if w == 0:
        return fresh
    again = [row(k, 2) for k in range((w - 1) * keys, w * keys)]
    return [r for pair in zip(fresh, again) for r in pair]


def run_phase(maintain, crash_plan, log):
    """One soak phase; returns its checkpoint series dict."""
    from fixtures import make_schema
    from test_upsert import upsert_rt_config

    from pinot_tpu.common.faults import InjectedCrash, crash_points
    from pinot_tpu.controller.compaction import SegmentSwapManager, \
        SwapJanitor
    from pinot_tpu.controller.periodic import RetentionManager
    from pinot_tpu.minion import MinionWorker, TaskQueue
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)
    from pinot_tpu.tools.cluster import EmbeddedCluster

    tag = "on" if maintain else "off"
    topic = f"compact_smoke_{tag}"
    stream = MemoryStream(topic, num_partitions=1)
    registry.register_stream_factory(
        f"mem_{topic}", MemoryStreamConsumerFactory(stream,
                                                    batch_size=50))
    work = tempfile.mkdtemp(prefix=f"compact_smoke_{tag}_")
    cluster = EmbeddedCluster(work, num_servers=1,
                              store_dir=os.path.join(work, "store"))
    series = {"keyMap": [], "scanP99Ms": [], "committedDocs": [],
              "countEqualsKeyMap": [], "queryErrors": 0,
              "crashGates": []}
    try:
        cluster.add_schema(make_schema())
        cfg = upsert_rt_config(f"mem_{topic}", topic, flush_rows=KEYS)
        if maintain:
            cfg.task_configs = {"UpsertCompactionTask": {
                "invalidDocsThresholdPercent": "10",
                "minInvalidDocs": "5"}}
            cfg.segments_config.retention_time_unit = "DAYS"
            cfg.segments_config.retention_time_value = RETENTION_DAYS
        cluster.add_table(cfg)
        mgr = cluster.controller.manager
        rtdm = cluster.participants["Server_0"].realtime

        class Clock:
            t = 1000.0
        queue = TaskQueue(mgr.store, clock=lambda: Clock.t,
                          lease_s=60.0)
        tm = cluster.controller.task_manager
        tm.queue = queue
        published = 0
        for w in range(WINDOWS):
            rows = window_rows(w)
            for r in rows:
                stream.publish(r, partition=0)
            published += len(rows)

            def consumed():
                rdms = list(rtdm._consuming.values())
                return rdms and max(r.offset for r in rdms) >= published
            if not wait_for(consumed, 60, f"window {w} consumption"):
                raise RuntimeError("consumption stalled")
            if maintain:
                crash_at = crash_plan.get(w)
                if crash_at:
                    # the crash gates need a swap to crash: wait for
                    # the seal-time deadness publication to land for
                    # at least one partially dead DONE segment
                    from pinot_tpu.realtime.upsert import deadness_path

                    def compactable():
                        for s in mgr.segment_names(RT_TABLE):
                            meta = mgr.segment_metadata(RT_TABLE, s) \
                                or {}
                            if meta.get("status") != "DONE":
                                continue
                            rec = mgr.store.get(
                                deadness_path(RT_TABLE, s))
                            if rec and 5 <= len(rec["invalid"]) < \
                                    int(rec["numDocs"] or 0):
                                return True
                        return False
                    if not wait_for(compactable, 30,
                                    "a compactable segment"):
                        raise RuntimeError(
                            f"window {w}: no compactable segment for "
                            f"the {crash_at} gate")
                tm.schedule_tasks()
                worker = MinionWorker(
                    mgr, instance_id=f"Minion_{tag}_{w}",
                    work_dir=os.path.join(work, f"minion_{w}"))
                worker.queue = queue
                if crash_at:
                    crash_points.arm(crash_at)
                    try:
                        worker.drain()
                        gate = f"{crash_at}: NEVER FIRED"
                    except InjectedCrash:
                        # kill -9 mid-swap: recover with a FRESH
                        # janitor over the durable store (restarted
                        # controller shape; the driver is provably
                        # dead so the live-driver age gate is waived),
                        # then lease-requeue the died-with-the-minion
                        # claim for worker #2
                        janitor = SwapJanitor(
                            SegmentSwapManager(mgr),
                            min_intent_age_s=0)
                        janitor.run(mgr)
                        Clock.t += 61
                        queue.requeue_expired()
                        worker2 = MinionWorker(
                            mgr, instance_id=f"Minion_{tag}_{w}b",
                            work_dir=os.path.join(work,
                                                  f"minion_{w}b"))
                        worker2.queue = queue
                        worker2.drain()
                        open_intents = cluster.controller.swaps \
                            .open_intents(RT_TABLE)
                        gate = (f"{crash_at}: recovered, "
                                f"{len(open_intents)} open intent(s)")
                        if open_intents:
                            raise RuntimeError(
                                f"unresolved intents {open_intents}")
                    finally:
                        crash_points.clear()
                    series["crashGates"].append(gate)
                    log(f"  window {w}: {gate}")
                else:
                    worker.drain()
                RetentionManager(
                    now_ms_fn=lambda: (w + 1) * DAY_MS + 1).run(mgr)
                SwapJanitor(cluster.controller.swaps).run(mgr)

            # checkpoint: scan latency, key-map size, committed docs,
            # and the exact-dedup invariant COUNT(*) == key map
            lat = []
            for i in range(QUERIES_PER_CHECKPOINT):
                q = ("SELECT COUNT(*), SUM(runs) FROM baseballStats"
                     if i % 2 else
                     "SELECT COUNT(*) FROM baseballStats "
                     "WHERE league = 'AL'")
                t0 = time.perf_counter()
                resp = cluster.query(q)
                lat.append((time.perf_counter() - t0) * 1e3)
                if resp.exceptions:
                    series["queryErrors"] += 1
            lat.sort()
            p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
            um = rtdm.upsert_manager(RT_TABLE)
            keymap = um.key_map_size()
            count = int(cluster.query(
                "SELECT COUNT(*) FROM baseballStats")
                .aggregation_results[0].value)
            docs = sum(int((mgr.segment_metadata(RT_TABLE, s) or {}
                            ).get("totalDocs") or 0)
                       for s in mgr.segment_names(RT_TABLE))
            series["keyMap"].append(keymap)
            series["scanP99Ms"].append(round(p99, 2))
            series["committedDocs"].append(docs)
            series["countEqualsKeyMap"].append(count == keymap)
            log(f"  [{tag}] window {w}: keyMap={keymap} count={count} "
                f"docs={docs} scanP99={p99:.1f}ms")
        return series
    finally:
        cluster.stop()


def main() -> int:
    def log(msg):
        print(msg, flush=True)

    log(f"== compaction soak: {WINDOWS} windows x {KEYS} keys, "
        "2x churn ==")
    log("phase OFF (no maintenance — the degradation baseline)")
    off = run_phase(False, {}, log)
    log("phase ON (compaction + retention + GC each window, "
        "kill -9 mid-swap twice)")
    on = run_phase(True, {WINDOWS // 2: "compact.staged",
                          WINDOWS // 2 + 1: "compact.pre_swap"}, log)

    # post-warmup reference: the live set reaches steady state once
    # retention holds (retention window + 1) cohorts, at window 3
    mid = min(3, WINDOWS - 2)
    gates = {
        # the problem exists: without maintenance the key map and the
        # committed-doc count grow monotonically with churn
        "offKeyMapGrows": off["keyMap"][-1] >= off["keyMap"][mid] +
        (WINDOWS - 1 - mid) * KEYS,
        "offDocsGrow": off["committedDocs"][-1] >
        1.5 * max(off["committedDocs"][mid], 1),
        # the fix holds: maintenance keeps both flat
        "onKeyMapFlat": on["keyMap"][-1] <=
        1.25 * max(on["keyMap"][mid], 1),
        "onDocsFlat": on["committedDocs"][-1] <=
        1.35 * max(on["committedDocs"][mid], 1),
        "onKeyMapBelowOff": on["keyMap"][-1] < 0.7 * off["keyMap"][-1],
        # scan latency stays flat (generous CI-noise bound: the OFF
        # phase's tail keeps growing with dead rows, ON must not)
        "onScanP99Flat": on["scanP99Ms"][-1] <=
        max(2.0 * on["scanP99Ms"][mid], on["scanP99Ms"][mid] + 25.0),
        # exactness all the way through, including across the kill -9s
        "onExactDedupEveryCheckpoint": all(on["countEqualsKeyMap"]),
        "onZeroQueryErrors": on["queryErrors"] == 0,
        "bothCrashGatesRecovered":
            len(on["crashGates"]) == 2 and
            all("recovered" in g for g in on["crashGates"]),
    }
    artifact = {
        "suite": "compaction_soak",
        "windows": WINDOWS, "keysPerWindow": KEYS,
        "churn": "2x (every row overwritten once)",
        "retentionDays": RETENTION_DAYS,
        "phaseOff": off, "phaseOn": on,
        "gates": gates,
        "pass": all(gates.values()),
    }
    with open(ARTIFACT, "w") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")
    log(f"gates: {json.dumps(gates, indent=1)}")
    log(f"artifact: {ARTIFACT}")
    if not artifact["pass"]:
        log("FAIL: compaction soak gates not met")
        return 1
    log("PASS: flat scan p99 + flat key map under 2x churn with "
        "maintenance on; monotonic growth with it off; kill -9 "
        "mid-swap recovered exactly")
    return 0


if __name__ == "__main__":
    sys.exit(main())
