"""Dedicated tests for common/retry.py: jitter bounds, cause chaining,
retry_on filtering, and zero-sleep injection (the module previously had
no direct coverage)."""
import random

import pytest

from pinot_tpu.common.retry import (ExponentialBackoffRetryPolicy,
                                    FixedDelayRetryPolicy,
                                    RandomDelayRetryPolicy,
                                    RetryExhaustedError, RetryPolicy)


def test_exponential_backoff_jitter_bounds():
    policy = ExponentialBackoffRetryPolicy(attempts=5, initial_delay_s=0.1,
                                           scale=2.0,
                                           rng=random.Random(3))
    for attempt in range(6):
        window = 0.1 * (2.0 ** attempt)
        for _ in range(50):
            d = policy.delay_for(attempt)
            # uniformly jittered to [0.5, 1.0) of the window
            assert 0.5 * window <= d < window


def test_exponential_backoff_seeded_rng_is_deterministic():
    a = ExponentialBackoffRetryPolicy(3, 0.5, rng=random.Random(11))
    b = ExponentialBackoffRetryPolicy(3, 0.5, rng=random.Random(11))
    assert [a.delay_for(i) for i in range(5)] == \
        [b.delay_for(i) for i in range(5)]


def test_retry_exhausted_chains_last_failure_as_cause():
    boom = ValueError("attempt-specific detail")

    def op():
        raise boom

    policy = FixedDelayRetryPolicy(attempts=3, delay_s=0.0)
    with pytest.raises(RetryExhaustedError) as exc_info:
        policy.attempt(op, sleep=lambda s: None)
    assert exc_info.value.__cause__ is boom
    assert "3 attempts" in str(exc_info.value)


def test_retry_on_filters_exception_classes():
    calls = []

    def op():
        calls.append(1)
        raise ValueError("not retryable here")

    policy = FixedDelayRetryPolicy(attempts=4, delay_s=0.0)
    # a non-matching exception propagates immediately, unwrapped
    with pytest.raises(ValueError):
        policy.attempt(op, retry_on=(KeyError,), sleep=lambda s: None)
    assert len(calls) == 1

    # a matching one is retried to exhaustion
    calls.clear()
    with pytest.raises(RetryExhaustedError):
        policy.attempt(op, retry_on=(ValueError,), sleep=lambda s: None)
    assert len(calls) == 4


def test_zero_sleep_injection_records_policy_delays():
    slept = []
    attempts = []

    def op():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionError("transient")
        return "ok"

    policy = FixedDelayRetryPolicy(attempts=5, delay_s=1.5)
    assert policy.attempt(op, sleep=slept.append) == "ok"
    # two failures → two sleeps, none real; no sleep after success
    assert slept == [1.5, 1.5]
    assert len(attempts) == 3


def test_no_sleep_after_final_attempt():
    slept = []
    policy = FixedDelayRetryPolicy(attempts=2, delay_s=0.7)

    def op():
        raise OSError("always")

    with pytest.raises(RetryExhaustedError):
        policy.attempt(op, sleep=slept.append)
    assert slept == [0.7]          # N attempts sleep only N-1 times


def test_random_delay_policy_bounds():
    policy = RandomDelayRetryPolicy(attempts=3, min_delay_s=0.2,
                                    max_delay_s=0.9,
                                    rng=random.Random(5))
    for attempt in range(10):
        assert 0.2 <= policy.delay_for(attempt) <= 0.9


def test_policy_validates_attempts():
    with pytest.raises(ValueError):
        RetryPolicy(0)
    with pytest.raises(ValueError):
        ExponentialBackoffRetryPolicy(attempts=-1, initial_delay_s=0.1)


def test_first_attempt_success_never_sleeps():
    slept = []
    policy = ExponentialBackoffRetryPolicy(attempts=4, initial_delay_s=9.0)
    assert policy.attempt(lambda: 42, sleep=slept.append) == 42
    assert slept == []
