// Native segment-build hot loops.
//
// The TPU answers queries; the HOST builds segments — and the build's hot
// loops (cube grouping, grouped stats, fixed-bit packing) are pure
// pointer-chasing/accumulation work where numpy pays a full array pass
// per operator. This is the same division of labor as the reference,
// whose segment creation is native Java/C++ speed
// (core/segment/creator/impl/SegmentIndexCreationDriverImpl.java): one
// tight loop per task, compiled -O3, called through ctypes.
//
// Build: compiled on first use by pinot_tpu/native/__init__.py with g++
// (graceful numpy fallback when no compiler is present).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// pack_bits: ids (< 2^nb) -> dense little-endian bitstream as uint32 words
// ---------------------------------------------------------------------------
void pack_bits_u32(const int32_t* ids, int64_t n, int nb, uint32_t* out,
                   int64_t n_words) {
    std::memset(out, 0, n_words * sizeof(uint32_t));
    uint64_t acc = 0;      // bit accumulator, low bits first
    int fill = 0;          // bits currently in acc
    int64_t w = 0;
    for (int64_t i = 0; i < n; ++i) {
        acc |= (uint64_t)(uint32_t)ids[i] << fill;
        fill += nb;
        while (fill >= 32) {
            out[w++] = (uint32_t)acc;
            acc >>= 32;
            fill -= 32;
        }
    }
    if (fill > 0 && w < n_words) out[w] = (uint32_t)acc;
}

// inverse of pack_bits_u32: dense little-endian bitstream -> ids
void unpack_bits_u32(const uint32_t* words, int64_t n_words, int nb,
                     int64_t n, int32_t* out) {
    uint64_t acc = 0;
    int fill = 0;
    int64_t w = 0;
    const uint32_t mask = (nb >= 32) ? 0xFFFFFFFFu
                                     : ((1u << nb) - 1u);
    for (int64_t i = 0; i < n; ++i) {
        while (fill < nb && w < n_words) {
            acc |= (uint64_t)words[w++] << fill;
            fill += 32;
        }
        out[i] = (int32_t)(acc & mask);
        acc >>= nb;
        fill -= nb;
    }
}

// ---------------------------------------------------------------------------
// group_index_i64: row keys -> per-row group ranks (sorted-key order) +
// sorted unique keys. Open-addressing hash (splitmix64 mix), then the
// unique set (tiny vs n) is sorted and ranks remapped.
// Returns g (number of groups), or -1 on alloc failure.
// ---------------------------------------------------------------------------
static inline uint64_t mix64(uint64_t x) {
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

int64_t group_index_i64(const int64_t* key, int64_t n,
                        int64_t* uniq_out, int32_t* rank_out) {
    if (n <= 0) return 0;
    uint64_t cap = 1;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int64_t> tkey;
    std::vector<int32_t> tgid;
    try {
        tkey.assign(cap, INT64_MIN);     // INT64_MIN = empty sentinel
        tgid.assign(cap, -1);
    } catch (...) { return -1; }
    const uint64_t mask = cap - 1;
    int64_t ng = 0;
    // pass 1: assign provisional group ids in first-seen order
    for (int64_t i = 0; i < n; ++i) {
        int64_t k = key[i];
        uint64_t h = mix64((uint64_t)k) & mask;
        for (;;) {
            if (tkey[h] == k) { rank_out[i] = tgid[h]; break; }
            if (tkey[h] == INT64_MIN) {
                tkey[h] = k;
                tgid[h] = (int32_t)ng;
                uniq_out[ng] = k;
                rank_out[i] = (int32_t)ng;
                ++ng;
                break;
            }
            h = (h + 1) & mask;
        }
    }
    // sort unique keys, remap provisional ids -> sorted ranks
    std::vector<int32_t> order((size_t)ng);
    for (int64_t i = 0; i < ng; ++i) order[i] = (int32_t)i;
    std::sort(order.begin(), order.end(),
              [&](int32_t a, int32_t b) { return uniq_out[a] < uniq_out[b]; });
    std::vector<int32_t> rank_of((size_t)ng);
    std::vector<int64_t> sorted((size_t)ng);
    for (int64_t r = 0; r < ng; ++r) {
        rank_of[order[r]] = (int32_t)r;
        sorted[r] = uniq_out[order[r]];
    }
    std::memcpy(uniq_out, sorted.data(), (size_t)ng * sizeof(int64_t));
    for (int64_t i = 0; i < n; ++i) rank_out[i] = rank_of[rank_out[i]];
    return ng;
}

// ---------------------------------------------------------------------------
// grouped stats: one pass accumulating count/sum/min/max per group
// ---------------------------------------------------------------------------
void group_counts_i64(const int32_t* rank, int64_t n, int64_t g,
                      int64_t* counts) {
    std::memset(counts, 0, (size_t)g * sizeof(int64_t));
    for (int64_t i = 0; i < n; ++i) counts[rank[i]]++;
}

void group_stats_f64(const int32_t* rank, const double* vals, int64_t n,
                     int64_t g, double* sums, double* mins, double* maxs) {
    for (int64_t j = 0; j < g; ++j) {
        sums[j] = 0.0;
        mins[j] = 1e308 * 10;            // +inf
        maxs[j] = -1e308 * 10;           // -inf
    }
    for (int64_t i = 0; i < n; ++i) {
        int32_t r = rank[i];
        double v = vals[i];
        sums[r] += v;
        if (v < mins[r]) mins[r] = v;
        if (v > maxs[r]) maxs[r] = v;
    }
}

// grouped stats over an argsort permutation: one pass fusing the gather
// (vals[order]) with sum/min/max accumulation per run — replaces a 64MB
// materialized gather plus three reduceat passes
void group_stats_sorted_f64(const int64_t* order, const int64_t* starts,
                            int64_t g, int64_t n, const double* vals,
                            double* sums, double* mins, double* maxs) {
    for (int64_t j = 0; j < g; ++j) {
        int64_t e = (j + 1 < g) ? starts[j + 1] : n;
        double s = 0.0, mn = 1e308 * 10, mx = -1e308 * 10;
        for (int64_t i = starts[j]; i < e; ++i) {
            double v = vals[order[i]];
            s += v;
            if (v < mn) mn = v;
            if (v > mx) mx = v;
        }
        sums[j] = s;
        mins[j] = mn;
        maxs[j] = mx;
    }
}

// mixed-radix packed key construction: key = ((d0*c1)+d1)*c2+d2 ... in one
// pass (numpy pays 2 full passes per dimension)
void packed_key_i64(const int32_t* const* dims, const int64_t* cards,
                    int n_dims, int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t k = 0;
        for (int d = 0; d < n_dims; ++d) k = k * cards[d] + dims[d][i];
        out[i] = k;
    }
}

}  // extern "C"
