"""Component microbenchmarks (the pinot-perf JMH analogue).

Parity: pinot-perf/src/main/java/.../perf/ — BenchmarkOfflineIndexReader,
RawIndexBenchmark, dictionary benchmarks, BenchmarkRealtimeConsumptionSpeed
(SURVEY.md §6). Each benchmark times one storage/engine component in
isolation and reports a JSON line {"bench", "value", "unit"}; `run_all`
returns the records (and the CLI prints them). Sizes are parameters so CI
smoke runs stay fast while full runs use realistic scales.
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, List

import numpy as np


def _rate(n: int, fn: Callable[[], None], reps: int = 3) -> float:
    """ops (rows) per second, median of reps."""
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return n / float(np.median(ts))


def bench_dictionary_encode(n: int = 1_000_000, card: int = 1000) -> dict:
    """SegmentDictionaryCreator path: string column → sorted dict + ids."""
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.segment.dictionary import Dictionary
    rng = np.random.default_rng(0)
    pool = np.array([f"value_{i:06d}" for i in range(card)], dtype=object)
    col = pool[rng.integers(0, card, n)]
    rate = _rate(n, lambda: Dictionary.build_encoded(DataType.STRING, col))
    return {"bench": "dictionary_encode_string", "value": round(rate),
            "unit": "rows/s"}


def bench_fwd_pack_unpack(n: int = 4_000_000, bits: int = 13) -> dict:
    """FixedBitSingleValueReader/Writer path: pack + unpack round-trip."""
    from pinot_tpu.segment.fwd import pack_bits, unpack_bits
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1 << bits, n).astype(np.int32)
    rate = _rate(n, lambda: unpack_bits(pack_bits(ids, bits), bits, n))
    return {"bench": "fwd_bitpack_roundtrip", "value": round(rate),
            "unit": "rows/s"}


def bench_inverted_lookup(n: int = 2_000_000, card: int = 500,
                          lookups: int = 200) -> dict:
    """BitmapInvertedIndexReader path: posting-list fetches."""
    from pinot_tpu.segment.inverted import InvertedIndexWriter
    import os
    import tempfile
    rng = np.random.default_rng(0)
    ids = rng.integers(0, card, n).astype(np.int32)
    with tempfile.TemporaryDirectory() as d:
        InvertedIndexWriter.write(d, "c", ids, card)
        from pinot_tpu.segment.inverted import InvertedIndexReader
        inv = InvertedIndexReader.load(d, "c", n)
        keys = rng.integers(0, card, lookups)
        rate = _rate(lookups, lambda: [inv.postings(int(k))
                                       for k in keys])
    return {"bench": "inverted_posting_lookup", "value": round(rate),
            "unit": "lookups/s"}


def bench_segment_build(rows: int = 1_000_000) -> dict:
    """SegmentIndexCreationDriverImpl path: full SSB segment build."""
    import tempfile

    from pinot_tpu.tools.datagen import build_ssb_segment_dirs
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        build_ssb_segment_dirs(d, rows, 1, seed=1, star_tree=True)
        dt = time.perf_counter() - t0
    return {"bench": "segment_build_ssb", "value": round(rows / dt),
            "unit": "rows/s"}


def bench_realtime_consumption(rows: int = 50_000) -> dict:
    """BenchmarkRealtimeConsumptionSpeed analogue: MutableSegmentImpl
    index_row throughput."""
    from pinot_tpu.common.schema import (Schema, dimension, metric)
    from pinot_tpu.common.datatype import DataType
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
    schema = Schema("t", [dimension("d1", DataType.STRING),
                          dimension("d2", DataType.INT),
                          metric("m1", DataType.LONG)])
    rng = np.random.default_rng(0)
    rws = [{"d1": f"v{int(rng.integers(0, 100))}",
            "d2": int(rng.integers(0, 1000)),
            "m1": int(rng.integers(0, 10_000))} for _ in range(rows)]

    def run():
        seg = MutableSegmentImpl(schema, TableConfig("t"), "s")
        for r in rws:
            seg.index_row(r)
    rate = _rate(rows, run)
    return {"bench": "realtime_index_row", "value": round(rate),
            "unit": "rows/s"}


def bench_startree_prefix_descent(rows: int = 2_000_000) -> dict:
    """StarTree query path: prefix-descent block narrowing vs cube size."""
    import tempfile

    from pinot_tpu.pql.optimizer import BrokerRequestOptimizer
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    from pinot_tpu.tools.datagen import build_ssb_segment_dirs
    with tempfile.TemporaryDirectory() as d:
        dirs, _, _ = build_ssb_segment_dirs(d, rows, 1, seed=2,
                                            star_tree=True)
        seg = ImmutableSegmentLoader.load(dirs[0])
        req = BrokerRequestOptimizer().optimize(compile_pql(
            "SELECT SUM(lo_revenue) FROM lineorder WHERE c_nation = "
            "'UNITED STATES' AND s_nation = 'UNITED STATES' GROUP BY "
            "c_city, s_city, d_year TOP 10000 "
            "OPTION(numGroupsLimit=4194304)"))
        ex = ServerQueryExecutor()
        ex.execute(req, [seg])
        n_q = 20
        rate = _rate(n_q, lambda: [ex.execute(req, [seg])
                                   for _ in range(n_q)])
    return {"bench": "startree_prefix_group_by", "value": round(rate, 1),
            "unit": "queries/s"}


BENCHES: Dict[str, Callable[..., dict]] = {
    "dictionary_encode": bench_dictionary_encode,
    "fwd_pack_unpack": bench_fwd_pack_unpack,
    "inverted_lookup": bench_inverted_lookup,
    "segment_build": bench_segment_build,
    "realtime_consumption": bench_realtime_consumption,
    "startree_prefix_descent": bench_startree_prefix_descent,
}


def _scaled_kwargs(fn: Callable[..., dict], scale: float) -> dict:
    """Scale a bench's n/rows defaults (floor 1000) — ONE rule shared by
    run_all and the CLI so recorded and CLI numbers stay comparable."""
    import inspect
    kw = {}
    for pname, p in inspect.signature(fn).parameters.items():
        if pname in ("n", "rows") and isinstance(p.default, int):
            kw[pname] = max(1000, int(p.default * scale))
    return kw


def run_all(scale: float = 1.0) -> List[dict]:
    """Run every microbenchmark; `scale` multiplies row counts (CI smoke
    uses ~0.01)."""
    return [fn(**_scaled_kwargs(fn, scale)) for fn in BENCHES.values()]


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="component microbenchmarks")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--only", choices=sorted(BENCHES), default=None)
    args = ap.parse_args(argv)
    benches = {args.only: BENCHES[args.only]} if args.only else BENCHES
    for fn in benches.values():
        print(json.dumps(fn(**_scaled_kwargs(fn, args.scale))),
              flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
