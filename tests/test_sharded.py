"""Mesh-sharded multi-segment execution tests (8 virtual devices).

Mirrors the reference's CombineOperator/CombineGroupByOperator correctness
expectations: sharded execution must return exactly the same answers as the
sequential per-segment path / the numpy oracle.
"""
import os
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, build_shared_segments
from oracle import Oracle

from pinot_tpu.engine import QueryEngine
from pinot_tpu.parallel import (NotShardable, ShardedQueryExecutor,
                                make_mesh)
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.query.reduce import BrokerReduceService


@pytest.fixture(scope="module")
def cluster():
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, n_segs=8, n=2048)
    mesh = make_mesh()
    return segs, Oracle(merged), mesh


def _reduce(request, block):
    return BrokerReduceService().reduce(request, [block])


def _run(sharded, segs, pql):
    request = compile_pql(pql)
    return _reduce(request, sharded.execute(request, segs))


def test_mesh_has_8_devices(cluster):
    _, _, mesh = cluster
    assert mesh.devices.size == 8


def test_sharded_count_sum_avg(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: r["yearID"] >= 2000)
    resp = _run(sharded, segs,
                "SELECT COUNT(*), SUM(runs), AVG(hits) FROM baseballStats "
                "WHERE yearID >= 2000")
    assert resp.aggregation_results[0].value == str(oracle.count(m))
    assert float(resp.aggregation_results[1].value) == pytest.approx(
        oracle.sum("runs", m))
    assert float(resp.aggregation_results[2].value) == pytest.approx(
        oracle.avg("hits", m), rel=1e-9)
    assert resp.num_segments_processed == 8


def test_sharded_min_max_range(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: r["teamID"] == "BOS")
    resp = _run(sharded, segs,
                "SELECT MIN(runs), MAX(runs), MINMAXRANGE(hits) "
                "FROM baseballStats WHERE teamID = 'BOS'")
    assert float(resp.aggregation_results[0].value) == oracle.min("runs", m)
    assert float(resp.aggregation_results[1].value) == oracle.max("runs", m)
    assert float(resp.aggregation_results[2].value) == \
        oracle.minmaxrange("hits", m)


def test_sharded_raw_column_aggs(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: r["league"] == "NL")
    resp = _run(sharded, segs,
                "SELECT SUM(salary), MIN(salary), MAX(salary) "
                "FROM baseballStats WHERE league = 'NL'")
    assert float(resp.aggregation_results[0].value) == pytest.approx(
        oracle.sum("salary", m), rel=1e-6)
    assert float(resp.aggregation_results[1].value) == pytest.approx(
        oracle.min("salary", m))
    assert float(resp.aggregation_results[2].value) == pytest.approx(
        oracle.max("salary", m))


def test_sharded_distinctcount_percentile(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: r["yearID"] < 2005)
    resp = _run(sharded, segs,
                "SELECT DISTINCTCOUNT(playerName), PERCENTILE90(runs) "
                "FROM baseballStats WHERE yearID < 2005")
    assert int(resp.aggregation_results[0].value) == \
        oracle.distinctcount("playerName", m)
    assert float(resp.aggregation_results[1].value) == pytest.approx(
        oracle.percentile("runs", m, 90))


def test_sharded_group_by(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: r["runs"] > 50)
    expected = oracle.group_by(["teamID", "league"], m,
                               ("sum", "hits"))
    resp = _run(sharded, segs,
                "SELECT SUM(hits) FROM baseballStats WHERE runs > 50 "
                "GROUP BY teamID, league TOP 1000")
    got = {tuple(g["group"]): float(g["value"])
           for g in resp.aggregation_results[0].group_by_result}
    assert got == {k: pytest.approx(v) for k, v in expected.items()}


def test_sharded_group_by_min_max_avg(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: True)
    for agg, okind in [("MIN(runs)", ("min", "runs")),
                       ("MAX(runs)", ("max", "runs")),
                       ("AVG(runs)", ("avg", "runs")),
                       ("COUNT(*)", ("count", None))]:
        expected = oracle.group_by(["league"], m, okind)
        resp = _run(sharded, segs,
                    f"SELECT {agg} FROM baseballStats GROUP BY league")
        got = {tuple(g["group"]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert got == {k: pytest.approx(v) for k, v in expected.items()}, agg


def test_sharded_mv_aggregation(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    m = oracle.mask(lambda r: "P" in r["position"])
    resp = _run(sharded, segs,
                "SELECT COUNT(*) FROM baseballStats WHERE position = 'P'")
    assert resp.aggregation_results[0].value == str(oracle.count(m))


def test_sharded_selection_limit_and_order(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    resp = _run(sharded, segs,
                "SELECT teamID, runs FROM baseballStats "
                "WHERE league = 'AL' ORDER BY runs DESC LIMIT 20")
    assert len(resp.selection_results.results) == 20
    got_runs = [int(r[1]) for r in resp.selection_results.results]
    m = oracle.mask(lambda r: r["league"] == "AL")
    expected = sorted(oracle.vals("runs", m), reverse=True)[:20]
    assert got_runs == [int(v) for v in expected]


def test_sharded_matches_sequential_engine(cluster):
    segs, oracle, mesh = cluster
    dev = QueryEngine(segs)
    sharded_engine = QueryEngine(segs, mesh=mesh)
    for pql in [
        "SELECT COUNT(*) FROM baseballStats WHERE teamID IN ('BOS','NYA')",
        "SELECT SUM(runs), MAX(hits) FROM baseballStats WHERE runs "
        "BETWEEN 10 AND 90",
        "SELECT AVG(average) FROM baseballStats GROUP BY teamID TOP 100",
    ]:
        a = dev.query(pql).to_json()
        b = sharded_engine.query(pql).to_json()
        assert a.get("selectionResults") == b.get("selectionResults"), pql
        ar, br = a.get("aggregationResults"), b.get("aggregationResults")
        assert (ar is None) == (br is None), pql
        for fa, fb in zip(ar or [], br or []):
            assert fa["function"] == fb["function"], pql
            if "groupByResult" in fa:
                ga = {tuple(g["group"]): float(g["value"])
                      for g in fa["groupByResult"]}
                gb = {tuple(g["group"]): float(g["value"])
                      for g in fb["groupByResult"]}
                # values may differ in the last ulp (f64 summation order
                # differs between per-segment dots and the psum'd histogram)
                assert ga.keys() == gb.keys(), pql
                for k in ga:
                    assert gb[k] == pytest.approx(ga[k], rel=1e-12), (pql, k)
            else:
                assert float(fb["value"]) == pytest.approx(
                    float(fa["value"]), rel=1e-12), pql


@pytest.fixture(scope="module")
def hetero():
    """Independently built segments — per-segment dictionaries, the way
    the real storage path always produces them (reference: every segment
    gets its own SegmentDictionaryCreator output)."""
    base = tempfile.mkdtemp()
    segs, all_cols = [], []
    for i in range(4):
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d)
        seg, cols = build_segment(d, n=1024, seed=i, name=f"h{i}")
        segs.append(seg)
        all_cols.append(cols)
    merged = {k: np.concatenate([c[k] for c in all_cols])
              for k in all_cols[0] if k != "position"}
    merged["position"] = sum((list(c["position"]) for c in all_cols), [])
    return segs, all_cols, Oracle(merged)


def test_heterogeneous_dictionaries_union_sharded(hetero):
    """Independently built segments (necessarily different dictionary
    subsets per segment) run on the DEVICE combine path via the stack-time
    union-dictionary remap — the value-domain merge of the reference's
    CombineGroupByOperator moved to stack time."""
    segs, _, oracle = hetero
    sharded = ShardedQueryExecutor(mesh=make_mesh())
    resp = _run(sharded, segs,
                "SELECT DISTINCTCOUNT(playerName), SUM(runs) "
                "FROM baseballStats")
    m = oracle.mask(lambda r: True)
    assert int(resp.aggregation_results[0].value) == \
        oracle.distinctcount("playerName", m)
    assert float(resp.aggregation_results[1].value) == pytest.approx(
        oracle.sum("runs", m))


def test_heterogeneous_group_by_union_sharded(hetero):
    segs, _, oracle = hetero
    sharded = ShardedQueryExecutor(mesh=make_mesh())
    m = oracle.mask(lambda r: r["runs"] > 50)
    expected = oracle.group_by(["teamID", "league"], m, ("sum", "hits"))
    resp = _run(sharded, segs,
                "SELECT SUM(hits) FROM baseballStats WHERE runs > 50 "
                "GROUP BY teamID, league TOP 1000")
    got = {tuple(g["group"]): float(g["value"])
           for g in resp.aggregation_results[0].group_by_result}
    assert got == {k: pytest.approx(v) for k, v in expected.items()}


def test_heterogeneous_selection_order_union_sharded(hetero):
    segs, _, oracle = hetero
    sharded = ShardedQueryExecutor(mesh=make_mesh())
    resp = _run(sharded, segs,
                "SELECT playerName, runs FROM baseballStats "
                "WHERE league = 'AL' ORDER BY runs DESC LIMIT 15")
    m = oracle.mask(lambda r: r["league"] == "AL")
    expected = sorted(oracle.vals("runs", m), reverse=True)[:15]
    got = [int(r[1]) for r in resp.selection_results.results]
    assert got == [int(v) for v in expected]


def test_folded_predicate_on_heterogeneous_dicts(hetero):
    """A predicate over a value present in only SOME segments'
    dictionaries constant-folds against the UNION dictionary, which is
    valid for every segment (folding against segment 0 alone was not —
    that regime used to force a NotShardable fallback)."""
    segs, all_cols, _ = hetero
    s0 = set(all_cols[0]["playerName"])
    s1 = set(all_cols[1]["playerName"])
    only1 = sorted(s1 - s0)[0]
    names = np.concatenate([c["playerName"] for c in all_cols])
    runs = np.concatenate([c["runs"] for c in all_cols])
    expected = float(runs[names != only1].sum())

    sharded = ShardedQueryExecutor(mesh=make_mesh())
    resp = _run(sharded, segs,
                f"SELECT SUM(runs) FROM baseballStats "
                f"WHERE playerName <> '{only1}'")
    assert float(resp.aggregation_results[0].value) == pytest.approx(expected)


def test_sharded_num_segments_matched():
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, n_segs=4, n=1024, seed=77)
    sharded = ShardedQueryExecutor(mesh=make_mesh())
    # match-nothing-ish filter: runs == 149 appears in every segment's
    # first-1024 enumeration? runs pool is 150 wide and n=1024 covers it,
    # so instead compare against the per-segment oracle count
    request = compile_pql(
        "SELECT COUNT(*) FROM baseballStats WHERE runs = 142 AND "
        "yearID = 1999")
    blk = sharded.execute(request, segs)
    per_seg = []
    for i in range(4):
        lo, hi = i * 1024, (i + 1) * 1024
        m = (merged["runs"][lo:hi] == 142) & (merged["yearID"][lo:hi] == 1999)
        per_seg.append(int(m.sum()))
    assert blk.stats.num_segments_matched == sum(1 for c in per_seg if c)
    assert blk.stats.num_docs_scanned == sum(per_seg)


def test_engine_falls_back_when_not_shardable():
    base = tempfile.mkdtemp()
    segs, all_cols = [], []
    for i in range(2):
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d)
        seg, cols = build_segment(d, n=1000, seed=i, name=f"f{i}")
        segs.append(seg)
        all_cols.append(cols)
    merged_runs = np.concatenate([c["runs"] for c in all_cols])
    engine = QueryEngine(segs, mesh=make_mesh())
    resp = engine.query("SELECT SUM(runs) FROM baseballStats")
    assert float(resp.aggregation_results[0].value) == pytest.approx(
        float(merged_runs.sum()))


def test_stack_cache_canonical_key_lru_and_evict(cluster):
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh, max_stacks=2)
    pql = "SELECT SUM(runs) FROM baseballStats WHERE yearID >= 1980"
    # different orderings of the same segment set share one stack
    _run(sharded, segs, pql)
    _run(sharded, list(reversed(segs)), pql)
    assert len(sharded._stacks) == 1
    # distinct subsets get distinct stacks, bounded by max_stacks (LRU)
    _run(sharded, segs[:4] + segs[4:], pql)  # same set again → still 1
    st_full = next(iter(sharded._stacks.values()))
    request = compile_pql(pql)
    sharded.execute(request, segs[:4])
    sharded.execute(request, segs[4:])
    assert len(sharded._stacks) == 2  # full-set stack evicted by LRU
    assert st_full not in sharded._stacks.values()
    # explicit eviction drops every stack containing the segment
    sharded.evict_segment(segs[0].segment_name)
    assert all(segs[0].segment_name not in k for k in sharded._stacks)


def test_stack_rebuilds_on_segment_refresh(cluster):
    import copy
    segs, oracle, mesh = cluster
    sharded = ShardedQueryExecutor(mesh=mesh)
    pql = "SELECT SUM(runs) FROM baseballStats WHERE yearID >= 1980"
    _run(sharded, segs, pql)
    st0 = next(iter(sharded._stacks.values()))
    # same names, one replaced object (refresh) → rebuild, not stale hit
    refreshed = list(segs)
    refreshed[3] = copy.copy(segs[3])
    _run(sharded, refreshed, pql)
    st1 = next(iter(sharded._stacks.values()))
    assert st1 is not st0


def test_data_manager_removal_listener_evicts_stack():
    from pinot_tpu.server import ServerInstance
    base = tempfile.mkdtemp()
    segs, merged = build_shared_segments(base, n_segs=4, n=1024, seed=5)
    server = ServerInstance(mesh=make_mesh())
    tdm = server.data_manager.table("baseballStats_OFFLINE", create=True)
    for s in segs:
        tdm.add_segment(s)
    request = compile_pql(
        "SELECT SUM(runs) FROM baseballStats WHERE yearID >= 1980")
    server.executor.sharded.execute(request, segs)
    assert len(server.executor.sharded._stacks) == 1
    tdm.remove_segment(segs[0].segment_name)
    assert len(server.executor.sharded._stacks) == 0
    server.data_manager.shutdown()
