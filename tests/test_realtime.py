"""Realtime ingestion end-to-end tests.

Mirrors the reference's LLCRealtimeClusterIntegrationTest /
HybridClusterIntegrationTest / FlakyConsumerRealtimeClusterIntegrationTest
and SegmentCompletionIntegrationTests: an embedded cluster consuming from an
in-process stream — queryable mid-consumption, committed through the
completion FSM, correct across the hybrid time-boundary flip, tolerant of
flaky consumers, and repairable after server death.
"""
import os
import tempfile
import time

import numpy as np
import pytest

from fixtures import make_columns, make_schema, make_table_config

from pinot_tpu.common.table_config import (IndexingConfig, SegmentsConfig,
                                           TableConfig, TableType)
from pinot_tpu.controller.realtime_manager import DONE, IN_PROGRESS
from pinot_tpu.realtime import registry
from pinot_tpu.realtime.segment_name import LLCSegmentName
from pinot_tpu.realtime.stream import (FlakyConsumerFactory, MemoryStream,
                                       MemoryStreamConsumerFactory)
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.tools.cluster import EmbeddedCluster

RT_TABLE = "baseballStats_REALTIME"


def make_rows(n, seed=0):
    cols = make_columns(n, seed)
    return [{
        "teamID": str(cols["teamID"][i]),
        "league": str(cols["league"][i]),
        "playerName": str(cols["playerName"][i]),
        "position": [str(x) for x in cols["position"][i]],
        "runs": int(cols["runs"][i]),
        "hits": int(cols["hits"][i]),
        "average": float(cols["average"][i]),
        "salary": float(cols["salary"][i]),
        "yearID": int(cols["yearID"][i]),
    } for i in range(n)]


def rt_config(factory_name, topic, flush_rows=100_000, replication=1):
    idx = IndexingConfig(
        no_dictionary_columns=["salary"],
        stream_configs={
            "stream.factory.name": factory_name,
            "stream.topic.name": topic,
            "realtime.segment.flush.threshold.size": str(flush_rows),
            "realtime.segment.flush.threshold.time.ms": "600000000",
        })
    return TableConfig(
        "baseballStats", table_type=TableType.REALTIME,
        indexing_config=idx,
        segments_config=SegmentsConfig(replication=replication,
                                       time_column_name="yearID"))


def wait_until(cond, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:  # noqa: BLE001 — condition not ready yet
            pass
        time.sleep(interval)
    return False


def count_star(cluster):
    resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
    if resp.exceptions:
        return -1
    return int(resp.aggregation_results[0].value)


def done_segments(cluster):
    mgr = cluster.controller.manager
    return [s for s in mgr.segment_names(RT_TABLE)
            if (mgr.segment_metadata(RT_TABLE, s) or {}).get("status")
            == DONE]


@pytest.fixture
def work_dir():
    return tempfile.mkdtemp()


def test_realtime_consume_query_commit_requery(work_dir):
    stream = MemoryStream("topic_e2e", num_partitions=2)
    registry.register_stream_factory(
        "mem_e2e", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_e2e", "topic_e2e", flush_rows=400))
        rows = make_rows(1000, seed=3)

        # phase 1: below the flush threshold — queryable mid-consumption
        for i, r in enumerate(rows[:300]):
            stream.publish(r, partition=i % 2)
        assert wait_until(lambda: count_star(cluster) == 300)
        exp_sum = sum(r["runs"] for r in rows[:300])
        resp = cluster.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_sum

        # phase 2: cross the threshold — segments commit, consumption rolls
        # over to the next sequence, nothing is lost or duplicated
        for i, r in enumerate(rows[300:]):
            stream.publish(r, partition=(300 + i) % 2)
        assert wait_until(lambda: len(done_segments(cluster)) >= 2)
        assert wait_until(lambda: count_star(cluster) == 1000)
        exp_sum = sum(r["runs"] for r in rows)
        resp = cluster.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_sum

        # committed metadata is consistent and durable (checkpoint story):
        # DONE segments have artifacts; successor starts at the end offset
        mgr = cluster.controller.manager
        for name in done_segments(cluster):
            meta = mgr.segment_metadata(RT_TABLE, name)
            assert os.path.isdir(meta["downloadPath"])
            assert meta["totalDocs"] > 0
            nxt = LLCSegmentName.parse(name).next()
            nxt_meta = mgr.segment_metadata(RT_TABLE, nxt.name)
            assert nxt_meta is not None
            assert nxt_meta["startOffset"] == meta["endOffset"]
            assert nxt_meta["status"] == IN_PROGRESS
        # ideal state: committed → ONLINE, successors → CONSUMING
        ideal = cluster.controller.coordinator.ideal_state(RT_TABLE)
        for name in done_segments(cluster):
            assert set(ideal[name].values()) == {"ONLINE"}
            nxt = LLCSegmentName.parse(name).next()
            assert set(ideal[nxt.name].values()) == {"CONSUMING"}
    finally:
        cluster.stop()


def test_completion_fsm_two_replicas(work_dir):
    """Two replicas consume the same partition; one commits, the loser
    discards and downloads the committed copy (SegmentCompletionManager
    parity: winner election + loser download path)."""
    stream = MemoryStream("topic_repl", num_partitions=1)
    registry.register_stream_factory(
        "mem_repl", MemoryStreamConsumerFactory(stream, batch_size=50))
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_repl", "topic_repl",
                                    flush_rows=500, replication=2))
        rows = make_rows(600, seed=11)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: len(done_segments(cluster)) >= 1)
        assert wait_until(lambda: count_star(cluster) == 600)
        seg0 = "baseballStats__0__0"
        # both replicas should end up serving the committed immutable copy
        def both_immutable():
            for server in cluster.servers.values():
                tdm = server.data_manager.table(RT_TABLE)
                if tdm is None or seg0 not in tdm.segment_names():
                    return False
                acquired, _ = tdm.acquire_segments([seg0])
                try:
                    if getattr(acquired[0].segment, "is_mutable", False):
                        return False
                finally:
                    for sdm in acquired:
                        tdm.release_segment(sdm)
            return True
        assert wait_until(both_immutable)
        exp_sum = sum(r["runs"] for r in rows)
        resp = cluster.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_sum
    finally:
        cluster.stop()


def test_flaky_consumer_recovers(work_dir):
    """Parity: FlakyConsumerRealtimeClusterIntegrationTest — consumer that
    randomly throws and corrupts payloads must not stop ingestion; garbage
    messages are dropped, exceptions retried."""
    stream = MemoryStream("topic_flaky", num_partitions=1)
    inner = MemoryStreamConsumerFactory(stream, batch_size=40)
    registry.register_stream_factory(
        "mem_flaky", FlakyConsumerFactory(inner, seed=7))
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_flaky", "topic_flaky",
                                    flush_rows=200))
        rows = make_rows(500, seed=5)
        for r in rows:
            stream.publish(r, partition=0)
        # ingestion keeps making progress through failures: segments commit
        # and (almost) all rows land — only corrupted payloads may be lost
        assert wait_until(lambda: len(done_segments(cluster)) >= 1)
        assert wait_until(lambda: count_star(cluster) >= 400)
        # the consumer fully drains the stream (offset reaches the end)
        mgr = cluster.controller.manager
        def drained():
            latest = max((LLCSegmentName.parse(s) for s in
                          mgr.segment_names(RT_TABLE)),
                         key=lambda l: l.sequence)
            meta = mgr.segment_metadata(RT_TABLE, latest.name) or {}
            start = int(meta.get("startOffset", 0))
            state = cluster.participants["Server_0"].realtime
            rdm = state._consuming.get(latest.name)
            off = rdm.offset if rdm is not None else start
            return off >= 500
        assert wait_until(drained)
    finally:
        cluster.stop()


def test_hybrid_time_boundary_across_commit(work_dir):
    """Hybrid table: offline segment + realtime stream; the time-boundary
    split must stay correct before and after realtime segments commit."""
    stream = MemoryStream("topic_hybrid", num_partitions=1)
    registry.register_stream_factory(
        "mem_hybrid", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        # offline side
        cluster.add_table(make_table_config())
        off_cols = make_columns(2000, seed=21)
        seg_dir = os.path.join(work_dir, "offline_seg")
        os.makedirs(seg_dir)
        SegmentCreator(make_schema(), make_table_config(),
                       segment_name="off_0").build(off_cols, seg_dir)
        cluster.upload_segment("baseballStats_OFFLINE", seg_dir)
        # realtime side
        cluster.add_table(rt_config("mem_hybrid", "topic_hybrid",
                                    flush_rows=400))
        rt_rows = make_rows(600, seed=22)
        for r in rt_rows:
            stream.publish(r, partition=0)

        boundary = int(off_cols["yearID"].max()) - 1
        exp = int((off_cols["yearID"] <= boundary).sum()) + \
            sum(1 for r in rt_rows if r["yearID"] > boundary)
        assert wait_until(lambda: count_star(cluster) == exp), \
            (count_star(cluster), exp)
        # after the flush threshold commits a realtime segment, the same
        # answer must hold (committed + consuming, no dup/loss at the flip)
        assert wait_until(lambda: len(done_segments(cluster)) >= 1)
        assert count_star(cluster) == exp
        exp_sum = int(off_cols["runs"][off_cols["yearID"] <= boundary]
                      .sum()) + \
            sum(r["runs"] for r in rt_rows if r["yearID"] > boundary)
        resp = cluster.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_sum
    finally:
        cluster.stop()


def test_consuming_repair_after_server_death(work_dir):
    """Parity: RealtimeSegmentValidationManager.ensureAllPartitionsConsuming
    — a dead server's consuming partition is reassigned and consumption
    resumes from the durable start offset (no data loss: stream replay)."""
    stream = MemoryStream("topic_repair", num_partitions=2)
    registry.register_stream_factory(
        "mem_repair", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_repair", "topic_repair",
                                    flush_rows=100_000))
        rows = make_rows(400, seed=31)
        for i, r in enumerate(rows):
            stream.publish(r, partition=i % 2)
        assert wait_until(lambda: count_star(cluster) == 400)

        # find a server owning a consuming partition and kill it
        ideal = cluster.controller.coordinator.ideal_state(RT_TABLE)
        victim = sorted(ideal["baseballStats__1__0"])[0]
        cluster.participants[victim].shutdown()
        cluster.controller.coordinator.deregister_participant(victim)
        # partial data while partition 1 is dark
        assert wait_until(lambda: 0 < count_star(cluster) < 400)

        # repair: reassign the consuming segment to a live server
        cluster.controller.realtime.ensure_all_partitions_consuming()
        assert wait_until(lambda: count_star(cluster) == 400)
        exp_sum = sum(r["runs"] for r in rows)
        resp = cluster.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp_sum
    finally:
        cluster.stop()


def test_stopped_consumer_repaired_on_live_server(work_dir):
    """A consumer that dies in ERROR on a live server reports
    stoppedConsuming; the validation task must bounce and reassign the
    partition (liveness alone can't detect it)."""
    stream = MemoryStream("topic_err", num_partitions=1)
    registry.register_stream_factory(
        "mem_err", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_err", "topic_err"))
        rows = make_rows(200, seed=41)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: count_star(cluster) == 200)

        # simulate a fatal consumer error (e.g. build failure)
        rt = cluster.participants["Server_0"].realtime
        rdm = rt._consuming["baseballStats__0__0"]
        rdm._stop.set()
        rdm._enter_error("simulated build failure")
        meta = cluster.controller.manager.segment_metadata(
            RT_TABLE, "baseballStats__0__0")
        assert meta.get("stoppedInstances") == ["Server_0"]

        # repair bounces the partition; consumption restarts from offset 0
        cluster.controller.realtime.ensure_all_partitions_consuming()
        for r in make_rows(100, seed=42):
            stream.publish(r, partition=0)
        assert wait_until(lambda: count_star(cluster) == 300)
        meta = cluster.controller.manager.segment_metadata(
            RT_TABLE, "baseballStats__0__0")
        assert "stoppedInstances" not in meta
    finally:
        cluster.stop()


def test_query_consistency_under_concurrent_ingestion(work_dir):
    """Queries racing the consumer thread must never error or see torn
    state: COUNT(*) and SUM over a snapshot are mutually consistent."""
    import threading

    stream = MemoryStream("topic_race", num_partitions=1)
    registry.register_stream_factory(
        "mem_race", MemoryStreamConsumerFactory(stream, batch_size=16))
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_race", "topic_race"))
        rows = [{"teamID": "BOS", "league": "AL", "playerName": f"p{i}",
                 "position": ["P"], "runs": 1, "hits": 1, "average": 0.5,
                 "salary": 1.0, "yearID": 2000} for i in range(3000)]

        stop = threading.Event()

        def publisher():
            for r in rows:
                stream.publish(r, partition=0)
                if stop.is_set():
                    return

        t = threading.Thread(target=publisher)
        t.start()
        try:
            for _ in range(60):
                resp = cluster.query(
                    "SELECT COUNT(*), SUM(runs) FROM baseballStats "
                    "WHERE teamID = 'BOS'")
                assert not resp.exceptions, resp.exceptions
                if resp.aggregation_results:
                    cnt = int(resp.aggregation_results[0].value)
                    s = float(resp.aggregation_results[1].value)
                    # runs == 1 per row → SUM must equal COUNT in any
                    # consistent snapshot (zero rows → SUM is -inf, the
                    # reference's empty-SUM default)
                    if cnt > 0:
                        assert s == cnt, (s, cnt)
        finally:
            stop.set()
            t.join()
        assert wait_until(lambda: count_star(cluster) == 3000)
    finally:
        cluster.stop()


# -- HLC (high-level consumer) path -----------------------------------------

def test_hlc_consume_flush_checkpoint_resume(work_dir):
    """Parity: HLRealtimeSegmentDataManager — group consumer, local
    segment flush (no completion FSM), durable checkpoint AFTER the
    flush, resume from the checkpoint replaying only unflushed rows."""
    from pinot_tpu.controller.property_store import PropertyStore
    from pinot_tpu.engine import QueryEngine
    from pinot_tpu.realtime.hlc import HLRealtimeSegmentDataManager
    from pinot_tpu.realtime.stream import JsonMessageDecoder, StreamConfig
    from pinot_tpu.server.data_manager import TableDataManager

    stream = MemoryStream("rsvp", num_partitions=2)
    factory = MemoryStreamConsumerFactory(stream, batch_size=200)
    scfg = StreamConfig(topic="rsvp", consumer_factory=factory,
                        decoder=JsonMessageDecoder(),
                        flush_threshold_rows=1000)
    store = PropertyStore()
    tdm = TableDataManager(RT_TABLE)
    rows = make_rows(2500, seed=3)
    for r in rows:
        stream.publish(r)

    def total_docs(t):
        sdms, _ = t.acquire_segments()
        try:
            return sum(s.segment.num_docs for s in sdms)
        finally:
            for s in sdms:
                t.release_segment(s)

    mgr = HLRealtimeSegmentDataManager(
        RT_TABLE, make_schema(), rt_config("unused", "rsvp"), scfg,
        group_id="g1", store=store, table_data_manager=tdm,
        instance_id="Server_0", work_dir=os.path.join(work_dir, "a"))
    try:
        deadline = time.time() + 30
        while time.time() < deadline and (mgr.segments_flushed < 2 or
                                          total_docs(tdm) < 2500):
            time.sleep(0.05)
        assert mgr.segments_flushed == 2
        assert total_docs(tdm) == 2500
        # HLC naming convention + flushed-vs-consuming split
        assert sorted(tdm.segment_names()) == [
            f"baseballStats__Server_0__g1__{i}" for i in range(3)]
        sdms, _ = tdm.acquire_segments()
        try:
            flushed_docs = sum(s.segment.num_docs for s in sdms
                               if not getattr(s.segment, "is_mutable",
                                              False))
            engine = QueryEngine([s.segment for s in sdms],
                                 use_device=False)
            resp = engine.query("SELECT COUNT(*) FROM baseballStats")
            assert int(resp.aggregation_results[0].value) == 2500
        finally:
            for s in sdms:
                tdm.release_segment(s)
        # the checkpoint covers exactly the FLUSHED rows
        ck = store.get(f"/CONSUMERS/{RT_TABLE}/g1")
        assert ck["sequence"] == 2
        assert sum(ck["offsets"].values()) == flushed_docs < 2500
    finally:
        mgr.stop()

    # restart with the same group + work_dir: flushed local segments
    # reload, and only the unflushed tail replays from the checkpoint —
    # no loss, no duplication
    tdm2 = TableDataManager(RT_TABLE)
    mgr2 = HLRealtimeSegmentDataManager(
        RT_TABLE, make_schema(), rt_config("unused", "rsvp"), scfg,
        group_id="g1", store=store, table_data_manager=tdm2,
        instance_id="Server_0", work_dir=os.path.join(work_dir, "a"))
    try:
        deadline = time.time() + 30
        while time.time() < deadline and total_docs(tdm2) < 2500:
            time.sleep(0.05)
        assert total_docs(tdm2) == 2500
        assert sorted(tdm2.segment_names()) == [
            f"baseballStats__Server_0__g1__{i}" for i in range(3)]
        # live rows keep flowing after the resume
        for r in make_rows(50, seed=4):
            stream.publish(r)
        while time.time() < deadline and total_docs(tdm2) < 2550:
            time.sleep(0.05)
        assert total_docs(tdm2) == 2550
    finally:
        mgr2.stop()


def test_hlc_flaky_consumer_keeps_ingesting(work_dir):
    """HLC over a flaky stream (exceptions + corrupt payloads): the
    consume loop retries and keeps flushing — ingestion never halts."""
    from pinot_tpu.controller.property_store import PropertyStore
    from pinot_tpu.realtime.hlc import HLRealtimeSegmentDataManager
    from pinot_tpu.realtime.stream import (FlakyConsumerFactory,
                                           JsonMessageDecoder, StreamConfig)
    from pinot_tpu.server.data_manager import TableDataManager

    stream = MemoryStream("rsvp_flaky", num_partitions=2)
    factory = FlakyConsumerFactory(
        MemoryStreamConsumerFactory(stream, batch_size=100), seed=5)
    scfg = StreamConfig(topic="rsvp_flaky", consumer_factory=factory,
                        decoder=JsonMessageDecoder(),
                        flush_threshold_rows=400)
    store, tdm = PropertyStore(), TableDataManager(RT_TABLE)
    for r in make_rows(1500, seed=6):
        stream.publish(r)
    mgr = HLRealtimeSegmentDataManager(
        RT_TABLE, make_schema(), rt_config("unused", "rsvp_flaky"), scfg,
        group_id="gf", store=store, table_data_manager=tdm,
        instance_id="Server_0", work_dir=os.path.join(work_dir, "f"))
    try:
        def total():
            sdms, _ = tdm.acquire_segments()
            try:
                return sum(s.segment.num_docs for s in sdms)
            finally:
                for s in sdms:
                    tdm.release_segment(s)
        assert wait_until(lambda: mgr.segments_flushed >= 2 and
                          total() >= 1200, timeout=30)
        assert store.get(f"/CONSUMERS/{RT_TABLE}/gf")["sequence"] >= 2
    finally:
        mgr.stop()


# ---------------------------------------------------------------------------
# TCP topic stream connector (cross-process SPI; parity: the Kafka 0.9
# connector proves the reference's stream SPI out-of-process —
# KafkaPartitionLevelConsumer / KafkaStreamLevelConsumer)
# ---------------------------------------------------------------------------


def test_tcp_stream_connector_spi():
    from pinot_tpu.realtime.stream import (JsonMessageDecoder, LARGEST_OFFSET,
                                           StreamConfig)
    from pinot_tpu.realtime.tcp_stream import (TcpStreamConsumerFactory,
                                               TcpTopicClient, TcpTopicServer)

    srv = TcpTopicServer()
    port = srv.start()
    try:
        srv.create_topic("unit_t", 2)
        pub = TcpTopicClient("127.0.0.1", port)
        for i in range(25):
            pub.publish_row("unit_t", {"i": i}, partition=i % 2)

        factory = TcpStreamConsumerFactory("127.0.0.1", port, batch_size=4)
        cfg = StreamConfig(topic="unit_t", consumer_factory=factory,
                           decoder=JsonMessageDecoder())

        meta = factory.create_metadata_provider(cfg)
        assert meta.partition_count() == 2
        assert meta.fetch_offset(0, LARGEST_OFFSET) == 13   # 0,2,...,24
        assert meta.fetch_offset(0, "smallest") == 0

        # LLC partition consumer: batched fetch honors start/end offsets
        c0 = factory.create_partition_consumer(cfg, 0)
        batch = c0.fetch_messages(0, None, 1000)
        assert [m.offset for m in batch.messages] == [0, 1, 2, 3]
        batch = c0.fetch_messages(batch.next_offset, 6, 1000)
        assert [m.offset for m in batch.messages] == [4, 5]
        rows = [cfg.decoder.decode(m.value) for m in batch.messages]
        assert rows == [{"i": 8}, {"i": 10}]
        c0.close()

        # HLC group consumer: drains all partitions, checkpoint resumes
        hl = factory.create_stream_consumer(cfg)
        seen = []
        while True:
            msgs = hl.next_messages(7)
            if not msgs:
                break
            seen.extend(cfg.decoder.decode(m.value)["i"] for m in msgs)
        assert sorted(seen) == list(range(25))
        ckpt = hl.checkpoint()
        hl.close()
        pub.publish_row("unit_t", {"i": 99}, partition=0)
        hl2 = factory.create_stream_consumer(cfg, checkpoint=ckpt)
        msgs = hl2.next_messages(10)
        assert [cfg.decoder.decode(m.value)["i"] for m in msgs] == [99]
        hl2.close()
        pub.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Device serving of consuming segments: periodic sorted snapshot
# (parity: consuming segments are first-class query targets on the same
# engine — MutableSegmentImpl.java:64-198; the TPU answer is a frozen
# sorted-dictionary prefix on the device kernels + a host tail)
# ---------------------------------------------------------------------------


def test_device_snapshot_frozen_tail_serving():
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.reduce import BrokerReduceService
    from pinot_tpu.pql.parser import compile_pql
    from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl

    seg = MutableSegmentImpl(make_schema(), make_table_config(), "cons_dev")
    rows = make_rows(10_000, seed=31)
    for r in rows[:9_000]:
        seg.index_row(r)

    frozen, tail = seg.device_view()
    assert frozen is not None
    assert not getattr(frozen, "is_mutable", False)
    assert frozen.num_docs >= seg.FREEZE_MIN_ROWS
    assert frozen.num_docs + tail.num_docs == 9_000
    # the frozen part's dictionaries ARE sorted (device precondition)
    fv = frozen.data_source("teamID").dictionary.values
    assert list(fv) == sorted(fv)
    n_first = frozen.num_docs

    ex = ServerQueryExecutor()
    red = BrokerReduceService()

    def ask(pql, n_rows):
        req = compile_pql(pql)
        resp = red.reduce(req, [ex.execute(req, [seg])])
        assert resp.num_segments_processed == 1   # one LOGICAL segment
        return resp

    def checks(n_rows):
        sub = rows[:n_rows]
        m = [r for r in sub if r["yearID"] >= 1990]
        resp = ask("SELECT COUNT(*), SUM(runs) FROM baseballStats "
                   "WHERE yearID >= 1990", n_rows)
        assert int(resp.aggregation_results[0].value) == len(m)
        assert float(resp.aggregation_results[1].value) == \
            float(sum(r["runs"] for r in m))
        g = ask("SELECT SUM(hits) FROM baseballStats GROUP BY league "
                "TOP 10", n_rows)
        exp = {}
        for r in sub:
            exp[r["league"]] = exp.get(r["league"], 0) + r["hits"]
        got = {x["group"][0]: float(x["value"])
               for x in g.aggregation_results[0].group_by_result}
        assert got == {k: float(v) for k, v in exp.items()}
        s = ask("SELECT playerName, runs FROM baseballStats "
                "ORDER BY runs DESC LIMIT 5", n_rows)
        exp_runs = sorted((r["runs"] for r in sub), reverse=True)[:5]
        assert [int(x[1]) for x in s.selection_results.results] == exp_runs

    checks(9_000)
    # tail grows; freeze point stays until the doubling threshold
    for r in rows[9_000:]:
        seg.index_row(r)
    checks(10_000)
    assert seg._frozen.num_docs == n_first       # 10k < 2 * n_first? no —
    # n_first == 8192+: 10_000 < 16_384, so no re-freeze yet
    # push past the doubling threshold: the snapshot refreshes
    more = make_rows(8_000, seed=32)
    for r in more:
        seg.index_row(r)
    frozen2, tail2 = seg.device_view()
    assert frozen2.num_docs == 18_000
    assert tail2.num_docs == 0
    sub = rows + more
    m = [r for r in sub if r["yearID"] >= 1990]
    resp = ask("SELECT COUNT(*) FROM baseballStats WHERE yearID >= 1990",
               18_000)
    assert int(resp.aggregation_results[0].value) == len(m)


def test_stats_history_sizes_next_segment(work_dir):
    """Parity: RealtimeSegmentStatsHistory.java:49 — completed-segment
    stats persist per table and size the NEXT consuming segment's
    initial allocations (no growth-copy ladder at steady state)."""
    from pinot_tpu.realtime.stats_history import RealtimeSegmentStatsHistory

    stream = MemoryStream("topic_sh", num_partitions=1)
    registry.register_stream_factory(
        "mem_sh", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_sh", "topic_sh",
                                    flush_rows=6000))
        rows = make_rows(7000, seed=9)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: len(done_segments(cluster)) >= 1,
                          timeout=30)
        assert wait_until(lambda: count_star(cluster) == 7000)

        rtdm = cluster.participants["Server_0"].realtime
        hist = rtdm.stats_history
        assert wait_until(lambda: len(hist.entries(RT_TABLE)) >= 1)
        entry = hist.entries(RT_TABLE)[0]
        assert entry["numRowsIndexed"] >= 6000
        assert entry["columns"]["teamID"]["cardinality"] > 0
        est = hist.estimate(RT_TABLE)
        assert est["rows"] > 4096       # above the allocation floor

        # the history is DURABLE (json on disk, atomic replace)
        reloaded = RealtimeSegmentStatsHistory(hist.path)
        assert reloaded.entries(RT_TABLE) == hist.entries(RT_TABLE)

        # the live consuming segment created AFTER the commit allocated
        # from the estimate: initial capacity >= pow2 ceiling of est rows
        def second_seg():
            for seg, rdm in rtdm._consuming.items():
                if LLCSegmentName.parse(seg).sequence >= 1:
                    return rdm
            return None
        assert wait_until(lambda: second_seg() is not None)
        rdm = second_seg()
        src = rdm.mutable._sources["teamID"]
        want = 4096
        while want < est["rows"]:
            want *= 2
        assert len(src._sv._arr) >= want > 4096, \
            (len(src._sv._arr), want)
    finally:
        cluster.stop()


def test_rebalance_preserves_consuming_segments(work_dir):
    """Regression: rebalancing a realtime table must pin in-progress LLC
    segments to their consumers (flipping them ONLINE would kill
    ingestion with 'no committed artifact')."""
    stream = MemoryStream("topic_rb", num_partitions=1)
    registry.register_stream_factory(
        "mem_rb", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=2)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_rb", "topic_rb",
                                    flush_rows=100_000))
        rows = make_rows(300, seed=4)
        for r in rows:
            stream.publish(r, partition=0)
        assert wait_until(lambda: count_star(cluster) == 300)

        target = cluster.controller.manager.rebalance_table(RT_TABLE)
        # the consuming segment kept its CONSUMING state + holders
        ideal = cluster.controller.coordinator.ideal_state(RT_TABLE)
        consuming = [s for s, m in ideal.items()
                     if "CONSUMING" in m.values()]
        assert consuming, ideal
        assert target[consuming[0]] == ideal[consuming[0]]

        # ingestion is still alive after the rebalance
        for r in make_rows(100, seed=5):
            stream.publish(r, partition=0)
        assert wait_until(lambda: count_star(cluster) == 400)
    finally:
        cluster.stop()


def test_consuming_freshness_reported(work_dir):
    """Parity: ServerQueryExecutorV1Impl's minConsumingFreshnessTimeMs /
    numConsumingSegmentsQueried — realtime queries report how fresh the
    consuming data is; offline-only queries report none."""
    stream = MemoryStream("topic_fr", num_partitions=1)
    registry.register_stream_factory(
        "mem_fr", MemoryStreamConsumerFactory(stream, batch_size=64))
    cluster = EmbeddedCluster(work_dir, num_servers=1)
    try:
        cluster.add_schema(make_schema())
        cluster.add_table(rt_config("mem_fr", "topic_fr",
                                    flush_rows=100_000))
        t0 = int(time.time() * 1e3)
        for r in make_rows(200, seed=6):
            stream.publish(r, partition=0)
        assert wait_until(lambda: count_star(cluster) == 200)
        resp = cluster.query("SELECT COUNT(*) FROM baseballStats")
        j = resp.to_json()
        assert j["numConsumingSegmentsQueried"] == 1, j
        assert t0 <= j["minConsumingFreshnessTimeMs"] <= \
            int(time.time() * 1e3) + 1000, j
    finally:
        cluster.stop()


def test_hlc_stats_history_feedback(work_dir):
    """The HLC path records flush stats and sizes the next consuming
    segment from them (same RealtimeSegmentStatsHistory loop as LLC)."""
    from pinot_tpu.realtime.hlc import HLRealtimeSegmentDataManager
    from pinot_tpu.realtime.stats_history import RealtimeSegmentStatsHistory
    from pinot_tpu.realtime.stream import StreamConfig
    from pinot_tpu.controller.property_store import PropertyStore
    from pinot_tpu.server.data_manager import TableDataManager

    stream = MemoryStream("topic_hsh", num_partitions=1)
    factory = MemoryStreamConsumerFactory(stream, batch_size=64)
    registry.register_stream_factory("mem_hsh", factory)
    # flush threshold ABOVE the 4096 allocation floor, so the hint
    # provably raises the next segment's initial capacity
    cfg = rt_config("mem_hsh", "topic_hsh", flush_rows=6000)
    stream_config = registry.resolve_stream_config(cfg)
    hist = RealtimeSegmentStatsHistory(os.path.join(work_dir, "sh.json"))
    store = PropertyStore()
    tdm = TableDataManager(RT_TABLE)
    mgr = HLRealtimeSegmentDataManager(
        RT_TABLE, make_schema(), cfg, stream_config, "g0", store,
        tdm, "srv0", work_dir, stats_history=hist)
    try:
        for r in make_rows(7000, seed=21):
            stream.publish(r, partition=0)
        assert wait_until(lambda: mgr.segments_flushed >= 1, timeout=30)
        assert wait_until(lambda: len(hist.entries(RT_TABLE)) >= 1)
        assert hist.entries(RT_TABLE)[0]["numRowsIndexed"] >= 6000
        # the live consuming segment allocated from the estimate — the
        # estimate exceeds the floor, so the assertion is non-vacuous
        est = hist.estimate(RT_TABLE)
        assert est["rows"] > 4096
        want = 4096
        while want < est["rows"]:
            want *= 2
        src = mgr.mutable._sources["teamID"]
        assert len(src._sv._arr) >= want > 4096
    finally:
        mgr.stop()


def test_commit_lease_expiry_reelects_winner(work_dir):
    """Parity: the commit-time lease — a winner that goes silent past
    its lease forfeits, and the next reporter is re-elected so the
    partition doesn't stall until periodic repair."""
    from pinot_tpu.common import completion as proto
    from pinot_tpu.controller.controller import Controller

    ctrl = Controller(os.path.join(work_dir, "ds"))
    rt = ctrl.realtime
    rt.election_wait_ms = 0.0           # elect on first report
    rt.commit_lease_ms = 30.0           # tiny lease for the test
    # two live replicas
    from pinot_tpu.controller.state_machine import StateModel
    ctrl.coordinator.register_participant("s1", StateModel())
    ctrl.coordinator.register_participant("s2", StateModel())
    from pinot_tpu.controller.manager import SEGMENTS
    seg = "baseballStats__0__0"
    ctrl.coordinator.set_ideal_state(
        RT_TABLE, {seg: {"s1": "CONSUMING", "s2": "CONSUMING"}})
    rt.store.set(f"{SEGMENTS}/{RT_TABLE}/{seg}",
                 {"segmentName": seg, "status": "IN_PROGRESS",
                  "startOffset": 0})

    r1 = rt.segment_consumed(RT_TABLE, seg, "s1", 100)
    assert r1.status == proto.COMMIT            # s1 elected, lease starts
    r2 = rt.segment_consumed(RT_TABLE, seg, "s2", 100)
    assert r2.status == proto.HOLD
    time.sleep(0.1)                              # lease expires
    r2 = rt.segment_consumed(RT_TABLE, seg, "s2", 100)
    assert r2.status == proto.COMMIT, r2.status  # re-elected
    # the old winner's commit_start is now refused
    assert rt.commit_start(RT_TABLE, seg, "s1", 100).status == proto.FAILED
    assert rt.commit_start(RT_TABLE, seg, "s2",
                           100).status == proto.COMMIT_CONTINUE


def test_extend_build_time_keeps_lease(work_dir):
    """SegmentBuildTimeLeaseExtender parity: extensions keep a slow
    winner's lease alive, so no re-election happens."""
    from pinot_tpu.common import completion as proto
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.controller.manager import SEGMENTS
    from pinot_tpu.controller.state_machine import StateModel

    ctrl = Controller(os.path.join(work_dir, "ds"))
    rt = ctrl.realtime
    rt.election_wait_ms = 0.0
    rt.commit_lease_ms = 500.0          # wide margin: CI-load safe
    ctrl.coordinator.register_participant("s1", StateModel())
    ctrl.coordinator.register_participant("s2", StateModel())
    seg = "baseballStats__0__0"
    ctrl.coordinator.set_ideal_state(
        RT_TABLE, {seg: {"s1": "CONSUMING", "s2": "CONSUMING"}})
    rt.store.set(f"{SEGMENTS}/{RT_TABLE}/{seg}",
                 {"segmentName": seg, "status": "IN_PROGRESS",
                  "startOffset": 0})
    assert rt.segment_consumed(RT_TABLE, seg, "s1",
                               50).status == proto.COMMIT
    # 6 x 150ms = 900ms elapsed, well past the ORIGINAL 500ms lease;
    # each extension grants a fresh 500ms (350ms slack per step under
    # CI load), so the winner stays elected throughout
    for _ in range(6):
        time.sleep(0.15)
        assert rt.extend_build_time(RT_TABLE, seg, "s1",
                                    extra_ms=500.0).status == \
            proto.PROCESSED
    assert rt.segment_consumed(RT_TABLE, seg, "s2",
                               50).status == proto.HOLD
    # a non-winner cannot extend
    assert rt.extend_build_time(RT_TABLE, seg, "s2").status == \
        proto.FAILED


def test_completion_fsm_survives_controller_restart(work_dir):
    """SURVEY §5.4(d): the completion FSM tolerates a controller restart
    by rebuilding from durable metadata — in-flight elections simply
    re-run when replicas re-report, and already-committed segments
    answer KEEP/DISCARD from the store."""
    from pinot_tpu.common import completion as proto
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.controller.manager import SEGMENTS
    from pinot_tpu.controller.realtime_manager import RealtimeSegmentManager
    from pinot_tpu.controller.state_machine import StateModel

    ctrl = Controller(os.path.join(work_dir, "ds"))
    rt = ctrl.realtime
    rt.election_wait_ms = 0.0
    ctrl.coordinator.register_participant("s1", StateModel())
    ctrl.coordinator.register_participant("s2", StateModel())
    seg = "baseballStats__0__0"
    # table config present in the durable store (commit_end reads it)
    rt.store.set(f"/CONFIGS/TABLE/{RT_TABLE}",
                 rt_config("none_fsm", "t_fsm").to_json())
    ctrl.coordinator.set_ideal_state(
        RT_TABLE, {seg: {"s1": "CONSUMING", "s2": "CONSUMING"}})
    rt.store.set(f"{SEGMENTS}/{RT_TABLE}/{seg}",
                 {"segmentName": seg, "status": "IN_PROGRESS",
                  "startOffset": 0})

    # s1 elected mid-flight, then the controller "restarts": a NEW
    # manager over the same durable store, empty in-memory FSM
    assert rt.segment_consumed(RT_TABLE, seg, "s1",
                               80).status == proto.COMMIT
    rt2 = RealtimeSegmentManager(ctrl.manager)
    rt2.election_wait_ms = 0.0

    # replicas re-report to the fresh controller: election re-runs
    r = rt2.segment_consumed(RT_TABLE, seg, "s2", 80)
    assert r.status == proto.COMMIT          # s2 elected by the new FSM
    assert rt2.commit_start(RT_TABLE, seg, "s2",
                            80).status == proto.COMMIT_CONTINUE

    # commit through the NEW manager using a real built segment
    d = os.path.join(work_dir, "built")
    SegmentCreator(make_schema(), make_table_config(),
                   seg).build(make_columns(500, seed=15), d)
    assert rt2.commit_end(RT_TABLE, seg, "s2", 80,
                          d).status == proto.COMMIT_SUCCESS

    # a third manager (another restart): committed segments answer from
    # durable metadata with no in-memory state at all
    rt3 = RealtimeSegmentManager(ctrl.manager)
    assert rt3.segment_consumed(RT_TABLE, seg, "s1",
                                80).status == proto.KEEP
    assert rt3.segment_consumed(RT_TABLE, seg, "s1",
                                70).status == proto.DISCARD
