"""Embedded cluster: controller + servers + broker in one process.

Parity: the reference's ClusterTest harness (pinot-integration-tests/.../
ClusterTest.java:85 — real Controller/Broker/Server instances in one JVM)
and the Quickstart wiring (tools/Quickstart.java:125-144). The full
production plumbing runs: property store, state transitions, deep store,
scatter-gather (in-process or TCP), broker reduce.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from pinot_tpu.broker.cluster_watcher import BrokerClusterWatcher
from pinot_tpu.broker.request_handler import (BrokerRequestHandler,
                                              InProcessTransport,
                                              TcpTransport)
from pinot_tpu.common.response import BrokerResponse
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.table_config import TableConfig
from pinot_tpu.controller.controller import Controller
from pinot_tpu.server.instance import ServerInstance
from pinot_tpu.server.participant import ServerParticipant


class EmbeddedCluster:
    """controller + num_servers query servers + one broker."""

    def __init__(self, work_dir: str, num_servers: int = 2,
                 tcp: bool = False, mesh=None, scheduler: str = "fcfs",
                 http: bool = False, store_dir: str = None,
                 server_max_pending: int = None,
                 cache_freshness_ms: float = None):
        """`store_dir`: persist cluster state (property-store WAL +
        snapshots) under this directory — a cluster rebuilt over the
        same work_dir/store_dir recovers its tables and segments."""
        from pinot_tpu.broker.quota import QueryQuotaManager
        self.work_dir = work_dir
        self.controller = Controller(os.path.join(work_dir, "deepstore"),
                                     store_dir=store_dir)
        self.servers: Dict[str, ServerInstance] = {}
        self.participants: Dict[str, ServerParticipant] = {}
        for i in range(num_servers):
            name = f"Server_{i}"
            server = ServerInstance(name, scheduler=scheduler, mesh=mesh,
                                    max_pending=server_max_pending)
            self.servers[name] = server
            participant = ServerParticipant(
                server, self.controller.manager,
                completion=self.controller.realtime,
                work_dir=os.path.join(work_dir, "server_work", name))
            self.participants[name] = participant
            self.controller.coordinator.register_participant(name,
                                                             participant)
        # ONE quota manager shared by the watcher (which converges
        # table-config quotas into it) and the broker (which enforces)
        self.quota = QueryQuotaManager()
        self.watcher = BrokerClusterWatcher(self.controller.coordinator,
                                            self.controller.manager,
                                            quota=self.quota)
        if tcp:
            endpoints = {name: ("127.0.0.1", server.start(port=0))
                         for name, server in self.servers.items()}
            transport = TcpTransport(endpoints)
        else:
            transport = InProcessTransport(self.servers)
        self.broker = BrokerRequestHandler(
            self.watcher.routing, transport,
            time_boundary=self.watcher.time_boundary,
            quota=self.quota,
            segment_pruner=self.watcher.partition_pruner,
            cache_freshness_ms=cache_freshness_ms)
        # segment lifecycle (upload/replace/drop) flushes the broker
        # result cache — the freshness bound only covers consuming-
        # ingestion staleness, not an offline backfill
        self.watcher.register_result_cache(self.broker.result_cache)
        self.broker_api = None
        self.controller_api = None
        self.server_apis: Dict[str, object] = {}
        self.broker_port: Optional[int] = None
        self.controller_port: Optional[int] = None
        self.server_http_ports: Dict[str, int] = {}
        if http:
            from pinot_tpu.broker.http_api import BrokerApiServer
            from pinot_tpu.controller.http_api import ControllerApiServer
            from pinot_tpu.server.http_api import ServerApiServer
            self.broker_api = BrokerApiServer(self.broker)
            self.broker_port = self.broker_api.start()
            self.controller_api = ControllerApiServer(self.controller)
            self.controller_port = self.controller_api.start()
            # per-server admin APIs: /health, /metrics, table/segment
            # debug views — the quickstart cluster serves the full
            # observability surface on every plane
            for name, server in self.servers.items():
                api = ServerApiServer(server)
                self.server_apis[name] = api
                self.server_http_ports[name] = api.start()

    # -- admin facade (parity: controller REST) ----------------------------
    def add_schema(self, schema: Schema) -> None:
        self.controller.manager.add_schema(schema)

    def add_table(self, config: TableConfig, **kw) -> str:
        from pinot_tpu.common.table_config import TableType
        if config.table_type == TableType.REALTIME:
            return self.controller.realtime.setup_table(config, **kw)
        return self.controller.manager.add_table(config, **kw)

    def upload_segment(self, table: str, segment_dir: str) -> str:
        return self.controller.manager.add_segment(table, segment_dir)

    def query(self, pql: str) -> BrokerResponse:
        return self.broker.handle(pql)

    def stop(self) -> None:
        if self.broker_api is not None:
            self.broker_api.stop()
        if self.controller_api is not None:
            self.controller_api.stop()
        for api in self.server_apis.values():
            api.stop()
        self.controller.stop()
        self.broker.close()
        for participant in self.participants.values():
            participant.shutdown()
        for server in self.servers.values():
            server.stop()
