"""concurrency: unguarded attribute mutation in threaded classes.

Server/realtime classes are touched by scheduler worker threads,
partition-consumer threads and state-transition threads at once. The
rule: inside modules on the concurrency watchlist, any ``self.X = ...``
(or ``self.X[k] = ...`` / ``self.X += ...``) OUTSIDE ``__init__`` must
happen under a ``with self.<lock>:`` where ``<lock>`` is a
``threading.Lock``/``RLock``/``Condition`` declared on the class.
Classes that declare no lock at all get every non-init mutation
flagged — either the class needs a lock or the single-writer argument
belongs in a suppression reason next to the mutation.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Set

from pinot_tpu.analysis import astutil
from pinot_tpu.analysis.core import Finding, Rule, register

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
_INIT_METHODS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}


def _lock_attrs(cls: ast.ClassDef, aliases) -> Set[str]:
    """self.X assigned anywhere in the class from a Lock/RLock/Condition."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                astutil.resolve(node.value.func, aliases) in _LOCK_CTORS:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    locks.add(tgt.attr)
    return locks


def _self_attr_of_target(tgt: ast.AST) -> str:
    """'X' when tgt writes self.X or self.X[...]; '' otherwise."""
    if isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Attribute) and \
            isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
        return tgt.attr
    return ""


class _MethodScan(ast.NodeVisitor):
    """Collect unguarded self-mutations, tracking the with-lock stack."""

    def __init__(self, lock_attrs: Set[str]):
        self.lock_attrs = lock_attrs
        self.depth = 0           # nested `with self.<lock>:` depth
        self.hits: List[ast.AST] = []   # (node, attr) pairs

    def visit_With(self, node: ast.With) -> None:
        held = any(
            _self_attr_of_target(item.context_expr) in self.lock_attrs
            for item in node.items)
        if held:
            self.depth += 1
        self.generic_visit(node)
        if held:
            self.depth -= 1

    def _record(self, node: ast.AST, targets) -> None:
        if self.depth:
            return
        for tgt in targets:
            attr = _self_attr_of_target(tgt)
            if attr and attr not in self.lock_attrs:
                self.hits.append((node, attr))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node, [node.target])
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(node, [node.target])
        self.generic_visit(node)


@register
class ConcurrencyRule(Rule):
    id = "concurrency"
    description = ("attributes of server/realtime classes mutated "
                   "outside __init__ without holding a lock declared "
                   "on the class")

    def check(self, ctx) -> Iterator[Finding]:
        if not ctx.in_prefixes(ctx.config.concurrency_prefixes):
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls, ctx.aliases)
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name in _INIT_METHODS:
                    continue
                scan = _MethodScan(locks)
                scan.visit(method)
                for node, attr in scan.hits:
                    if locks:
                        msg = (f"`{cls.name}.{method.name}` mutates "
                               f"self.{attr} without holding "
                               f"{'/'.join(sorted(locks))}")
                    else:
                        msg = (f"`{cls.name}.{method.name}` mutates "
                               f"self.{attr} but the class declares no "
                               "lock — add one or justify the "
                               "single-writer invariant in a "
                               "suppression reason")
                    yield ctx.finding(self.id, node, msg)
