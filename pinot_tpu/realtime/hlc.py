"""High-level consumer (HLC) realtime ingestion.

Parity: pinot-core/.../realtime/HLRealtimeSegmentDataManager.java:61 —
the legacy consumer path. Unlike LLC there is NO controller completion
FSM: the stream's group management owns partition assignment
(StreamLevelConsumer SPI), the server indexes rows into a consuming
segment that is queryable immediately, FULL segments convert to
immutable segments locally and swap into the server's data manager, and
only after a segment is durable does the consumer-group checkpoint
persist (ZK offset commits in the reference; the property store record
``/CONSUMERS/<table>/<group>`` here). Restart resumes from the last
checkpoint, so rows after it replay — the reference's at-least-once
post-persist commit semantics.

HLC segment naming follows the reference's
``<table>__<instance>__<group>__<seq>`` convention.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

import shutil

from pinot_tpu.common.table_name import raw_table
from pinot_tpu.ingestion.transformer import CompoundTransformer
from pinot_tpu.realtime import converter
from pinot_tpu.realtime.mutable_segment import MutableSegmentImpl
from pinot_tpu.realtime.stream import StreamConfig
from pinot_tpu.segment.loader import ImmutableSegmentLoader

log = logging.getLogger(__name__)

CONSUMERS = "/CONSUMERS"
_POLL_S = 0.05


class HLRealtimeSegmentDataManager:
    """Group-consume → index → flush-local → checkpoint loop for one
    (table, consumer group) on one server instance."""

    def __init__(self, table: str, schema, table_config,
                 stream_config: StreamConfig, group_id: str, store,
                 table_data_manager, instance_id: str, work_dir: str,
                 on_segment_flushed: Optional[Callable] = None,
                 batch_rows: int = 1000, stats_history=None):
        self.table = table
        self.schema = schema
        self.table_config = table_config
        self.stream_config = stream_config
        self.group_id = group_id
        self.store = store
        self.tdm = table_data_manager
        self.instance_id = instance_id
        self.work_dir = work_dir
        self.on_segment_flushed = on_segment_flushed
        self.batch_rows = batch_rows
        self.stats_history = stats_history
        self.transformer = CompoundTransformer(schema)
        self.segments_flushed = 0

        rec = store.get(self._ckpt_path) or {}
        self._seq = int(rec.get("sequence", 0))
        checkpoint = {int(k): int(v)
                      for k, v in (rec.get("offsets") or {}).items()}
        self.consumer = stream_config.consumer_factory \
            .create_stream_consumer(stream_config, checkpoint or None)
        # restart: re-serve previously flushed local segments (parity:
        # the reference HLC re-loads its local segments via Helix on
        # restart — the checkpoint skips their rows, so without this
        # they would be lost)
        for seq in range(self._seq):
            seg_dir = os.path.join(work_dir, self._segment_name(seq))
            if os.path.isdir(seg_dir) and \
                    self._segment_name(seq) not in \
                    table_data_manager.segment_names():
                try:
                    table_data_manager.add_segment(
                        ImmutableSegmentLoader.load(seg_dir))
                except Exception:  # noqa: BLE001 — torn local artifact:
                    log.exception("could not reload flushed segment %s",
                                  seg_dir)
        self.mutable: MutableSegmentImpl = self._new_consuming_segment()
        self._deadline = time.monotonic() + \
            stream_config.flush_threshold_time_ms / 1e3
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hlc-{table}-{group_id}")
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------

    @property
    def _ckpt_path(self) -> str:
        return f"{CONSUMERS}/{self.table}/{self.group_id}"

    def _segment_name(self, seq: int) -> str:
        return (f"{raw_table(self.table)}__{self.instance_id}__"
                f"{self.group_id}__{seq}")

    def _new_consuming_segment(self) -> MutableSegmentImpl:
        # allocation sizing from prior flushes (RealtimeSegmentStatsHistory
        # parity — same feedback loop as the LLC path)
        hint = self.stats_history.estimate(self.table) \
            if self.stats_history is not None else None
        mutable = MutableSegmentImpl(self.schema, self.table_config,
                                     self._segment_name(self._seq),
                                     stats_hint=hint)
        # queryable from the first row (refcounted like any segment)
        self.tdm.add_segment(mutable)
        return mutable

    def stop(self) -> None:
        self._stop.set()
        if threading.current_thread() is not self._thread:
            self._thread.join(timeout=10)
        try:
            self.consumer.close()
        except Exception:  # noqa: BLE001
            pass

    # -- consume loop ------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                if self.mutable.num_docs >= \
                        self.stream_config.flush_threshold_rows or \
                        (time.monotonic() >= self._deadline and
                         self.mutable.num_docs > 0):
                    self._flush()
                    continue
                try:
                    msgs = self.consumer.next_messages(self.batch_rows)
                except Exception:  # noqa: BLE001 — flaky stream:
                    log.warning("HLC fetch failed for %s/%s; retrying",
                                self.table, self.group_id, exc_info=True)
                    self._stop.wait(_POLL_S)
                    continue
                if not msgs:
                    self._stop.wait(_POLL_S)
                    continue
                for msg in msgs:
                    row = self.stream_config.decoder.decode(msg.value)
                    if row is not None:
                        try:
                            row = self.transformer.transform(row)
                        except Exception:  # noqa: BLE001 — poison record
                            row = None
                    if row is None:
                        continue
                    self.mutable.index_row(row)
        except Exception:  # noqa: BLE001 — keep the server alive
            log.exception("HLC consumer %s/%s died", self.table,
                          self.group_id)

    def _flush(self) -> None:
        """Convert the consuming segment to an immutable one IN PLACE
        (same name → refcounted swap in the data manager), then persist
        the consumer checkpoint — durability before commit."""
        name = self.mutable.segment_name
        # before the swap drops the mutable's buffers; guarded — the
        # O(docs) stat pass is wasted without a history to record into
        stats = self.mutable.collect_stats() \
            if self.stats_history is not None else None
        out_dir = os.path.join(self.work_dir, name)
        # a crash between flush and checkpoint replays this sequence —
        # never build into a directory holding a previous torn attempt
        shutil.rmtree(out_dir, ignore_errors=True)
        os.makedirs(out_dir, exist_ok=True)
        meta = converter.convert(self.mutable, out_dir, name)
        immutable = ImmutableSegmentLoader.load(out_dir)
        self.tdm.add_segment(immutable)        # same-name swap
        if self.on_segment_flushed is not None:
            try:
                self.on_segment_flushed(self.table, name, out_dir, meta,
                                        self.instance_id)
            except Exception:  # noqa: BLE001 — registration is advisory
                log.exception("segment-flushed callback failed for %s",
                              name)
        self._seq += 1
        self.store.set(self._ckpt_path, {
            "offsets": {str(p): int(o)
                        for p, o in self.consumer.checkpoint().items()},
            "sequence": self._seq,
            "lastSegment": name,
            "updatedAtMs": int(time.time() * 1e3),
        })
        self.segments_flushed += 1
        if self.stats_history is not None:
            self.stats_history.add_segment_stats(self.table, stats)
        log.info("HLC flushed %s (%d docs), checkpoint persisted",
                 name, meta.total_docs)
        self.mutable = self._new_consuming_segment()
        self._deadline = time.monotonic() + \
            self.stream_config.flush_threshold_time_ms / 1e3
