"""Auxiliary per-segment index structures (beyond the columnar core).

`ivf`: the IVF ANN coarse quantizer for VECTOR columns — k-means
centroids trained as a batched device kernel, per-row centroid
assignments persisted next to the `.vec.fwd.npy` block, and probe-list
selection fused into the filter plane as its own lane kind.
"""
