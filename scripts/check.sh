#!/usr/bin/env bash
# CI gate: tier-1 tests, then tpulint against the committed baseline.
# Either failing fails the build.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tier-1 pytest =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider

echo "== chaos (broker fault tolerance) =="
# dedicated gate: the fault-injection suite must stay green and fast
# even if a future tier-1 filter stops collecting it implicitly
env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py tests/test_retry.py \
    -q -p no:cacheprovider

echo "== crash recovery (durability plane) =="
# kill-and-restart gates: WAL/snapshot recovery, torn tails, seeded
# crash points, cold-start reloads, integrity quarantine + repair ...
env JAX_PLATFORMS=cpu python -m pytest tests/test_crash_recovery.py \
    -q -p no:cacheprovider
# ... plus a scripted kill-restart of the distributed quickstart that
# must converge (zero re-downloads) within a bounded window
env JAX_PLATFORMS=cpu python scripts/crash_restart_smoke.py

echo "== upsert (mutable-scenario durability) =="
# primary-key dedup crash gates: kill -9 mid upsert stream at each
# seeded crash point, restart, exact-count + latest-value convergence
# with host-vs-device masked-result parity ...
env JAX_PLATFORMS=cpu python -m pytest tests/test_upsert.py \
    -q -p no:cacheprovider
# ... plus a scripted kill-restart that must converge with ZERO topic
# re-reads before the key-map snapshot offset
env JAX_PLATFORMS=cpu python scripts/upsert_smoke.py

echo "== self-healing (membership churn + controller failover) =="
# continuous two-table load (OFFLINE + REALTIME upserts) while the
# harness kill -9s the consuming server, then the lead controller, then
# SIGTERM-drains a server: replication must repair, consumption resume
# with exact-count/latest-value convergence, the standby serve commits
# within ~one lease period, and the drain cost zero query errors
env JAX_PLATFORMS=cpu python -m pytest tests/test_selfheal.py \
    -q -p no:cacheprovider
env JAX_PLATFORMS=cpu python scripts/selfheal_smoke.py

echo "== compaction soak (background maintenance plane) =="
# two-phase soak at 2x upsert churn: WITHOUT maintenance the key map
# and masked-dead rows grow monotonically; WITH the minion plane
# (deadness-driven compaction swaps + TTL retention with delayed
# delete + upsert key GC) scan p99, committed docs and
# upsertKeyMapSize stay flat — while a kill -9 of the minion
# (compact.staged) and of the swap driver (compact.pre_swap) both
# recover exactly from the durable intent records, with COUNT(*) ==
# key-map size at every checkpoint; artifact: COMPACT_r09.json
env JAX_PLATFORMS=cpu python scripts/compaction_smoke.py

echo "== tenant isolation (ingress control) =="
# two-tenant overload gate: an aggressor flooding at 10x its per-tenant
# token-bucket quota must be throttled with typed 429s while the victim
# tenant sharing the table keeps its unloaded steady-state p99 (within
# 1.5x + a CI-noise floor); quota/admission/result-cache unit suites
# run in tier-1 above — this drives the stack end to end
env JAX_PLATFORMS=cpu python scripts/tenant_isolation_smoke.py

echo "== vector search (similarity over mutable embeddings) =="
# embedded cluster with a primary-key upsert table carrying a VECTOR
# column: filtered VECTOR_SIMILARITY top-k must match the independent
# numpy oracle bit-exactly, an upsert published mid-run must rank FIRST
# on the next converged query, and the superseded row must never rank
env JAX_PLATFORMS=cpu python scripts/vector_smoke.py

echo "== join smoke (multi-stage query engine) =="
# SSB-style dim × fact through the full stage plane: broadcast +
# co-partitioned joins exact vs the numpy oracle, stage-1 blocks
# fetched over the TCP exchange byte-identically, window invariants +
# determinism, DISTINCTCOUNTHLL register-identical to the host sketch,
# host/device/sharded join parity, and a REALTIME upsert fact table
# whose join tracks mid-run upserts (superseded rows never join)
env JAX_PLATFORMS=cpu python scripts/join_smoke.py

echo "== qps smoke (serving plane) =="
# one short target-QPS rung over the real TCP mux: catches serving-plane
# regressions (per-connection serialization, serde blow-ups) in seconds
env JAX_PLATFORMS=cpu python scripts/qps_smoke.py

echo "== obs smoke (observability plane) =="
# /metrics must serve valid Prometheus exposition on broker + servers +
# controller, and a trace=true query must return a non-empty merged
# trace tree with per-server subtrees
env JAX_PLATFORMS=cpu python scripts/obs_smoke.py

echo "== residency smoke (tiered memory pressure) =="
# a working set ~3x the device budget must serve with graceful
# degradation: every answer bit-equal to the unbounded twin run, the
# HBM ledger never above budget at checkpoints, the full
# device->host->disk ladder exercised (promotions/demotions/cold hits
# all nonzero), and a bounded p99 penalty — never a cliff or a wrong
# answer
env JAX_PLATFORMS=cpu python scripts/residency_smoke.py

echo "== batch smoke (cross-query dispatch coalescing) =="
# a concurrent same-plan-shape mix must coalesce (batchOccupancy > 1)
# and answer bit-identically to a batchWindowMs=0 sequential twin —
# catches member-mixing fan-backs and literals leaking into the
# shared kernel spec in seconds
env JAX_PLATFORMS=cpu python scripts/batch_smoke.py

echo "== production soak (short mode: one cluster, every subsystem) =="
# 120s scaled-down soak of the FULL production shape: multi-process HA
# cluster (standalone store + lead/standby controller + servers +
# broker + minion) serving the weighted mix (SSB + joins + windows +
# VECTOR_SIMILARITY + 2-tenant quotas) while realtime upserts churn,
# with a deterministic chaos schedule firing one kill -9 of a serving
# server and one lead-controller failover mid-run. Gates: ZERO
# unflagged errors (every BrokerResponse exception carries a
# machine-readable errorCode), per-class p99 in bounds, recoveries
# inside deadlines, leak gauges flat. Full 30+ min run commits
# SOAK_r15.json; this short gate reuses the identical harness.
env PINOT_TPU_SOAK_SECONDS="${PINOT_TPU_SOAK_SECONDS:-120}" \
    SOAK_ARTIFACT="${SOAK_ARTIFACT:-/tmp/soak_ci.json}" \
    python scripts/prod_soak.py

echo "== tpulint (deep + protocol tiers) =="
# --deep adds the below-the-AST gates on top of the AST families:
# every registered kernel is traced with jax.make_jaxpr across the
# shape-bucket grid (no host callbacks, no 64-bit avals in 32-bit
# mode, stable retrace) and the serde wire surface must round-trip
# against the committed wire-schema.json. --protocol adds the
# crash-protocol gates: staged-write durability ordering over the
# durable writers, crash-point coverage (every durable mutation
# splittable, every point armed by a test), the metrics exposition
# contract, an exhaustive crash-interleaving model check of the
# extracted lease/rebalance/takeover/upsert-seal/drain/compact-swap
# transition systems against the written ROBUSTNESS.md invariants
# (state counts logged; hitting --max-states is a finding, never
# silent), and a drift gate against the committed protocol-model.json.
# On failure the CLI prints a findings-diff summary (rule id,
# file:line, fix-or-suppress guidance) — and for invariant violations,
# the counterexample trace. --lifecycle adds the resource-lifecycle
# tier (device uploads routed through the residency ledger, query-path
# caches structurally bounded), --sarif exports every finding for CI
# annotation, and lint.sh fails the gate if the whole four-tier run
# exceeds its wall-time budget (default 30s).
exec "$(dirname "$0")/lint.sh" --lifecycle --deep --protocol \
    --sarif lint.sarif
