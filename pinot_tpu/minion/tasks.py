"""Minion task model + property-store-backed task queue.

Parity: the Helix Task Framework usage in
pinot-controller/.../helix/core/minion/PinotHelixTaskResourceManager.java
(task queues per task type, task states) and
pinot-common PinotTaskConfig. The TPU build replaces the Helix task
state machine with atomic claim/complete updates on the cluster
property store — the same single-writer CAS discipline the ideal-state
updates use.
"""
from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Dict, List, Optional

from pinot_tpu.controller.property_store import PropertyStore

TASKS_ROOT = "/TASKS"

# task states (parity: TaskState in the Helix task framework)
GENERATED = "GENERATED"
IN_PROGRESS = "IN_PROGRESS"
COMPLETED = "COMPLETED"
ERROR = "ERROR"


@dataclasses.dataclass
class PinotTaskConfig:
    """Parity: pinot-common PinotTaskConfig — task type + string configs."""
    task_type: str
    configs: Dict[str, str] = dataclasses.field(default_factory=dict)
    task_id: str = ""

    def __post_init__(self):
        if not self.task_id:
            self.task_id = (f"Task_{self.task_type}_"
                            f"{uuid.uuid4().hex[:12]}")

    def to_json(self) -> dict:
        return {"taskType": self.task_type, "taskId": self.task_id,
                "configs": dict(self.configs)}

    @classmethod
    def from_json(cls, d: dict) -> "PinotTaskConfig":
        return cls(task_type=d["taskType"], configs=dict(d.get("configs", {})),
                   task_id=d["taskId"])


# common config keys (parity: core/common/MinionConstants.java)
TABLE_NAME_KEY = "tableName"
SEGMENT_NAME_KEY = "segmentName"
DOWNLOAD_URL_KEY = "downloadURL"
COLUMNS_TO_CONVERT_KEY = "columnsToConvert"
MERGED_SEGMENTS_KEY = "segmentNames"          # comma-separated, merge tasks


class TaskQueue:
    """Task lifecycle on the property store.

    /TASKS/<taskType>/<taskId> → {"config": ..., "state": ...,
    "worker": ..., "info": ...}. Claiming is an atomic read-modify-write
    so concurrent minions never double-run a task.
    """

    def __init__(self, store: PropertyStore):
        self.store = store

    def submit(self, task: PinotTaskConfig) -> str:
        self.store.set(f"{TASKS_ROOT}/{task.task_type}/{task.task_id}", {
            "config": task.to_json(), "state": GENERATED,
            "submitTimeMs": int(time.time() * 1e3)})
        return task.task_id

    def claim(self, worker_id: str, task_types: List[str]
              ) -> Optional[PinotTaskConfig]:
        """Atomically move one GENERATED task to IN_PROGRESS."""
        for ttype in task_types:
            for task_id in self.store.children(f"{TASKS_ROOT}/{ttype}"):
                path = f"{TASKS_ROOT}/{ttype}/{task_id}"
                claimed = {}

                def try_claim(rec):
                    if rec and rec.get("state") == GENERATED:
                        rec = dict(rec)
                        rec["state"] = IN_PROGRESS
                        rec["worker"] = worker_id
                        claimed["config"] = rec["config"]
                    return rec or {}

                self.store.update(path, try_claim)
                if claimed:
                    return PinotTaskConfig.from_json(claimed["config"])
        return None

    def finish(self, task: PinotTaskConfig, state: str,
               info: str = "") -> None:
        path = f"{TASKS_ROOT}/{task.task_type}/{task.task_id}"

        def done(rec):
            rec = dict(rec or {})
            rec["state"] = state
            rec["info"] = info
            rec["endTimeMs"] = int(time.time() * 1e3)
            return rec

        self.store.update(path, done)

    def task_states(self, task_type: str) -> Dict[str, str]:
        out = {}
        for task_id in self.store.children(f"{TASKS_ROOT}/{task_type}"):
            rec = self.store.get(f"{TASKS_ROOT}/{task_type}/{task_id}")
            if rec:
                out[task_id] = rec.get("state", "?")
        return out

    def tasks_for_segment(self, task_type: str, table: str,
                          segment: str) -> List[str]:
        """Open (non-terminal) tasks already covering a segment — used by
        generators to avoid duplicate scheduling."""
        out = []
        for task_id in self.store.children(f"{TASKS_ROOT}/{task_type}"):
            rec = self.store.get(f"{TASKS_ROOT}/{task_type}/{task_id}")
            if not rec or rec.get("state") in (COMPLETED, ERROR):
                continue
            cfg = rec.get("config", {}).get("configs", {})
            if cfg.get(TABLE_NAME_KEY) == table and \
                    segment in cfg.get(SEGMENT_NAME_KEY, "").split(","):
                out.append(task_id)
        return out
