"""Query schedulers: FCFS and token-bucket priority.

Parity: pinot-core/.../core/query/scheduler/ — QuerySchedulerFactory
(algorithms "fcfs" | "tokenbucket", QuerySchedulerFactory.java:40-68),
PriorityScheduler + TokenSchedulerGroup (token bucket ≈ CPU-ms accounting
with linear decay, TokenSchedulerGroup.java:31-56), bounded per-group
concurrency. Execution happens on a thread pool; the device serializes
kernels anyway, so scheduling decides ORDER and fairness, exactly the
role it plays in the reference.
"""
from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional


class QueryScheduler:
    """submit(group, fn) -> Future; subclasses order execution."""

    def __init__(self, num_workers: int = 4):
        self._pool = ThreadPoolExecutor(max_workers=num_workers)
        self.num_workers = num_workers

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        raise NotImplementedError

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class FCFSQueryScheduler(QueryScheduler):
    """First-come-first-served (the reference default)."""

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        return self._pool.submit(fn)


class TokenBucketScheduler(QueryScheduler):
    """Priority scheduling by per-group token accounting.

    Each group (table) accrues tokens linearly over time and spends
    wall-clock-ms tokens when its queries run; the pending query from the
    group with the most tokens runs first. Mirrors TokenSchedulerGroup's
    `tokens = tokens*decay + lifetime_ms*num_workers - used_ms`.
    """

    TOKEN_LIFETIME_MS = 100.0

    def __init__(self, num_workers: int = 4):
        super().__init__(num_workers)
        self._groups: Dict[str, float] = {}
        self._last_refresh: Dict[str, float] = {}
        self._queue: list = []            # (-tokens, seq, group, fn, future)
        self._seq = 0
        self._lock = threading.Lock()

    def _refresh_tokens(self, group: str) -> float:
        now = time.monotonic()
        last = self._last_refresh.get(group, now)
        tokens = self._groups.get(group, 0.0)
        tokens = tokens * 0.5 + (now - last) * 1e3 * self.num_workers
        tokens = min(tokens, self.TOKEN_LIFETIME_MS * self.num_workers * 2)
        self._groups[group] = tokens
        self._last_refresh[group] = now
        return tokens

    def submit(self, group: str, fn: Callable[[], object]) -> Future:
        future: Future = Future()
        with self._lock:
            tokens = self._refresh_tokens(group)
            heapq.heappush(self._queue,
                           (-tokens, self._seq, group, fn, future))
            self._seq += 1
        self._pool.submit(self._drain)
        return future

    def _drain(self) -> None:
        with self._lock:
            if not self._queue:
                return
            _, _, group, fn, future = heapq.heappop(self._queue)
        if not future.set_running_or_notify_cancel():
            return
        t0 = time.monotonic()
        try:
            future.set_result(fn())
        except BaseException as e:  # noqa: BLE001 — future carries it
            future.set_exception(e)
        finally:
            used_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self._groups[group] = self._groups.get(group, 0.0) - used_ms


def make_scheduler(algorithm: str = "fcfs", num_workers: int = 4
                   ) -> QueryScheduler:
    """Parity: QuerySchedulerFactory.create (falls back to FCFS)."""
    if algorithm == "tokenbucket":
        return TokenBucketScheduler(num_workers)
    return FCFSQueryScheduler(num_workers)
