"""Star-tree query execution: route eligible queries to a cube.

Parity: core/startree/ query side — StarTreeFilterOperator +
StarTreeAggregationExecutor/StarTreeGroupByExecutor and the plan nodes
that swap in when a query's dimensions/metrics are covered
(StarTreeV2's eligibility rules). Here the cube is a columnar grouped
table, so execution is: evaluate the filter over the cube's dictId lanes,
then weighted aggregation over the surviving groups.

Cube rows are SORTED by the split order (lexicographic in the packed
dictId key — the build's sorted factorize guarantees it), which is the
flattened form of the reference's tree: a conjunctive filter whose
leading split dimensions resolve to dictId intervals narrows to
contiguous row blocks by binary search (OffHeapStarTreeNode child lookup
≡ np.searchsorted on the sorted dim lane), and only the surviving block
rows are scanned for the residual predicates. A covering cube therefore
answers in O(log groups + matched rows) host time instead of O(groups).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import (BrokerRequest, FilterOperator,
                                      FilterQueryTree)
from pinot_tpu.query.aggregation import make_functions
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock

_COVERED_BASES = {"COUNT", "SUM", "AVG", "MIN", "MAX", "MINMAXRANGE"}

# stop expanding prefix blocks past this fan-out: the residual scan over
# a bounded union of blocks is cheaper than deep enumeration
_PREFIX_BLOCK_LIMIT = 512


class _CubeDataSource:
    """Segment-DataSource-shaped view of one cube dimension lane."""

    def __init__(self, parent_ds, ids: np.ndarray):
        self.metadata = parent_ds.metadata
        self.dictionary = parent_ds.dictionary
        self.dict_ids = ids
        self.raw_values = None
        self.mv_dict_ids = None
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None


class _CubeView:
    """Segment-shaped facade so host filter evaluation runs unchanged."""

    def __init__(self, segment, cube):
        self._segment = segment
        self._cube = cube
        self.num_docs = cube.n_groups
        self.segment_name = segment.segment_name

    def has_column(self, col: str) -> bool:
        return col in self._cube.dim_ids

    def data_source(self, col: str) -> _CubeDataSource:
        return _CubeDataSource(self._segment.data_source(col),
                               self._cube.dim_ids[col])


def _eligible_cube(segment, request: BrokerRequest, functions):
    """Pick the first cube covering the query, or None.

    Coverage: filter + group columns ⊆ dimensions (expressions allowed in
    filters when their source columns are dimensions); aggregations are
    COUNT(*) or covered-base functions over cube metrics.
    """
    cubes = getattr(segment, "star_trees", None)
    if not cubes or not request.is_aggregation or request.is_selection:
        return None
    if request.query_options.options.get("useStarTree") == "false":
        return None
    needed_dims = set()
    for c in request.filter_columns():
        needed_dims.update(expr_mod.referenced_columns(c))
    group_cols = list(request.group_by.columns) if request.group_by else []
    for c in group_cols:
        if expr_mod.is_expression(c):
            return None                       # group keys must be plain dims
        needed_dims.add(c)
    needed_metrics = set()
    for f in functions:
        if f.info.is_mv:
            return None
        if f.info.base == "COUNT":
            continue
        if f.info.base not in _COVERED_BASES:
            return None
        if expr_mod.is_expression(f.column):
            return None
        needed_metrics.add(f.column)
    best = None
    best_score = None
    leaves = _conjunctive_leaves(request.filter)
    for cube in cubes:
        if not (needed_dims <= set(cube.dimensions) and
                needed_metrics <= set(cube.metrics)):
            continue
        score, frac = _prefix_narrowing(segment, cube, leaves)
        if cube.n_groups * frac * 8 > segment.num_docs:
            # a cube nearly as tall as the segment must be narrowed to a
            # genuinely small block before it beats the doc-scale kernel:
            # a prefix "hit" from one wide RANGE on the leading dim (e.g.
            # dim >= 'A') would otherwise degrade to a near-full host scan
            continue
        key = (score, -cube.n_groups * frac)
        if best is None or key > best_score:
            best, best_score = cube, key
    return best


def _prefix_narrowing(segment, cube, leaves) -> Tuple[int, float]:
    """(depth, est fraction): how many leading split dims a conjunctive
    filter narrows, and the estimated fraction of cube rows left after the
    descent (product of per-dim dictId coverage under a uniform-ids
    assumption). Depth ranks cube choice; the fraction gates eligibility so
    a wide RANGE on the leading dim doesn't count as real narrowing."""
    if not leaves:
        return 0, 1.0
    by_col = {}
    for lf in leaves:
        by_col.setdefault(lf.column, []).append(lf)
    score = 0
    frac = 1.0
    for dim in cube.dimensions:
        ivs = None
        ds = segment.data_source(dim)
        for lf in by_col.get(dim, ()):
            ivs = _leaf_id_intervals(lf, ds)
            if ivs is not None:
                break
        if ivs is None:
            break
        score += 1
        card = max(1, len(ds.dictionary)) if ds.dictionary is not None \
            else 1
        covered = sum(b - a for a, b in ivs)
        frac *= min(1.0, covered / card)
        if not all(b - a == 1 for a, b in ivs):
            break                       # descent stops after an interval
    return score, frac


def _conjunctive_leaves(tree: Optional[FilterQueryTree]
                        ) -> Optional[List[FilterQueryTree]]:
    """Flatten an AND-only filter tree into its leaves; None when the
    tree contains OR (prefix narrowing needs a pure conjunction)."""
    if tree is None:
        return []
    if tree.is_leaf():
        return [tree]
    if tree.operator != FilterOperator.AND:
        return None
    out: List[FilterQueryTree] = []
    for c in tree.children:
        sub = _conjunctive_leaves(c)
        if sub is None:
            return None
        out.extend(sub)
    return out


def _leaf_id_intervals(leaf: FilterQueryTree, ds
                       ) -> Optional[List[Tuple[int, int]]]:
    """Sorted-dictionary dictId intervals [a, b) equivalent to the leaf,
    or None when the leaf can't narrow a sorted cube lane (NOT/NOT_IN/
    REGEXP, expression columns, unsorted mutable dictionaries)."""
    if expr_mod.is_expression(leaf.column):
        return None
    d = ds.dictionary
    if d is None or not getattr(d, "is_sorted", True):
        return None
    op = leaf.operator
    if op == FilterOperator.EQUALITY:
        i = d.index_of(leaf.values[0])
        return [] if i < 0 else [(i, i + 1)]
    if op == FilterOperator.IN:
        ids = sorted({d.index_of(v) for v in leaf.values} - {-1})
        return [(i, i + 1) for i in ids]
    if op == FilterOperator.RANGE:
        lo, hi = d.range_to_id_interval(
            leaf.lower, leaf.upper, leaf.lower_inclusive,
            leaf.upper_inclusive)
        return [] if hi <= lo else [(lo, hi)]
    return None


def _prefix_select(segment, cube, leaves: List[FilterQueryTree]
                   ) -> Optional[Tuple[np.ndarray, int]]:
    """(selected row indices, rows examined) via sorted-prefix descent,
    or None when the leading split dimension is unconstrained (full scan
    is then the only option). Parity: StarTreeFilterOperator's
    depth-first child matching over OffHeapStarTreeNode, done as binary
    searches on the sorted dim lanes."""
    by_col: Dict[str, List[FilterQueryTree]] = {}
    for lf in leaves:
        by_col.setdefault(lf.column, []).append(lf)

    blocks: List[Tuple[int, int]] = [(0, cube.n_groups)]
    consumed: set = set()
    narrowed = False
    for dim in cube.dimensions:
        ivs = None
        src = None
        for lf in by_col.get(dim, ()):
            ivs = _leaf_id_intervals(lf, segment.data_source(dim))
            if ivs is not None:
                src = lf
                break
        if ivs is None:
            break                       # unconstrained dim: stop descent
        lane = cube.dim_ids[dim]
        new_blocks: List[Tuple[int, int]] = []
        if len(blocks) * max(len(ivs), 1) > _PREFIX_BLOCK_LIMIT:
            break
        dt = lane.dtype.type          # dim lanes are int32; ids fit
        for lo, hi in blocks:
            seg_lane = lane[lo:hi]
            for a, b in ivs:
                # dtype-matched scalars: a python-int key would make numpy
                # promote (copy+cast) the whole lane per call (~120x)
                s = lo + int(np.searchsorted(seg_lane, dt(a), side="left"))
                e = lo + int(np.searchsorted(seg_lane, dt(b), side="left"))
                if s < e:
                    new_blocks.append((s, e))
        blocks = new_blocks
        consumed.add(id(src))
        narrowed = True
        if not blocks:
            break
        if not all(b - a == 1 for a, b in ivs):
            # rows inside a multi-id block aren't sorted by deeper dims
            break
    if not narrowed:
        return None

    sel = (np.concatenate([np.arange(lo, hi, dtype=np.int64)
                           for lo, hi in blocks])
           if blocks else np.zeros(0, np.int64))
    examined = int(sel.size)
    residual = [lf for lf in leaves if id(lf) not in consumed]
    if residual and sel.size:
        from pinot_tpu.query import host_exec
        view = _SlicedCubeView(segment, cube, sel)
        m = np.ones(sel.size, dtype=bool)
        for lf in residual:
            m &= host_exec._eval_leaf(lf, view)
        sel = sel[m]
    return sel, examined


class _SlicedCubeView:
    """_CubeView restricted to a row subset (residual predicate eval)."""

    def __init__(self, segment, cube, sel: np.ndarray):
        self._segment = segment
        self._cube = cube
        self._sel = sel
        self.num_docs = int(sel.size)
        self.segment_name = segment.segment_name

    def has_column(self, col: str) -> bool:
        return col in self._cube.dim_ids

    def data_source(self, col: str) -> _CubeDataSource:
        return _CubeDataSource(self._segment.data_source(col),
                               self._cube.dim_ids[col][self._sel])


def _cube_select(segment, cube, tree: Optional[FilterQueryTree]
                 ) -> Tuple[np.ndarray, int]:
    """Selected cube row indices + rows-examined. Prefix descent when
    the filter is conjunctive and constrains the leading split dims;
    full member-gather scan otherwise. Raises for predicates the host
    evaluator can't resolve (callers fall back to the non-cube path)."""
    leaves = _conjunctive_leaves(tree)
    if leaves is not None and tree is not None:
        ps = _prefix_select(segment, cube, leaves)
        if ps is not None:
            return ps
    from pinot_tpu.query import host_exec
    view = _CubeView(segment, cube)
    mask = host_exec._eval_filter(tree, view)
    return np.nonzero(mask)[0], cube.n_groups  # tpulint: disable=host-sync -- mask is host numpy (host_exec filter eval)


def try_star_tree_execute(segment, request: BrokerRequest
                          ) -> Optional[IntermediateResultsBlock]:
    """Execute over a covering cube; None when not eligible."""
    if not getattr(segment, "star_trees", None):
        return None
    functions = make_functions(request.aggregations)
    cube = _eligible_cube(segment, request, functions)
    if cube is None:
        return None
    try:
        sel, examined = _cube_select(segment, cube, request.filter)
    except Exception:  # noqa: BLE001 — unresolvable predicate: fall back
        return None

    blk = IntermediateResultsBlock()
    counts = cube.counts
    matched_docs = int(counts[sel].sum())
    if request.is_group_by:
        _cube_group_by(segment, cube, request, functions, sel, blk)
    else:
        blk.agg_intermediates = [
            _cube_aggregate(cube, f, sel) for f in functions]
    blk.stats = ExecutionStats(
        num_docs_scanned=int(sel.size),           # groups, not raw docs —
        # parity: star-tree queries report aggregated doc counts
        num_entries_scanned_in_filter=examined,
        num_segments_processed=1,
        num_segments_matched=1 if matched_docs else 0,
        total_docs=segment.num_docs)
    return blk


def try_star_tree_execute_multi(segments, request: BrokerRequest
                                ) -> Optional[IntermediateResultsBlock]:
    """Vectorized cube execution across MANY segments at once.

    The per-segment path emits one group_map dict per segment and merges
    them entry-by-entry in Python — fine for two segments, dominant cost
    for many. Here the matched cube rows (decoded group values, counts,
    stat lanes) from every segment are concatenated and aggregated in one
    numpy group-by pass. Parity: the combine step of
    StarTreeAggregationExecutor outputs, done columnar.
    """
    if not request.is_aggregation or request.is_selection:
        return None
    functions = make_functions(request.aggregations)
    pairs = []
    for seg in segments:
        cube = _eligible_cube(seg, request, functions)
        if cube is None:
            return None                   # all segments must be covered
        pairs.append((seg, cube))

    gcols = list(request.group_by.columns) if request.group_by else []
    # per gcol: (union value table, per-segment local-id -> union-id LUTs)
    # — cached per (segment set, column); keeps the hot path free of
    # OBJECT-array uniques (python string compares dominated the q3.2
    # residual at 8 segments)
    unions = [_union_lut([seg for seg, _ in pairs], c) for c in gcols]
    code_chunks: List[List[np.ndarray]] = [[] for _ in gcols]
    cnt_chunks: List[np.ndarray] = []
    stat_chunks: Dict[str, List[np.ndarray]] = {}
    # each column's stat lanes exactly once per segment — two functions
    # over the same column (MIN(x), MAX(x)) must not double-append
    stat_cols = sorted({f.column for f in functions
                        if f.info.base != "COUNT"})
    total_docs = 0
    matched_groups = 0
    scanned = 0
    for si, (seg, cube) in enumerate(pairs):
        total_docs += seg.num_docs
        try:
            sel, examined = _cube_select(seg, cube, request.filter)
        except Exception:  # noqa: BLE001 — unresolvable predicate
            return None
        scanned += examined
        matched_groups += len(sel)
        cnt_chunks.append(cube.counts[sel])
        for i, c in enumerate(gcols):
            lut = unions[i][1][si]
            code_chunks[i].append(lut[cube.dim_ids[c][sel]])
        for col in stat_cols:
            stats = cube.metric_stats[col]
            for k in ("sum", "min", "max"):
                stat_chunks.setdefault(f"{col}.{k}", []).append(
                    stats[k][sel])

    counts = np.concatenate(cnt_chunks) if cnt_chunks else \
        np.zeros(0, np.int64)
    stats_cat = {k: np.concatenate(v) for k, v in stat_chunks.items()}
    blk = IntermediateResultsBlock()
    if not gcols:
        mask_all = np.ones(len(counts), dtype=bool)
        flat_cube = StarTreeCubeLike(counts, stats_cat)
        blk.agg_intermediates = [
            _cube_aggregate(flat_cube, f, mask_all) for f in functions]
    else:
        _multi_group_by([u[0] for u in unions], code_chunks, counts,
                        stats_cat, functions, blk)
        from pinot_tpu.query.combine import trim_group_map, trim_size_for
        t = trim_size_for(request.group_by.top_n)
        if len(blk.group_map) > 4 * t:
            # same memory/parity bound combine_blocks applies on the
            # per-segment path (AggregationGroupByTrimmingService)
            blk.group_map = trim_group_map(blk.group_map, functions, t)
    blk.stats = ExecutionStats(
        num_docs_scanned=matched_groups,
        num_entries_scanned_in_filter=scanned,
        num_segments_processed=len(segments),
        num_segments_matched=len(segments) if matched_groups else 0,
        total_docs=total_docs)
    return blk


class StarTreeCubeLike:
    """Concatenated cross-segment cube rows, shaped like a cube for
    _cube_aggregate."""

    def __init__(self, counts: np.ndarray, stats_cat: Dict[str, np.ndarray]):
        self.counts = counts
        self.metric_stats: Dict[str, Dict[str, np.ndarray]] = {}  # tpulint: disable=cache-bound -- keyed by metric column: bounded by the star-tree's metric set
        for k, arr in stats_cat.items():
            col, stat = k.rsplit(".", 1)
            self.metric_stats.setdefault(col, {})[stat] = arr


_UNION_LUT_CACHE: Dict = {}
_UNION_LUT_LOCK = threading.Lock()


def _segment_cache_identity(s, col: str):
    """Stable identity for one (segment, column) cache axis.

    id(s) is NOT stable: after a segment unload/reload the interpreter
    can reuse the address for the replacement segment, silently serving
    the OLD union LUT — wrong group-by values with no error. Name +
    num_docs + crc + dictionary fingerprint (cardinality and boundary
    values change whenever the value set changes) pin the entry to the
    segment artifact's contents instead of its transient address."""
    d = s.data_source(col).dictionary
    n = len(d)
    fingerprint = (n, str(d.values[0]), str(d.values[n - 1])) if n else (0,)
    md = getattr(s, "metadata", None)
    return (getattr(s, "segment_name", None), s.num_docs,
            getattr(md, "crc", None), fingerprint)


def _union_lut(segments, col: str):
    """(union value table, per-segment local-dictId -> union-id LUT).

    Cached per (segment identity tuple, column): the union merge and its
    object-array compares run once per segment set, leaving only int
    gathers on the query hot path."""
    key = (tuple(_segment_cache_identity(s, col) for s in segments), col)
    with _UNION_LUT_LOCK:
        hit = _UNION_LUT_CACHE.get(key)
    if hit is not None:
        return hit
    dicts = [np.asarray(s.data_source(col).dictionary.values)
             for s in segments]
    union = np.unique(np.concatenate(dicts)) if dicts else \
        np.zeros(0, object)
    luts = [np.searchsorted(union, d).astype(np.int64) for d in dicts]
    with _UNION_LUT_LOCK:
        if len(_UNION_LUT_CACHE) > 256:
            _UNION_LUT_CACHE.clear()
        _UNION_LUT_CACHE[key] = (union, luts)
    return union, luts


def _multi_group_by(uniq_vals, code_chunks, counts, stats_cat, functions,
                    blk) -> None:
    """Cross-segment group-by over UNION-id codes (int lanes only; the
    object-domain work happened once in _union_lut)."""
    n = len(counts)
    codes = [np.concatenate(chunks).astype(np.int64) if chunks else
             np.zeros(0, np.int64) for chunks in code_chunks]
    key = np.zeros(n, dtype=np.int64)
    for u, inv in zip(uniq_vals, codes):
        key = key * max(len(u), 1) + inv
    uniq_keys, inverse = np.unique(key, return_inverse=True)
    g = len(uniq_keys)

    value_cols = []
    rem = uniq_keys.copy()
    for u in reversed(uniq_vals):
        value_cols.append(u[rem % max(len(u), 1)])
        rem //= max(len(u), 1)
    value_cols.reverse()

    _fill_group_map(blk, functions, g, inverse, counts, value_cols,
                    lambda f, k: stats_cat[f"{f.column}.{k}"])


def _cube_aggregate(cube, f, sel: np.ndarray):
    """sel: selected row indices (or a boolean mask — fancy indexing
    treats both identically here)."""
    base = f.info.base
    mask = sel
    cnt = int(cube.counts[mask].sum())
    if base == "COUNT":
        return cnt
    if cnt == 0:
        return None
    stats = cube.metric_stats[f.column]
    if base == "SUM":
        return float(stats["sum"][mask].sum())
    if base == "AVG":
        return (float(stats["sum"][mask].sum()), cnt)
    if base == "MIN":
        return float(stats["min"][mask].min())
    if base == "MAX":
        return float(stats["max"][mask].max())
    if base == "MINMAXRANGE":
        return (float(stats["min"][mask].min()),
                float(stats["max"][mask].max()))
    raise ValueError(base)


def _cube_group_by(segment, cube, request, functions, sel: np.ndarray,
                   blk: IntermediateResultsBlock) -> None:
    gcols = request.group_by.columns
    lanes = [cube.dim_ids[c][sel].astype(np.int64) for c in gcols]
    cards = [segment.data_source(c).metadata.cardinality for c in gcols]
    key = np.zeros(len(sel), dtype=np.int64)
    for lane, card in zip(lanes, cards):
        key = key * card + lane
    uniq, inverse = np.unique(key, return_inverse=True)
    g = len(uniq)

    value_cols = []
    rem = uniq.copy()
    for c, card in zip(reversed(gcols), reversed(cards)):
        d = segment.data_source(c).dictionary
        value_cols.append(d.decode(rem % card))
        rem //= card
    value_cols.reverse()

    _fill_group_map(blk, functions, g, inverse, cube.counts[sel],
                    value_cols,
                    lambda f, k: cube.metric_stats[f.column][k][sel])


def _fill_group_map(blk: IntermediateResultsBlock, functions, g: int,
                    inverse: np.ndarray, row_counts: np.ndarray,
                    value_cols, stat_rows) -> None:
    """Shared group-by finisher for the single-segment and multi-segment
    cube paths: scatter matched cube rows into `g` group slots and emit
    the engine's standard intermediate formats (AVG = (sum, count),
    MINMAXRANGE = (min, max)). `stat_rows(f, kind)` yields the matched
    rows' "sum"/"min"/"max" lane for function f."""
    gcounts = np.zeros(g, dtype=np.int64)
    np.add.at(gcounts, inverse, row_counts)
    per_fn: List[List] = []
    for f in functions:
        base = f.info.base
        if base == "COUNT":
            per_fn.append([int(c) for c in gcounts])
            continue
        if base in ("SUM", "AVG"):
            sums = np.zeros(g)
            np.add.at(sums, inverse, stat_rows(f, "sum"))
            if base == "SUM":
                per_fn.append([float(s) for s in sums])
            else:
                per_fn.append([(float(s), int(c))
                               for s, c in zip(sums, gcounts)])
        else:
            mins = np.full(g, np.inf)
            maxs = np.full(g, -np.inf)
            np.minimum.at(mins, inverse, stat_rows(f, "min"))
            np.maximum.at(maxs, inverse, stat_rows(f, "max"))
            if base == "MIN":
                per_fn.append([float(v) for v in mins])
            elif base == "MAX":
                per_fn.append([float(v) for v in maxs])
            else:
                per_fn.append([(float(a), float(b))
                               for a, b in zip(mins, maxs)])
    # tolist() converts np scalars to python at C speed — the per-element
    # _plain/.item() genexpr was the profile's top fixed cost per query
    col_lists = [np.asarray(vc).tolist() for vc in value_cols]
    n_fn = len(functions)
    blk.group_map = {
        key: [per_fn[fi][i] for fi in range(n_fn)]
        for i, key in enumerate(zip(*col_lists))}
