"""Minimal asyncio HTTP/1.1 server with pattern routing.

Parity: the role Jersey/Grizzly (controller, broker REST) plays in the
reference — an embedded HTTP layer hosting resource handlers
(pinot-controller/.../api/ControllerAdminApiApplication.java,
pinot-broker/.../BrokerAdminApiApplication.java). Implemented directly on
asyncio (stdlib only — no external HTTP framework in the image): request
parsing with Content-Length bodies, keep-alive, `{name}` path captures,
JSON and binary responses.
"""
from __future__ import annotations

import asyncio
import json
import re
import urllib.parse
from typing import Awaitable, Callable, Dict, List, Optional, Tuple


class HttpRequest:
    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes,
                 path_params: Optional[Dict[str, str]] = None,
                 client: str = ""):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.path_params = path_params or {}
        self.client = client

    def json(self):
        return json.loads(self.body.decode("utf-8")) if self.body else None


class HttpResponse:
    def __init__(self, status: int = 200, body: bytes = b"",
                 content_type: str = "application/json",
                 headers: Optional[Dict[str, str]] = None):
        self.status = status
        self.body = body
        self.content_type = content_type
        # extra response headers (e.g. Retry-After on 429)
        self.headers = headers or {}

    @staticmethod
    def of_json(obj, status: int = 200,
                headers: Optional[Dict[str, str]] = None
                ) -> "HttpResponse":
        return HttpResponse(status, json.dumps(obj).encode("utf-8"),
                            headers=headers)

    @staticmethod
    def error(status: int, message: str) -> "HttpResponse":
        return HttpResponse.of_json({"error": message}, status)


Handler = Callable[[HttpRequest], Awaitable[HttpResponse]]


def metrics_response(registry, request: HttpRequest) -> HttpResponse:
    """The shared /metrics handler every component API mounts:
    Prometheus text exposition (obs/prometheus.py) by default, the
    legacy flat JSON snapshot behind ?format=json."""
    if request.query.get("format") == "json":
        return HttpResponse.of_json(registry.snapshot())
    from pinot_tpu.obs.prometheus import CONTENT_TYPE, render_prometheus
    return HttpResponse(200, render_prometheus(registry).encode("utf-8"),
                        content_type=CONTENT_TYPE)


class _PayloadTooLarge(Exception):
    pass

_REASONS = {200: "OK", 204: "No Content", 400: "Bad Request",
            403: "Forbidden", 404: "Not Found", 405: "Method Not Allowed",
            413: "Payload Too Large", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable"}


class HttpRouter:
    """(METHOD, "/path/{with}/{captures}") → async handler."""

    def __init__(self) -> None:
        self._routes: List[Tuple[str, re.Pattern, Handler]] = []

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        rx = re.compile(
            "^" + re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern) + "$")
        self._routes.append((method.upper(), rx, handler))

    def match(self, method: str, path: str
              ) -> Tuple[Optional[Handler], Dict[str, str], bool]:
        """→ (handler, path_params, path_exists)."""
        path_exists = False
        for m, rx, handler in self._routes:
            match = rx.match(path)
            if match:
                path_exists = True
                if m == method.upper():
                    return handler, {k: urllib.parse.unquote(v)
                                     for k, v in match.groupdict().items()
                                     }, True
        return None, {}, path_exists


class HttpServer:
    """Serves an HttpRouter on an asyncio event loop."""

    MAX_BODY = 512 * 1024 * 1024     # segments upload through this path

    def __init__(self, host: str, port: int, router: HttpRouter,
                 ssl_context=None):
        self.host = host
        self.port = port
        self.router = router
        self.ssl_context = ssl_context   # ssl.SSLContext → serve https
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # wait_closed() (3.12) waits for every open connection; an
            # idle keep-alive client would park it forever — cancel the
            # per-connection tasks so shutdown is prompt, then WAIT for
            # them to unwind (an abandoned cancelled task is destroyed
            # pending once the loop halts)
            tasks = list(self._conn_tasks)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            try:
                await self._server.wait_closed()
            except asyncio.CancelledError:
                pass
            self._server = None

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        client = f"{peer[0]}:{peer[1]}" if peer else ""
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            while True:
                try:
                    request = await self._read_request(reader, client)
                except _PayloadTooLarge:
                    await self._write_response(
                        writer, HttpResponse.error(413, "payload too "
                                                   "large"), keep=False)
                    break
                if request is None:
                    break
                response = await self._dispatch(request)
                keep = request.headers.get("connection", "").lower() \
                    != "close"
                await self._write_response(writer, response, keep)
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, ValueError):
            pass       # malformed request / oversized header line
        except asyncio.CancelledError:
            pass       # server shutdown cancelled this connection
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader: asyncio.StreamReader,
                            client: str) -> Optional[HttpRequest]:
        try:
            line = await reader.readline()
        except (ValueError, ConnectionError):
            return None
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, target, _version = line.decode("latin-1").split(None, 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            hl = await reader.readline()
            if hl in (b"\r\n", b"\n", b""):
                break
            if b":" in hl:
                k, v = hl.decode("latin-1").split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > self.MAX_BODY:
            raise _PayloadTooLarge
        body = await reader.readexactly(length) if length else b""
        parsed = urllib.parse.urlsplit(target)
        query = {k: v[0] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
        return HttpRequest(method.upper(), parsed.path, query, headers,
                           body, client=client)

    async def _dispatch(self, request: HttpRequest) -> HttpResponse:
        if request.method == "OPTIONS":
            # CORS preflight: browser clients (e.g. the controller's
            # query console) fetch the broker cross-origin
            return HttpResponse(204, b"", content_type="text/plain")
        handler, params, path_exists = self.router.match(
            request.method, request.path)
        if handler is None:
            if path_exists:
                return HttpResponse.error(405, "method not allowed")
            return HttpResponse.error(404, f"no such path: {request.path}")
        request.path_params = params
        try:
            return await handler(request)
        except Exception as e:  # noqa: BLE001 — handler error → 500 JSON
            return HttpResponse.error(500, f"{type(e).__name__}: {e}")

    async def _write_response(self, writer: asyncio.StreamWriter,
                              response: HttpResponse, keep: bool) -> None:
        reason = _REASONS.get(response.status, "Unknown")
        extra = "".join(f"{k}: {v}\r\n"
                        for k, v in response.headers.items())
        head = (f"HTTP/1.1 {response.status} {reason}\r\n"
                f"Content-Type: {response.content_type}\r\n"
                f"Content-Length: {len(response.body)}\r\n"
                "Access-Control-Allow-Origin: *\r\n"
                "Access-Control-Allow-Methods: "
                "GET, POST, DELETE, OPTIONS\r\n"
                "Access-Control-Allow-Headers: Content-Type\r\n"
                f"{extra}"
                f"Connection: {'keep-alive' if keep else 'close'}\r\n"
                "\r\n")
        writer.write(head.encode("latin-1") + response.body)
        await writer.drain()


class ApiServer:
    """Base lifecycle for an HTTP API: router on an event-loop thread.

    Subclasses populate the router in __init__ via self.router.add(...).
    """

    def __init__(self) -> None:
        from pinot_tpu.transport.tcp import EventLoopThread
        self.router = HttpRouter()
        self._loop_cls = EventLoopThread
        self._loop = None
        self._server: Optional[HttpServer] = None
        self.port: Optional[int] = None
        self.tls_config = None           # TlsConfig → serve https

    def start(self, host: str = "127.0.0.1", port: int = 0,
              tls_config=None) -> int:
        if tls_config is not None:
            self.tls_config = tls_config
        ssl_ctx = self.tls_config.server_context() \
            if self.tls_config is not None else None
        self._loop = self._loop_cls()
        self._server = HttpServer(host, port, self.router, ssl_ctx)
        self._loop.run(self._server.start())
        self.port = self._server.port
        return self.port

    def stop(self) -> None:
        if self._server is not None and self._loop is not None:
            self._loop.run(self._server.stop())
            self._server = None
        if self._loop is not None:
            self._loop.stop()
            self._loop = None
