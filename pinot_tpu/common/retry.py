"""Retry policies for transient remote-operation failures.

Parity: pinot-common/.../utils/retry/ — RetryPolicies.fixedDelayRetryPolicy /
exponentialBackoffRetryPolicy / randomDelayRetryPolicy and the
RetryPolicy.attempt contract (run the operation up to N times, sleeping
per policy between attempts, raising the last failure when exhausted).
Used by the segment fetch path (SegmentFetcherAndLoader's download
retries) and available to any remote client.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")


class RetryExhaustedError(Exception):
    """All attempts failed; __cause__ carries the last failure."""


class RetryPolicy:
    """attempts total tries; delay_for(i) seconds after failed try i."""

    def __init__(self, attempts: int):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.attempts = attempts

    def delay_for(self, attempt: int) -> float:
        raise NotImplementedError

    def attempt(self, op: Callable[[], T],
                retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                sleep: Callable[[float], None] = time.sleep) -> T:
        last: BaseException | None = None
        for i in range(self.attempts):
            try:
                return op()
            except retry_on as e:  # noqa: PERF203 — retry loop
                last = e
                if i + 1 < self.attempts:
                    sleep(self.delay_for(i))
        raise RetryExhaustedError(
            f"operation failed after {self.attempts} attempts: "
            f"{last!r}") from last


class FixedDelayRetryPolicy(RetryPolicy):
    def __init__(self, attempts: int, delay_s: float):
        super().__init__(attempts)
        self.delay_s = float(delay_s)

    def delay_for(self, attempt: int) -> float:
        return self.delay_s


class ExponentialBackoffRetryPolicy(RetryPolicy):
    """delay = initial * scale^attempt, uniformly jittered to [0.5, 1)x
    (the reference randomizes within the window to avoid thundering
    herds on a recovering endpoint)."""

    def __init__(self, attempts: int, initial_delay_s: float,
                 scale: float = 2.0, rng: random.Random | None = None):
        super().__init__(attempts)
        self.initial_delay_s = float(initial_delay_s)
        self.scale = float(scale)
        self._rng = rng or random.Random()

    def delay_for(self, attempt: int) -> float:
        window = self.initial_delay_s * (self.scale ** attempt)
        return window * (0.5 + 0.5 * self._rng.random())


class RandomDelayRetryPolicy(RetryPolicy):
    def __init__(self, attempts: int, min_delay_s: float,
                 max_delay_s: float, rng: random.Random | None = None):
        super().__init__(attempts)
        self.min_delay_s = float(min_delay_s)
        self.max_delay_s = float(max_delay_s)
        self._rng = rng or random.Random()

    def delay_for(self, attempt: int) -> float:
        return self._rng.uniform(self.min_delay_s, self.max_delay_s)
