"""Tenant management: tagged instances scope assignment and routing.

Parity: PinotHelixResourceManager.java:701,883,931 (createBrokerTenant /
createServerTenant / instance tag updates via TagNameUtils) and the REST
CRUD surface of PinotTenantRestletResource.java:80. Tag scheme mirrors
TagNameUtils:

    <tenant>_OFFLINE / <tenant>_REALTIME   server roles
    <tenant>_BROKER                        broker role

A table's ``tenants.server`` selects which instances its segments may be
assigned to (controller/manager.py consults :func:`server_tenant_tag`);
``tenants.broker`` selects which brokers serve it (the
``/BROKERRESOURCE/<table>`` record, watched by the client's dynamic
broker selector). A bare legacy tag (e.g. ``"DefaultTenant"``) counts as
the SERVER roles of that tenant (pre-tenant server participants register
it; brokers always self-register with explicit ``_BROKER`` tags), so
pre-tenant clusters keep working.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from pinot_tpu.controller.state_machine import LIVE

DEFAULT_TENANT = "DefaultTenant"
BROKER_RESOURCE = "/BROKERRESOURCE"

_ROLE_SUFFIXES = ("_OFFLINE", "_REALTIME", "_BROKER")


def server_tenant_tag(tenant: str, table_type: str = "OFFLINE") -> str:
    role = "REALTIME" if str(table_type).upper() == "REALTIME" else \
        "OFFLINE"
    return f"{tenant or DEFAULT_TENANT}_{role}"


def broker_tenant_tag(tenant: str) -> str:
    return f"{tenant or DEFAULT_TENANT}_BROKER"


def split_tag(tag: str):
    """(tenant, role) — role None for a bare legacy tag."""
    for suf in _ROLE_SUFFIXES:
        if tag.endswith(suf):
            return tag[:-len(suf)], suf[1:]
    return tag, None


def has_tag(tags: Iterable[str], wanted: str) -> bool:
    """Exact tag match, or a bare legacy tag covering the SERVER roles
    of its tenant (pre-tenant server participants register just
    "DefaultTenant"; brokers always self-register with _BROKER tags, so
    a bare tag never makes a server look like a broker)."""
    tags = list(tags or ())
    if wanted in tags:
        return True
    tenant, role = split_tag(wanted)
    return role in ("OFFLINE", "REALTIME") and tenant in tags


class TenantError(ValueError):
    pass


def live_instances_with_tag(store, tag: Optional[str]) -> List[str]:
    """THE canonical tag-filtered live-instance scan — used by both the
    coordinator's assignment path and the tenant REST views so tag
    semantics can't diverge."""
    out = []
    for inst in store.children(LIVE):
        rec = store.get(f"{LIVE}/{inst}") or {}
        if tag is None or has_tag(rec.get("tags", []), tag):
            out.append(inst)
    return sorted(out)


def _bare_server_tags(tenant: str) -> List[str]:
    """The explicit form of a bare legacy tag's coverage (both server
    roles) — used when an operation must strip the bare form but keep
    part of what it implied."""
    return [server_tenant_tag(tenant, "OFFLINE"),
            server_tenant_tag(tenant, "REALTIME")]


class TenantManager:
    """Tenant CRUD over live-instance tag records."""

    def __init__(self, store):
        self.store = store

    # -- tag plumbing ------------------------------------------------------
    def instance_tags(self, instance: str) -> List[str]:
        rec = self.store.get(f"{LIVE}/{instance}") or {}
        return list(rec.get("tags", []))

    def update_instance_tags(self, instance: str,
                             add: Iterable[str] = (),
                             remove: Iterable[str] = ()) -> List[str]:
        path = f"{LIVE}/{instance}"
        if self.store.get(path) is None:
            raise TenantError(f"instance {instance} is not live")

        def mut(rec):
            rec = dict(rec or {})
            tags = [t for t in rec.get("tags", []) if t not in set(remove)]
            for t in add:
                if t not in tags:
                    tags.append(t)
            rec["tags"] = tags
            return rec

        return self.store.update(path, mut)["tags"]

    def live_instances(self) -> List[str]:
        return sorted(self.store.children(LIVE))

    def instances_with_tag(self, tag: str) -> List[str]:
        return live_instances_with_tag(self.store, tag)

    # -- tenant CRUD (parity: PinotTenantRestletResource) ------------------
    def create_server_tenant(self, name: str,
                             instances: Iterable[str]) -> List[str]:
        """Tag instances with both server roles of the tenant (the
        reference splits offline/realtime counts; both-role tagging is
        its common single-tenant-server deployment)."""
        insts = list(instances)
        if not insts:
            raise TenantError("server tenant needs at least one instance")
        for inst in insts:
            # retagging takes the instance out of the default SERVER
            # pool (parity: the reference retags from the default tag)
            self.update_instance_tags(
                inst, add=[server_tenant_tag(name, "OFFLINE"),
                           server_tenant_tag(name, "REALTIME")],
                remove=() if name == DEFAULT_TENANT
                else (DEFAULT_TENANT,))
        return insts

    def create_broker_tenant(self, name: str,
                             instances: Iterable[str]) -> List[str]:
        insts = list(instances)
        if not insts:
            raise TenantError("broker tenant needs at least one instance")
        for inst in insts:
            add = [broker_tenant_tag(name)]
            remove = ()
            if name != DEFAULT_TENANT and \
                    DEFAULT_TENANT in self.instance_tags(inst):
                # the bare tag covered the server roles: keep them
                # explicit while leaving the default pool
                add += _bare_server_tags(DEFAULT_TENANT)
                remove = (DEFAULT_TENANT,)
            self.update_instance_tags(inst, add=add, remove=remove)
        return insts

    def tenants(self) -> Dict[str, List[str]]:
        """{"SERVER_TENANTS": [...], "BROKER_TENANTS": [...]}."""
        servers, brokers = set(), set()
        for inst in self.store.children(LIVE):
            for tag in self.instance_tags(inst):
                tenant, role = split_tag(tag)
                if role == "BROKER":
                    brokers.add(tenant)
                else:              # suffixed server tag or bare legacy
                    servers.add(tenant)
        return {"SERVER_TENANTS": sorted(servers),
                "BROKER_TENANTS": sorted(brokers)}

    def tenant_instances(self, name: str, role: str = "SERVER"
                         ) -> List[str]:
        if role.upper() == "BROKER":
            return self.instances_with_tag(broker_tenant_tag(name))
        return sorted(set(
            self.instances_with_tag(server_tenant_tag(name, "OFFLINE")) +
            self.instances_with_tag(server_tenant_tag(name, "REALTIME"))))

    def delete_tenant(self, name: str, role: str = "SERVER",
                      tables: Optional[Iterable[str]] = None) -> None:
        """Untag every instance; refused while a table still references
        the tenant (parity: the reference 409s on tenants in use)."""
        for table_cfg in tables or ():
            tc = table_cfg.tenant_config
            used = tc.broker if role.upper() == "BROKER" else tc.server
            if used == name:
                raise TenantError(
                    f"tenant {name} is in use by "
                    f"{table_cfg.table_name_with_type}")
        broker_role = role.upper() == "BROKER"
        remove = [broker_tenant_tag(name)] if broker_role else \
            [server_tenant_tag(name, "OFFLINE"),
             server_tenant_tag(name, "REALTIME")]
        for inst in self.store.children(LIVE):
            tags = self.instance_tags(inst)
            rm = [t for t in remove if t in tags]
            if not broker_role and name in tags:
                rm.append(name)       # bare legacy tag = server roles
            if rm:
                # an instance left with no tags would be orphaned out of
                # every pool — return it to the default pool OF ITS ROLE
                # (parity: the reference retags untagged instances to the
                # default; the bare tag means server roles only, so an
                # ex-broker gets the explicit default broker tag)
                add = []
                if not (set(tags) - set(rm)):
                    add = [broker_tenant_tag(DEFAULT_TENANT)] \
                        if broker_role else [DEFAULT_TENANT]
                self.update_instance_tags(inst, add=add, remove=rm)
