"""metrics-contract: the exposition surface is a declared contract.

Two checks, both protocol-tier (they need whole-tree knowledge):

- **Registration**: every metric NAME passed as a string literal to
  `metrics.meter/gauge/timer/peek_timer` must be a value declared in
  one of the metric enum classes in `common/metrics.py`. Those classes
  ARE the exposition contract — dashboards, alerts and the obs smoke
  test key on them; an ad-hoc literal name is a series that exists only
  where one call site happens to run, is invisible to review, and
  silently vanishes when that call site moves. (Table/cause SUFFIXES —
  the second argument — are intentionally free-form, mirroring the
  reference's table-level metrics.)

- **Gauge balance** (the `admissionQueueDepth` shape): a gauge exported
  via `set_callable(lambda: self.<attr>)` over a counter attribute that
  some method increments must have a balancing decrement somewhere in
  the class — and when the increment and decrement live in the SAME
  method with raising-capable calls between them, the decrement must
  sit in a `finally`/`except` block, or the first exception leaks the
  depth forever (the gauge drifts up until the capacity watermark sheds
  everything). Cross-method pairings (inc in `admit`, dec in `release`
  wired through a future callback) are the caller's contract and are
  left to review — this rule pins down the two shapes it can prove.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from pinot_tpu.analysis.core import Finding, Rule, register
from pinot_tpu.analysis.rules.durability import repo_sources, unsuppressed

METRICS_DECL_FILE = "pinot_tpu/common/metrics.py"

_METRIC_FACTORIES = ("meter", "gauge", "timer", "peek_timer")

#: trees whose metric call sites the registration check audits
SCAN_PATHS = ("pinot_tpu",)
_EXCLUDED_PREFIXES = ("pinot_tpu/analysis/",)


from pinot_tpu.analysis.astutil import safe_unparse as _u  # noqa: E402


def declared_metric_names(source: str) -> Set[str]:
    """Every string constant assigned at class level in the metric enum
    classes of common/metrics.py (Meter/Gauge/Timer/QueryPhase)."""
    names: Set[str] = set()
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not node.name.endswith(("Meter", "Gauge", "Timer",
                                   "QueryPhase", "Phase")):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Constant) and \
                    isinstance(stmt.value.value, str):
                names.add(stmt.value.value)
    return names


def check_registration(sources: Dict[str, str],
                       declared: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(sources):
        if path == METRICS_DECL_FILE or \
                any(path.startswith(p) for p in _EXCLUDED_PREFIXES):
            continue
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr in _METRIC_FACTORIES and node.args):
                continue
            receiver = _u(node.func.value).lower()
            if "metric" not in receiver and "registry" not in receiver:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    arg.value not in declared:
                findings.append(Finding(
                    path, node.lineno, "metrics-contract",
                    f"metric name {arg.value!r} is not declared in "
                    "common/metrics.py — the exposition contract "
                    "(dashboards, obs smoke) cannot see it; declare a "
                    "constant in the component's enum class"))
    return findings


# ---------------------------------------------------------------------------
# Gauge balance
# ---------------------------------------------------------------------------


def _gauge_backed_attrs(cls: ast.ClassDef) -> Dict[str, int]:
    """attr -> line for gauges exported as `set_callable(lambda:
    self.<attr>)` (the live counter shape; method refs are snapshots,
    not counters, and are skipped)."""
    out: Dict[str, int] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == "set_callable" and node.args):
            continue
        if not (isinstance(node.func.value, ast.Call) and
                isinstance(node.func.value.func, ast.Attribute) and
                node.func.value.func.attr == "gauge"):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Lambda) and \
                isinstance(arg.body, ast.Attribute) and \
                isinstance(arg.body.value, ast.Name) and \
                arg.body.value.id == "self":
            out[arg.body.attr] = node.lineno
    return out


def _writes_of(method: ast.AST, attr: str
               ) -> List[Tuple[str, int, ast.AST]]:
    """('inc'|'dec', line, node) for every +/- write of self.<attr>."""
    out = []
    target = f"self.{attr}"
    for node in ast.walk(method):
        if isinstance(node, ast.AugAssign) and _u(node.target) == target:
            op = "inc" if isinstance(node.op, ast.Add) else "dec"
            out.append((op, node.lineno, node))
        elif isinstance(node, ast.Assign) and \
                _u(node.targets[0]) == target:
            text = _u(node.value)
            if "+ 1" in text or "+1" in text:
                out.append(("inc", node.lineno, node))
            elif "- 1" in text or "-1" in text:
                out.append(("dec", node.lineno, node))
    return out


def _in_handler_or_finally(method: ast.AST, node: ast.AST) -> bool:
    for t in ast.walk(method):
        if isinstance(t, ast.Try):
            for blk in list(t.finalbody) + \
                    [s for h in t.handlers for s in h.body]:
                if node is blk or any(node is d for d in ast.walk(blk)):
                    return True
    return False


def check_gauge_balance(sources: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(sources):
        if any(path.startswith(p) for p in _EXCLUDED_PREFIXES):
            continue
        try:
            tree = ast.parse(sources[path], filename=path)
        except SyntaxError:
            continue
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for attr, decl_line in sorted(
                    _gauge_backed_attrs(cls).items()):
                methods = [m for m in cls.body if isinstance(
                    m, (ast.FunctionDef, ast.AsyncFunctionDef))]
                incs, decs = [], []
                for m in methods:
                    for op, line, node in _writes_of(m, attr):
                        (incs if op == "inc" else decs).append(
                            (m, line, node))
                if incs and not decs:
                    m, line, _n = incs[0]
                    findings.append(Finding(
                        path, line, "metrics-contract",
                        f"gauge-backed counter `self.{attr}` is "
                        f"incremented in `{m.name}` but never "
                        "decremented anywhere in "
                        f"`{cls.name}` — the exported depth can only "
                        "drift up"))
                    continue
                # same-method pairs: the dec must survive exceptions.
                # Risky = a call strictly BETWEEN the increment and the
                # first following decrement — calls after the pair has
                # already balanced (trailing logging etc.) cannot leak
                for m in methods:
                    writes = _writes_of(m, attr)
                    m_incs = [w for w in writes if w[0] == "inc"]
                    m_decs = [w for w in writes if w[0] == "dec"]
                    if not (m_incs and m_decs):
                        continue
                    inc_line = min(w[1] for w in m_incs)
                    dec_after = [w[1] for w in m_decs if w[1] > inc_line]
                    dec_line = min(dec_after) if dec_after else \
                        max(getattr(n, "lineno", 0) for n in ast.walk(m))
                    risky = any(isinstance(n, ast.Call) and
                                inc_line < getattr(n, "lineno", 0)
                                < dec_line
                                for n in ast.walk(m))
                    if risky and not any(
                            _in_handler_or_finally(m, w[2])
                            for w in m_decs):
                        findings.append(Finding(
                            path, m_decs[0][1], "metrics-contract",
                            f"`{cls.name}.{m.name}` increments "
                            f"gauge-backed `self.{attr}` and "
                            "decrements it on the success path only — "
                            "an exception between the two leaks the "
                            "depth forever; put the balancing write in "
                            "a finally block"))
    return findings


@register
class MetricsContractRule(Rule):
    id = "metrics-contract"
    description = ("metric names must be declared in common/metrics.py; "
                   "gauge-backed counters must balance on exception "
                   "paths (protocol tier)")
    tier = "protocol"

    def check(self, ctx) -> Iterator[Finding]:
        return iter(())

    def check_global(self,
                     sources: Optional[Dict[str, str]] = None
                     ) -> List[Finding]:
        srcs = repo_sources(SCAN_PATHS, sources)
        decl_src = srcs.get(METRICS_DECL_FILE)
        findings: List[Finding] = []
        if decl_src is None:
            findings.append(Finding(
                METRICS_DECL_FILE, 1, self.id,
                "metric declaration module not found — the "
                "registration check has no contract to verify"))
            declared: Set[str] = set()
        else:
            declared = declared_metric_names(decl_src)
        findings += check_registration(srcs, declared)
        findings += check_gauge_balance(srcs)
        return unsuppressed(findings, srcs)
