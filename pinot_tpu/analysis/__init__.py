"""tpulint — JAX-aware static analysis for the pinot_tpu codebase.

The performance-native components of this datastore (columnar scan,
bitmap intersection, hash group-by, star-tree traversal) are XLA
kernels, so the correctness-and-speed story hinges on JAX-specific
hazards the reference Java codebase never had:

- silent device→host transfers on the kernel path (``host-sync``)
- retracing / recompilation storms from unhashable or mutable jit
  inputs (``retrace``)
- 64-bit literals silently downcast when x64 is disabled, and int32
  doc-id arithmetic that can overflow (``dtype-drift``)
- server/realtime class state mutated across threads without a held
  lock (``concurrency``)
- JAX symbols absent from the installed version or on a deprecation
  denylist — the exact class of break that took out the seed's 33
  shard_map tests (``api-compat``)

Usage::

    python -m pinot_tpu.analysis pinot_tpu/            # lint the tree
    python -m pinot_tpu.analysis --write-baseline ...  # grandfather
    # per-line:  <code>  # tpulint: disable=host-sync -- reason
    # per-file:  # tpulint: disable-file=concurrency -- reason

See docs/ANALYSIS.md for the rule catalogue and baseline workflow.
"""
from pinot_tpu.analysis.core import (AnalysisConfig, Finding, Rule,
                                     all_rules, load_baseline,
                                     write_baseline)
from pinot_tpu.analysis.runner import (AnalysisResult, analyze_paths,
                                       analyze_source, diff_baseline)

__all__ = [
    "AnalysisConfig", "AnalysisResult", "Finding", "Rule", "all_rules",
    "analyze_paths", "analyze_source", "diff_baseline", "load_baseline",
    "write_baseline",
]
