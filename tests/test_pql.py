"""PQL compiler unit tests (reference tier:
pinot-common/src/test/.../pql/parsers/Pql2CompilerTest).
"""
import pytest

from pinot_tpu.common.request import FilterOperator
from pinot_tpu.pql.lexer import PqlSyntaxError
from pinot_tpu.pql.optimizer import BrokerRequestOptimizer
from pinot_tpu.pql.parser import compile_pql


def test_aggregation_query_shape():
    q = compile_pql("SELECT SUM(a), COUNT(*) FROM t WHERE x = 3 "
                    "GROUP BY g1, g2 TOP 42")
    assert q.table_name == "t"
    assert [a.function_name for a in q.aggregations] == ["SUM", "COUNT"]
    assert q.group_by.columns == ["g1", "g2"]
    assert q.group_by.top_n == 42
    assert q.filter.operator == FilterOperator.EQUALITY


def test_selection_query_shape():
    q = compile_pql("SELECT a, b FROM t ORDER BY a DESC, b LIMIT 5, 20")
    s = q.selection
    assert s.columns == ["a", "b"]
    assert s.offset == 5 and s.size == 20
    assert [(o.column, o.ascending) for o in s.order_by] == \
        [("a", False), ("b", True)]


def test_comparison_operators_map_to_ranges():
    for op, lower, upper, li, ui in [
            (">", "5", None, False, True), (">=", "5", None, True, True),
            ("<", None, "5", True, False), ("<=", None, "5", True, True)]:
        q = compile_pql(f"SELECT COUNT(*) FROM t WHERE x {op} 5")
        f = q.filter
        assert f.operator == FilterOperator.RANGE
        assert f.lower == lower and f.upper == upper
        assert f.lower_inclusive == li and f.upper_inclusive == ui


def test_optimizer_or_eq_to_in_and_flatten():
    q = compile_pql("SELECT COUNT(*) FROM t WHERE (a = 1 OR a = 2 OR a = 3) "
                    "AND (b = 'x' AND c > 0)")
    q = BrokerRequestOptimizer().optimize(q)
    assert q.filter.operator == FilterOperator.AND
    kinds = sorted(c.operator.value for c in q.filter.children)
    assert kinds == ["EQUALITY", "IN", "RANGE"]


def test_optimizer_range_merge():
    q = compile_pql("SELECT COUNT(*) FROM t WHERE x > 2 AND x <= 10")
    q = BrokerRequestOptimizer().optimize(q)
    f = q.filter
    assert f.operator == FilterOperator.RANGE
    assert f.lower == "2" and not f.lower_inclusive
    assert f.upper == "10" and f.upper_inclusive


def test_having_tree():
    q = compile_pql("SELECT SUM(a) FROM t GROUP BY g HAVING SUM(a) > 10 "
                    "AND SUM(a) <= 20")
    h = q.having
    assert h.operator == FilterOperator.AND
    assert len(h.children) == 2
    assert h.children[0].agg.function_name == "SUM"


def test_syntax_errors():
    for bad in ["SELECT", "SELECT a FROM", "SELECT a FROM t WHERE",
                "SELECT a, SUM(b) FROM t"]:
        with pytest.raises(PqlSyntaxError):
            compile_pql(bad)
