"""Multi-process-shaped cluster: planes joined only through the store.

Parity: the reference's production deployment — controller, servers and
broker as separate processes around ZooKeeper.  Here each plane is wired
exactly as its process entrypoint wires it (tools/distributed.py), and
every interaction crosses real TCP: cluster state through the store
server (watches, ephemerals), queries through the framed data plane.
Covers the MultiNodesOfflineClusterIntegrationTest + instance-death
recovery (ChaosMonkey pattern: a killed server's ephemeral session drops
it from the external view and queries keep answering).
"""
import os
import tempfile
import time

import numpy as np
import pytest

from fixtures import build_segment, make_columns, make_schema, \
    make_table_config
from oracle import Oracle

from pinot_tpu.common.table_config import SegmentsConfig
from pinot_tpu.tools.distributed import (DistributedBroker,
                                         DistributedController,
                                         DistributedServer)

N = 4_000


def _await(cond, timeout=10.0, msg=""):
    from test_realtime import wait_until
    assert wait_until(cond, timeout=timeout, interval=0.02), \
        f"timed out: {msg}"


@pytest.fixture(scope="module")
def cluster():
    base = tempfile.mkdtemp()
    ctrl = DistributedController(base)
    servers = [
        DistributedServer(f"Server_{i}", "127.0.0.1", ctrl.store_port,
                          ctrl.deep_store_dir,
                          work_dir=os.path.join(base, f"s{i}_work"))
        for i in range(2)]
    broker = DistributedBroker("127.0.0.1", ctrl.store_port,
                               ctrl.deep_store_dir)
    # data: 4 segments, replication 2 so both servers host every segment
    cols_all = []
    ctrl.controller.manager.add_schema(make_schema())
    cfg = make_table_config(
        segments_config=SegmentsConfig(replication=2))
    ctrl.controller.manager.add_table(cfg)
    for i in range(4):
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d)
        _, cols = build_segment(d, n=N, seed=100 + i, name=f"dseg_{i}")
        cols_all.append(cols)
        ctrl.controller.manager.add_segment("baseballStats_OFFLINE", d)
    merged = {}
    for k in cols_all[0]:
        if isinstance(cols_all[0][k], list):
            merged[k] = sum((c[k] for c in cols_all), [])
        else:
            merged[k] = np.concatenate([c[k] for c in cols_all])
    yield ctrl, servers, broker, Oracle(merged)
    broker.stop()
    for s in servers:
        try:
            s.stop()
        except Exception:  # noqa: BLE001 — killed servers can't deregister
            pass
    ctrl.stop()


def test_segments_load_via_store_watches(cluster):
    ctrl, servers, broker, oracle = cluster
    # both server processes must converge to hosting all 4 segments
    for s in servers:
        _await(lambda: len(
            s.server.data_manager.table("baseballStats_OFFLINE",
                                        create=True).segment_names()) == 4,
            timeout=30, msg=f"{s.agent.instance_id} segment load")
    # the external view converges asynchronously after the servers report
    # their current states over the networked store — wait for it too
    def _ev_converged():
        view = ctrl.controller.coordinator.external_view(
            "baseballStats_OFFLINE")
        return len(view.segment_states) == 4 and all(
            set(states.values()) == {"ONLINE"} and len(states) == 2
            for states in view.segment_states.values())
    _await(_ev_converged, timeout=30, msg="external view convergence")


def test_query_through_remote_planes(cluster):
    ctrl, servers, broker, oracle = cluster
    _await(lambda: broker.watcher.routing.has_table(
        "baseballStats_OFFLINE"), msg="routing table")
    m = oracle.mask(lambda r: r["yearID"] > 2000)
    resp = None
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        resp = broker.query(
            "SELECT COUNT(*), SUM(runs) FROM baseballStats "
            "WHERE yearID > 2000")
        if not resp.exceptions and \
                int(resp.aggregation_results[0].value) == oracle.count(m):
            break
        time.sleep(0.05)
    assert int(resp.aggregation_results[0].value) == oracle.count(m)
    assert float(resp.aggregation_results[1].value) == \
        pytest.approx(oracle.sum("runs", m))
    assert resp.num_servers_queried >= 1

    g = broker.query("SELECT COUNT(*) FROM baseballStats "
                     "GROUP BY league TOP 10")
    got = {r["group"][0]: int(r["value"])
           for r in g.aggregation_results[0].group_by_result}
    exp = oracle.group_by(["league"], oracle.mask(lambda r: True),
                          ("count", None))
    assert got == {k[0]: v for k, v in exp.items()}


def test_server_death_drops_ephemerals_and_queries_survive(cluster):
    ctrl, servers, broker, oracle = cluster
    _await(lambda: broker.watcher.routing.has_table(
        "baseballStats_OFFLINE"), msg="routing table")
    victim = servers[1]
    victim.kill()          # no deregistration: session death only
    store = ctrl.store
    _await(lambda: store.get(
        f"/LIVEINSTANCES/{victim.agent.instance_id}") is None,
        msg="ephemeral live record reaped")
    _await(lambda: all(
        victim.agent.instance_id not in states
        for states in ctrl.controller.coordinator.external_view(
            "baseballStats_OFFLINE").segment_states.values()),
        msg="external view drops dead instance")
    # broker rerouted onto the survivor: full, correct answers
    m = oracle.mask(lambda r: True)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        resp = broker.query("SELECT COUNT(*) FROM baseballStats")
        if not resp.exceptions and \
                int(resp.aggregation_results[0].value) == oracle.count(m):
            break
        time.sleep(0.05)
    assert int(resp.aggregation_results[0].value) == oracle.count(m)


def test_nonhttp_broker_registers_for_quota_division(cluster):
    """Per-broker quota shares divide the cluster rate by the live
    *_BROKER records — a broker without an HTTP API must still
    register (tag-only, no endpoint) or the division under-counts and
    the cluster admits above the configured quota."""
    ctrl, servers, broker, oracle = cluster
    rec = broker.store.get(f"/LIVEINSTANCES/{broker.instance_id}")
    assert rec is not None and any(
        str(t).endswith("_BROKER") for t in rec["tags"])
    assert "host" not in rec        # no endpoint advertised to clients
    assert broker._num_live_brokers() == 1
    b2 = DistributedBroker("127.0.0.1", ctrl.store_port,
                           ctrl.deep_store_dir)
    try:
        # the count is maintained from the live watch stream (O(1) on
        # the hot view path), so join visibility is async
        _await(lambda: broker._num_live_brokers() == 2,
               msg="incumbent sees the joining broker")
        assert b2._num_live_brokers() == 2   # self + watched incumbent
    finally:
        b2.stop()
    _await(lambda: broker._num_live_brokers() == 1,
           msg="graceful stop deregisters")


def test_graceful_server_stop_deregisters(cluster):
    ctrl, servers, broker, oracle = cluster
    # runs last (module order): stop the remaining server gracefully
    survivor = servers[0]
    survivor.stop()
    store = ctrl.store
    assert store.get(f"/LIVEINSTANCES/{survivor.agent.instance_id}") is None
    assert store.list_paths(
        f"/CURRENTSTATES/{survivor.agent.instance_id}/") == []
    _await(lambda: ctrl.controller.coordinator.external_view(
        "baseballStats_OFFLINE").segment_states == {},
        msg="view empties after last server departs")


# ---------------------------------------------------------------------------
# True multi-process deployment: admin CLI process entrypoints, every
# interaction over TCP/HTTP (parity: StartController/Server/BrokerCommand)
# ---------------------------------------------------------------------------

def test_three_process_cluster_over_cli():
    import json
    import subprocess
    import sys
    import urllib.request

    base = tempfile.mkdtemp()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    procs = []

    def spawn(*cmd):
        p = subprocess.Popen([sys.executable, "-m",
                              "pinot_tpu.tools.admin", *cmd],
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             env=env, cwd="/root/repo", text=True)
        procs.append(p)
        line = p.stdout.readline().strip()
        assert line, (p.stderr.read() if p.poll() is not None else "no boot line")
        return json.loads(line)

    def http(method, url, body=None, ctype="application/json"):
        req = urllib.request.Request(
            url, data=body, method=method,
            headers={"Content-Type": ctype} if body else {})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        ctrl = spawn("StartController", "--dir", base, "--store-port", "0")
        store = f"127.0.0.1:{ctrl['storePort']}"
        deep = ctrl["deepStore"]
        spawn("StartServer", "--store", store, "--deep-store", deep,
              "--instance-id", "Server_A")
        broker = spawn("StartBroker", "--store", store, "--deep-store",
                       deep)

        capi = f"http://127.0.0.1:{ctrl['httpPort']}"
        http("POST", f"{capi}/schemas",
             json.dumps(make_schema().to_json()).encode())
        http("POST", f"{capi}/tables",
             json.dumps(make_table_config().to_json()).encode())
        seg_dir = os.path.join(base, "seg")
        os.makedirs(seg_dir)
        _, cols = build_segment(seg_dir, n=1_000, seed=3, name="cli_seg")
        from pinot_tpu.controller.http_api import pack_segment_dir
        http("POST", f"{capi}/segments/baseballStats_OFFLINE",
             pack_segment_dir(seg_dir), ctype="application/octet-stream")

        oracle = Oracle(cols)
        m = oracle.mask(lambda r: r["yearID"] >= 2000)
        bapi = f"http://127.0.0.1:{broker['httpPort']}"
        deadline = time.monotonic() + 30
        out = None
        while time.monotonic() < deadline:
            try:
                out = http("POST", f"{bapi}/query", json.dumps(
                    {"pql": "SELECT COUNT(*) FROM baseballStats "
                            "WHERE yearID >= 2000"}).encode())
                if not out.get("exceptions") and \
                        out["aggregationResults"][0]["value"] == \
                        str(oracle.count(m)):
                    break
            except Exception:
                pass
            time.sleep(0.2)
        assert out is not None
        assert out["aggregationResults"][0]["value"] == \
            str(oracle.count(m)), out
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# ---------------------------------------------------------------------------
# Realtime over the multi-process shape: LLC completion protocol via the
# controller's HTTP API (parity: ServerSegmentCompletionProtocolHandler →
# LLCSegmentCompletionHandlers), stream consumption on the server process,
# segment build + split-commit upload, CONSUMING→ONLINE via store watches.
# ---------------------------------------------------------------------------

def test_distributed_realtime_consume_commit_requery():
    from test_realtime import make_rows, rt_config, wait_until
    from pinot_tpu.realtime import registry
    from pinot_tpu.realtime.stream import (MemoryStream,
                                           MemoryStreamConsumerFactory)

    base = tempfile.mkdtemp()
    stream = MemoryStream("topic_dist", num_partitions=2)
    registry.register_stream_factory(
        "mem_dist", MemoryStreamConsumerFactory(stream, batch_size=64))
    ctrl = DistributedController(base, http=True)
    server = DistributedServer(
        "Server_rt", "127.0.0.1", ctrl.store_port, ctrl.deep_store_dir,
        work_dir=os.path.join(base, "rt_work"),
        controller_http=f"127.0.0.1:{ctrl.http_port}")
    broker = DistributedBroker("127.0.0.1", ctrl.store_port,
                               ctrl.deep_store_dir)
    try:
        ctrl.controller.manager.add_schema(make_schema())
        ctrl.controller.realtime.setup_table(
            rt_config("mem_dist", "topic_dist", flush_rows=300))
        rows = make_rows(800, seed=9)

        def count():
            resp = broker.query("SELECT COUNT(*) FROM baseballStats")
            return -1 if resp.exceptions else \
                int(resp.aggregation_results[0].value)

        # mid-consumption (below flush threshold)
        for i, r in enumerate(rows[:200]):
            stream.publish(r, partition=i % 2)
        assert wait_until(lambda: count() == 200)

        # cross the threshold: build → HTTP split-commit upload →
        # CONSUMING→ONLINE → rollover; nothing lost or duplicated
        for i, r in enumerate(rows[200:]):
            stream.publish(r, partition=(200 + i) % 2)
        mgr = ctrl.controller.manager

        def done():
            return [s for s in mgr.segment_names("baseballStats_REALTIME")
                    if (mgr.segment_metadata("baseballStats_REALTIME", s)
                        or {}).get("status") == "DONE"]

        assert wait_until(lambda: len(done()) >= 2, timeout=30)
        assert wait_until(lambda: count() == 800, timeout=30)
        exp = sum(r["runs"] for r in rows)
        resp = broker.query("SELECT SUM(runs) FROM baseballStats")
        assert float(resp.aggregation_results[0].value) == exp
        # committed artifacts came through the HTTP upload into deep store
        for name in done():
            meta = mgr.segment_metadata("baseballStats_REALTIME", name)
            assert meta["downloadPath"].startswith(ctrl.deep_store_dir)
            assert os.path.isdir(meta["downloadPath"])
    finally:
        registry.unregister_stream_factory("mem_dist")
        broker.stop()
        server.stop()
        ctrl.stop()


# ---------------------------------------------------------------------------
# Cross-process stream connector: the consuming server is a SEPARATE OS
# process reading the stream over TCP (parity: the reference proves its
# stream SPI with the out-of-process Kafka connector —
# KafkaPartitionLevelConsumer.java). The server process is kill -9'd
# mid-consumption and a replacement resumes from the last committed
# offsets: nothing lost, nothing duplicated.
# ---------------------------------------------------------------------------

def test_crossprocess_realtime_tcp_stream_kill_restart():
    import json
    import signal
    import subprocess
    import sys
    import urllib.request

    from test_realtime import make_rows, wait_until
    from pinot_tpu.common.table_config import (IndexingConfig, TableConfig,
                                               TableType)
    from pinot_tpu.realtime.tcp_stream import TcpTopicClient, TcpTopicServer

    topic_srv = TcpTopicServer()
    tport = topic_srv.start()
    topic_srv.create_topic("t_xproc", 2)
    pub = TcpTopicClient("127.0.0.1", tport)

    base = tempfile.mkdtemp()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
    procs = []

    def spawn(*cmd):
        p = subprocess.Popen([sys.executable, "-m",
                              "pinot_tpu.tools.admin", *cmd],
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             env=env, cwd="/root/repo", text=True)
        procs.append(p)
        line = p.stdout.readline().strip()
        assert line, (p.stderr.read() if p.poll() is not None
                      else "no boot line")
        return p, json.loads(line)

    def http(method, url, body=None):
        req = urllib.request.Request(
            url, data=body, method=method,
            headers={"Content-Type": "application/json"} if body else {})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        _, ctrl = spawn("StartController", "--dir", base,
                        "--store-port", "0")
        store = f"127.0.0.1:{ctrl['storePort']}"
        deep = ctrl["deepStore"]
        chttp = f"127.0.0.1:{ctrl['httpPort']}"

        def start_server():
            p, _ = spawn("StartServer", "--store", store, "--deep-store",
                         deep, "--instance-id", "Server_XRT",
                         "--controller-http", chttp,
                         "--dir", os.path.join(base, "xrt_work"))
            return p

        srv_proc = start_server()
        _, broker = spawn("StartBroker", "--store", store,
                          "--deep-store", deep)

        capi = f"http://127.0.0.1:{ctrl['httpPort']}"
        http("POST", f"{capi}/schemas",
             json.dumps(make_schema().to_json()).encode())
        cfg = TableConfig(
            "baseballStats", table_type=TableType.REALTIME,
            indexing_config=IndexingConfig(
                no_dictionary_columns=["salary"],
                stream_configs={
                    "stream.factory.name": "tcp",
                    "stream.topic.name": "t_xproc",
                    "stream.tcp.host": "127.0.0.1",
                    "stream.tcp.port": str(tport),
                    "realtime.segment.flush.threshold.size": "300",
                    "realtime.segment.flush.threshold.time.ms": "600000000",
                }),
            segments_config=SegmentsConfig(replication=1,
                                           time_column_name="yearID"))
        http("POST", f"{capi}/tables", json.dumps(cfg.to_json()).encode())

        bapi = f"http://127.0.0.1:{broker['httpPort']}"

        def agg(pql):
            try:
                out = http("POST", f"{bapi}/query",
                           json.dumps({"pql": pql}).encode())
            except Exception:  # noqa: BLE001 — broker still booting
                return None
            if out.get("exceptions"):
                return None
            return out["aggregationResults"][0]["value"]

        rows = make_rows(800, seed=21)
        for i, r in enumerate(rows[:200]):
            pub.publish_row("t_xproc", r, partition=i % 2)
        # rows published by THIS process are served by the consuming
        # segments of the REMOTE server process
        assert wait_until(
            lambda: agg("SELECT COUNT(*) FROM baseballStats") == "200",
            timeout=60), "remote consuming segments never served the rows"

        # kill -9 mid-consumption (no deregistration, no flush)
        srv_proc.send_signal(signal.SIGKILL)
        srv_proc.wait(timeout=10)
        for i, r in enumerate(rows[200:]):
            pub.publish_row("t_xproc", r, partition=(200 + i) % 2)

        # a replacement server process resumes from the last committed
        # offsets — exactly-once totals prove no loss and no duplication
        srv_proc = start_server()
        exp_sum = float(sum(r["runs"] for r in rows))
        assert wait_until(
            lambda: agg("SELECT COUNT(*) FROM baseballStats") == "800",
            timeout=90), "replacement server did not recover all rows"
        got = agg("SELECT SUM(runs) FROM baseballStats")
        assert got is not None and float(got) == exp_sum, (got, exp_sum)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        pub.close()
        topic_srv.stop()


# ---------------------------------------------------------------------------
# Dynamic broker selection + prepared statements (client API completeness)
# ---------------------------------------------------------------------------


def test_dynamic_broker_selector_survives_broker_kill(tmp_path):
    """VERDICT done-condition: the client keeps querying across a broker
    kill/restart with no reconfiguration (DynamicBrokerSelector.java:41
    parity over the property store)."""
    from pinot_tpu.client.connection import (PinotClientError,
                                             connect_dynamic)

    base = str(tmp_path)
    ctrl = DistributedController(base)
    server = DistributedServer("Server_0", "127.0.0.1", ctrl.store_port,
                               ctrl.deep_store_dir,
                               work_dir=os.path.join(base, "s0_work"))
    b1 = DistributedBroker("127.0.0.1", ctrl.store_port,
                           ctrl.deep_store_dir, http=True,
                           instance_id="Broker_1")
    b2 = DistributedBroker("127.0.0.1", ctrl.store_port,
                           ctrl.deep_store_dir, http=True,
                           instance_id="Broker_2")
    conn = None
    try:
        ctrl.controller.manager.add_schema(make_schema())
        cfg = make_table_config()
        ctrl.controller.manager.add_table(cfg)
        d = os.path.join(base, "seg0")
        os.makedirs(d)
        _, cols = build_segment(d, n=2000, seed=7, name="dynseg")
        ctrl.controller.manager.add_segment("baseballStats_OFFLINE", d)

        conn = connect_dynamic("127.0.0.1", ctrl.store_port)
        sel = conn._selector
        _await(lambda: len(sel.live_brokers()) == 2, msg="2 brokers seen")
        # /BROKERRESOURCE carries the table→broker mapping
        assert set(ctrl.controller.manager.refresh_broker_resource(
            "baseballStats_OFFLINE")) == {"Broker_1", "Broker_2"}

        _await(lambda: b1.handler.routing.has_table(
            "baseballStats_OFFLINE") and b2.handler.routing.has_table(
            "baseballStats_OFFLINE"), msg="brokers routable")
        rs = conn.execute("SELECT COUNT(*) FROM baseballStats")
        assert int(rs.result_set(0).get(0, 0)) == 2000

        # prepared statement with escaping through the same connection
        ps = conn.prepare("SELECT COUNT(*) FROM baseballStats "
                          "WHERE teamID = ?")
        ps.set_string(0, "BOS")
        exp = int(np.sum(np.asarray(cols["teamID"]) == "BOS"))
        assert int(ps.execute().result_set(0).get(0, 0)) == exp
        assert "''" in conn.prepare("SELECT COUNT(*) FROM x WHERE a = ?"
                                    ).set_string(0, "O'Brien").fill()

        # kill one broker (session death, no deregistration): the client
        # must keep answering via the survivor with no reconfiguration
        b1.kill()
        _await(lambda: len(sel.live_brokers()) == 1, msg="kill observed")
        for _ in range(8):
            rs = conn.execute("SELECT COUNT(*) FROM baseballStats")
            assert int(rs.result_set(0).get(0, 0)) == 2000

        # a replacement broker joins: the client picks it up, again with
        # no reconfiguration
        b3 = DistributedBroker("127.0.0.1", ctrl.store_port,
                               ctrl.deep_store_dir, http=True,
                               instance_id="Broker_3")
        try:
            _await(lambda: len(sel.live_brokers()) == 2,
                   msg="replacement seen")
            assert "Broker_3" in sel.live_brokers()
            _await(lambda: b3.handler.routing.has_table(
                "baseballStats_OFFLINE"), msg="b3 routable")
            for _ in range(8):
                rs = conn.execute("SELECT COUNT(*) FROM baseballStats")
                assert int(rs.result_set(0).get(0, 0)) == 2000
        finally:
            b3.stop()
    finally:
        if conn is not None:
            conn.close()
        b2.stop()
        try:
            server.stop()
        except Exception:
            pass
        ctrl.stop()


def test_rebalance_reload_churn_zero_failures(tmp_path):
    """Across repeated stepping rebalances + rolling reloads over REAL
    TCP processes, a continuous query load sees zero wrong answers and
    zero surfaced errors. Exercises the full no-downtime stack: add-step
    convergence on the NEWLY ADDED replicas, per-replica reload bounces
    that wait for the unload to be OBSERVED before flipping back, the
    broker's unservable-window routing grace, and the missing-segment
    re-dispatch."""
    import threading

    base = str(tmp_path)
    ctrl = DistributedController(base)
    servers = {f"Server_{i}": DistributedServer(
        f"Server_{i}", "127.0.0.1", ctrl.store_port, ctrl.deep_store_dir,
        work_dir=os.path.join(base, f"s{i}")) for i in range(3)}
    broker = DistributedBroker("127.0.0.1", ctrl.store_port,
                               ctrl.deep_store_dir)
    try:
        mgr = ctrl.controller.manager
        mgr.add_schema(make_schema())
        cfg = make_table_config()
        cfg.segments_config.replication = 2
        mgr.add_table(cfg)
        total = 0
        for i in range(4):
            d = os.path.join(base, f"chseg{i}")
            os.makedirs(d)
            build_segment(d, n=1000, seed=50 + i, name=f"chseg{i}")
            mgr.add_segment("baseballStats_OFFLINE", d)
            total += 1000

        def settled():
            r = broker.query("SELECT COUNT(*) FROM baseballStats")
            return not r.exceptions and \
                int(r.aggregation_results[0].value) == total
        _await(settled, timeout=20, msg="bootstrap routed")

        failures = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                r = broker.query("SELECT COUNT(*) FROM baseballStats")
                if r.exceptions or \
                        int(r.aggregation_results[0].value) != total:
                    failures.append(r.to_json())

        t = threading.Thread(target=hammer)
        t.start()
        try:
            for _ in range(3):
                mgr.rebalance_table("baseballStats_OFFLINE",
                                    batch_size=1)
                mgr.reload_table("baseballStats_OFFLINE")
        finally:
            stop.set()
            t.join()
        assert not failures, failures[:2]
    finally:
        broker.stop()
        for s in servers.values():
            try:
                s.stop()
            except Exception:  # noqa: BLE001
                pass
        ctrl.stop()
