"""Multi-segment combine + pruning tests.

Mirrors the reference's inter-segment tier (CombineOperator /
CombineGroupByOperator merge + SegmentPrunerService).
"""
import os
import tempfile

import numpy as np
import pytest

from fixtures import build_segment, make_columns
from oracle import Oracle

from pinot_tpu.engine import QueryEngine


@pytest.fixture(scope="module")
def multi():
    segs, all_cols = [], []
    base = tempfile.mkdtemp()
    for i in range(4):
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d)
        seg, cols = build_segment(d, n=2500, seed=100 + i, name=f"s{i}")
        segs.append(seg)
        all_cols.append(cols)
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    return (QueryEngine(segs), QueryEngine(segs, use_device=False),
            Oracle(merged))


def test_multiseg_count_sum(multi):
    dev, host, oracle = multi
    m = oracle.mask(lambda r: r["yearID"] >= 2000)
    for e in (dev, host):
        resp = e.query("SELECT COUNT(*), SUM(runs) FROM baseballStats "
                       "WHERE yearID >= 2000")
        assert resp.aggregation_results[0].value == str(oracle.count(m))
        assert float(resp.aggregation_results[1].value) == pytest.approx(
            oracle.sum("runs", m))
        assert resp.num_segments_processed == 4
        assert resp.total_docs == 10000


def test_multiseg_distinctcount_merges_sets(multi):
    dev, host, oracle = multi
    m = oracle.mask(lambda r: True)
    for e in (dev, host):
        resp = e.query("SELECT DISTINCTCOUNT(playerName) FROM baseballStats")
        assert int(resp.aggregation_results[0].value) == \
            oracle.distinctcount("playerName", m)


def test_multiseg_percentile_exact(multi):
    dev, host, oracle = multi
    m = oracle.mask(lambda r: r["league"] == "NL")
    for e in (dev, host):
        resp = e.query("SELECT PERCENTILE90(hits) FROM baseballStats "
                       "WHERE league = 'NL'")
        assert float(resp.aggregation_results[0].value) == \
            oracle.percentile("hits", m, 90)


def test_multiseg_group_by(multi):
    dev, host, oracle = multi
    m = oracle.mask(lambda r: True)
    expected = oracle.group_by(["league"], m, ("max", "hits"))
    for e in (dev, host):
        resp = e.query("SELECT MAX(hits) FROM baseballStats GROUP BY league")
        got = {tuple(g["group"]): float(g["value"])
               for g in resp.aggregation_results[0].group_by_result}
        assert got == {k: v for k, v in expected.items()}


def test_multiseg_selection_order_by(multi):
    dev, host, oracle = multi
    m = oracle.mask(lambda r: r["teamID"] == "BOS")
    top = np.sort(oracle.vals("runs", m))[::-1][:8]
    for e in (dev, host):
        resp = e.query("SELECT runs FROM baseballStats WHERE teamID = 'BOS' "
                       "ORDER BY runs DESC LIMIT 8")
        got = [int(r[0]) for r in resp.selection_results.results]
        assert got == [int(x) for x in top]


def test_pruning_by_time_range(multi):
    dev, host, oracle = multi
    # build two segments with disjoint year ranges and check pruning stats
    base = tempfile.mkdtemp()
    segs = []
    for i, years in enumerate([(1990, 1995), (2010, 2015)]):
        cols = make_columns(500, seed=i)
        cols["yearID"] = np.random.default_rng(i).integers(
            years[0], years[1], 500).astype(np.int32)
        d = os.path.join(base, f"seg{i}")
        os.makedirs(d)
        from fixtures import make_schema, make_table_config
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import ImmutableSegmentLoader
        SegmentCreator(make_schema(), make_table_config(),
                       segment_name=f"p{i}").build(cols, d)
        segs.append(ImmutableSegmentLoader.load(d))
    e = QueryEngine(segs)
    resp = e.query(
        "SELECT COUNT(*) FROM baseballStats WHERE yearID >= 2012")
    assert resp.num_segments_processed == 1  # one segment pruned
    rng = np.random.default_rng(1)
    expect = int((rng.integers(2010, 2015, 500) >= 2012).sum())
    assert resp.aggregation_results[0].value == str(expect)


def test_bloom_pruning_on_absent_value(multi):
    dev, host, oracle = multi
    resp = dev.query(
        "SELECT COUNT(*) FROM baseballStats WHERE teamID = 'XYZ'")
    assert resp.aggregation_results[0].value == "0"
