"""Star-tree query execution: route eligible queries to a cube.

Parity: core/startree/ query side — StarTreeFilterOperator +
StarTreeAggregationExecutor/StarTreeGroupByExecutor and the plan nodes
that swap in when a query's dimensions/metrics are covered
(StarTreeV2's eligibility rules). Here the cube is a columnar grouped
table, so execution is: evaluate the filter over the cube's dictId lanes
(reusing the host filter evaluator through a segment-shaped facade),
then weighted aggregation over the surviving groups.

Cubes are small by construction (bounded at build), so this runs
host-side numpy — O(groups) instead of the device's O(docs); doc-scale
work never happens at all, which is the entire point of the structure.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pinot_tpu.common import expression as expr_mod
from pinot_tpu.common.request import BrokerRequest
from pinot_tpu.query.aggregation import make_functions
from pinot_tpu.query.blocks import ExecutionStats, IntermediateResultsBlock

_COVERED_BASES = {"COUNT", "SUM", "AVG", "MIN", "MAX", "MINMAXRANGE"}


class _CubeDataSource:
    """Segment-DataSource-shaped view of one cube dimension lane."""

    def __init__(self, parent_ds, ids: np.ndarray):
        self.metadata = parent_ds.metadata
        self.dictionary = parent_ds.dictionary
        self.dict_ids = ids
        self.raw_values = None
        self.mv_dict_ids = None
        self.inverted_index = None
        self.bloom_filter = None
        self.sorted_ranges = None


class _CubeView:
    """Segment-shaped facade so host filter evaluation runs unchanged."""

    def __init__(self, segment, cube):
        self._segment = segment
        self._cube = cube
        self.num_docs = cube.n_groups
        self.segment_name = segment.segment_name

    def has_column(self, col: str) -> bool:
        return col in self._cube.dim_ids

    def data_source(self, col: str) -> _CubeDataSource:
        return _CubeDataSource(self._segment.data_source(col),
                               self._cube.dim_ids[col])


def _eligible_cube(segment, request: BrokerRequest, functions):
    """Pick the first cube covering the query, or None.

    Coverage: filter + group columns ⊆ dimensions (expressions allowed in
    filters when their source columns are dimensions); aggregations are
    COUNT(*) or covered-base functions over cube metrics.
    """
    cubes = getattr(segment, "star_trees", None)
    if not cubes or not request.is_aggregation or request.is_selection:
        return None
    if request.query_options.options.get("useStarTree") == "false":
        return None
    needed_dims = set()
    for c in request.filter_columns():
        needed_dims.update(expr_mod.referenced_columns(c))
    group_cols = list(request.group_by.columns) if request.group_by else []
    for c in group_cols:
        if expr_mod.is_expression(c):
            return None                       # group keys must be plain dims
        needed_dims.add(c)
    needed_metrics = set()
    for f in functions:
        if f.info.is_mv:
            return None
        if f.info.base == "COUNT":
            continue
        if f.info.base not in _COVERED_BASES:
            return None
        if expr_mod.is_expression(f.column):
            return None
        needed_metrics.add(f.column)
    for cube in cubes:
        if needed_dims <= set(cube.dimensions) and \
                needed_metrics <= set(cube.metrics) and \
                cube.n_groups * 8 <= segment.num_docs:
            # the cube must actually compress: scanning a cube nearly as
            # tall as the segment costs more than the doc-scale kernel
            return cube
    return None


def try_star_tree_execute(segment, request: BrokerRequest
                          ) -> Optional[IntermediateResultsBlock]:
    """Execute over a covering cube; None when not eligible."""
    if not getattr(segment, "star_trees", None):
        return None
    functions = make_functions(request.aggregations)
    cube = _eligible_cube(segment, request, functions)
    if cube is None:
        return None
    from pinot_tpu.query import host_exec
    view = _CubeView(segment, cube)
    try:
        mask = host_exec._eval_filter(request.filter, view)
    except Exception:  # noqa: BLE001 — unresolvable predicate: fall back
        return None

    blk = IntermediateResultsBlock()
    counts = cube.counts
    matched_docs = int(counts[mask].sum())
    if request.is_group_by:
        _cube_group_by(segment, cube, request, functions, mask, blk)
    else:
        blk.agg_intermediates = [
            _cube_aggregate(cube, f, mask) for f in functions]
    blk.stats = ExecutionStats(
        num_docs_scanned=int(mask.sum()),         # groups, not raw docs —
        # parity: star-tree queries report aggregated doc counts
        num_entries_scanned_in_filter=cube.n_groups,
        num_segments_processed=1,
        num_segments_matched=1 if matched_docs else 0,
        total_docs=segment.num_docs)
    return blk


def try_star_tree_execute_multi(segments, request: BrokerRequest
                                ) -> Optional[IntermediateResultsBlock]:
    """Vectorized cube execution across MANY segments at once.

    The per-segment path emits one group_map dict per segment and merges
    them entry-by-entry in Python — fine for two segments, dominant cost
    for many. Here the matched cube rows (decoded group values, counts,
    stat lanes) from every segment are concatenated and aggregated in one
    numpy group-by pass. Parity: the combine step of
    StarTreeAggregationExecutor outputs, done columnar.
    """
    if not request.is_aggregation or request.is_selection:
        return None
    functions = make_functions(request.aggregations)
    pairs = []
    for seg in segments:
        cube = _eligible_cube(seg, request, functions)
        if cube is None:
            return None                   # all segments must be covered
        pairs.append((seg, cube))

    from pinot_tpu.query import host_exec
    gcols = list(request.group_by.columns) if request.group_by else []
    val_chunks: List[List[np.ndarray]] = [[] for _ in gcols]
    cnt_chunks: List[np.ndarray] = []
    stat_chunks: Dict[str, List[np.ndarray]] = {}
    # each column's stat lanes exactly once per segment — two functions
    # over the same column (MIN(x), MAX(x)) must not double-append
    stat_cols = sorted({f.column for f in functions
                        if f.info.base != "COUNT"})
    total_docs = 0
    matched_groups = 0
    scanned = 0
    for seg, cube in pairs:
        total_docs += seg.num_docs
        scanned += cube.n_groups
        view = _CubeView(seg, cube)
        try:
            mask = host_exec._eval_filter(request.filter, view)
        except Exception:  # noqa: BLE001 — unresolvable predicate
            return None
        sel = np.nonzero(mask)[0]
        matched_groups += len(sel)
        cnt_chunks.append(cube.counts[sel])
        for i, c in enumerate(gcols):
            d = seg.data_source(c).dictionary
            val_chunks[i].append(np.asarray(
                d.decode(cube.dim_ids[c][sel])))
        for col in stat_cols:
            stats = cube.metric_stats[col]
            for k in ("sum", "min", "max"):
                stat_chunks.setdefault(f"{col}.{k}", []).append(
                    stats[k][sel])

    counts = np.concatenate(cnt_chunks) if cnt_chunks else \
        np.zeros(0, np.int64)
    stats_cat = {k: np.concatenate(v) for k, v in stat_chunks.items()}
    blk = IntermediateResultsBlock()
    if not gcols:
        mask_all = np.ones(len(counts), dtype=bool)
        flat_cube = StarTreeCubeLike(counts, stats_cat)
        blk.agg_intermediates = [
            _cube_aggregate(flat_cube, f, mask_all) for f in functions]
    else:
        _multi_group_by(gcols, val_chunks, counts, stats_cat, functions,
                        blk)
        from pinot_tpu.query.combine import trim_group_map, trim_size_for
        t = trim_size_for(request.group_by.top_n)
        if len(blk.group_map) > 4 * t:
            # same memory/parity bound combine_blocks applies on the
            # per-segment path (AggregationGroupByTrimmingService)
            blk.group_map = trim_group_map(blk.group_map, functions, t)
    blk.stats = ExecutionStats(
        num_docs_scanned=matched_groups,
        num_entries_scanned_in_filter=scanned,
        num_segments_processed=len(segments),
        num_segments_matched=len(segments) if matched_groups else 0,
        total_docs=total_docs)
    return blk


class StarTreeCubeLike:
    """Concatenated cross-segment cube rows, shaped like a cube for
    _cube_aggregate."""

    def __init__(self, counts: np.ndarray, stats_cat: Dict[str, np.ndarray]):
        self.counts = counts
        self.metric_stats: Dict[str, Dict[str, np.ndarray]] = {}
        for k, arr in stats_cat.items():
            col, stat = k.rsplit(".", 1)
            self.metric_stats.setdefault(col, {})[stat] = arr


def _multi_group_by(gcols, val_chunks, counts, stats_cat, functions,
                    blk) -> None:
    n = len(counts)
    codes = []
    uniq_vals = []
    for chunks in val_chunks:
        lane = np.concatenate(chunks) if chunks else np.zeros(0, object)
        u, inv = np.unique(lane, return_inverse=True)
        uniq_vals.append(u)
        codes.append(inv.astype(np.int64))
    key = np.zeros(n, dtype=np.int64)
    for u, inv in zip(uniq_vals, codes):
        key = key * max(len(u), 1) + inv
    uniq_keys, inverse = np.unique(key, return_inverse=True)
    g = len(uniq_keys)

    value_cols = []
    rem = uniq_keys.copy()
    for u in reversed(uniq_vals):
        value_cols.append(u[rem % max(len(u), 1)])
        rem //= max(len(u), 1)
    value_cols.reverse()

    _fill_group_map(blk, functions, g, inverse, counts, value_cols,
                    lambda f, k: stats_cat[f"{f.column}.{k}"])


def _cube_aggregate(cube, f, mask: np.ndarray):
    base = f.info.base
    cnt = int(cube.counts[mask].sum())
    if base == "COUNT":
        return cnt
    if cnt == 0:
        return None
    stats = cube.metric_stats[f.column]
    if base == "SUM":
        return float(stats["sum"][mask].sum())
    if base == "AVG":
        return (float(stats["sum"][mask].sum()), cnt)
    if base == "MIN":
        return float(stats["min"][mask].min())
    if base == "MAX":
        return float(stats["max"][mask].max())
    if base == "MINMAXRANGE":
        return (float(stats["min"][mask].min()),
                float(stats["max"][mask].max()))
    raise ValueError(base)


def _cube_group_by(segment, cube, request, functions, mask: np.ndarray,
                   blk: IntermediateResultsBlock) -> None:
    gcols = request.group_by.columns
    sel = np.nonzero(mask)[0]
    lanes = [cube.dim_ids[c][sel].astype(np.int64) for c in gcols]
    cards = [segment.data_source(c).metadata.cardinality for c in gcols]
    key = np.zeros(len(sel), dtype=np.int64)
    for lane, card in zip(lanes, cards):
        key = key * card + lane
    uniq, inverse = np.unique(key, return_inverse=True)
    g = len(uniq)

    value_cols = []
    rem = uniq.copy()
    for c, card in zip(reversed(gcols), reversed(cards)):
        d = segment.data_source(c).dictionary
        value_cols.append(d.decode(rem % card))
        rem //= card
    value_cols.reverse()

    _fill_group_map(blk, functions, g, inverse, cube.counts[sel],
                    value_cols,
                    lambda f, k: cube.metric_stats[f.column][k][sel])


def _fill_group_map(blk: IntermediateResultsBlock, functions, g: int,
                    inverse: np.ndarray, row_counts: np.ndarray,
                    value_cols, stat_rows) -> None:
    """Shared group-by finisher for the single-segment and multi-segment
    cube paths: scatter matched cube rows into `g` group slots and emit
    the engine's standard intermediate formats (AVG = (sum, count),
    MINMAXRANGE = (min, max)). `stat_rows(f, kind)` yields the matched
    rows' "sum"/"min"/"max" lane for function f."""
    gcounts = np.zeros(g, dtype=np.int64)
    np.add.at(gcounts, inverse, row_counts)
    per_fn: List[List] = []
    for f in functions:
        base = f.info.base
        if base == "COUNT":
            per_fn.append([int(c) for c in gcounts])
            continue
        if base in ("SUM", "AVG"):
            sums = np.zeros(g)
            np.add.at(sums, inverse, stat_rows(f, "sum"))
            if base == "SUM":
                per_fn.append([float(s) for s in sums])
            else:
                per_fn.append([(float(s), int(c))
                               for s, c in zip(sums, gcounts)])
        else:
            mins = np.full(g, np.inf)
            maxs = np.full(g, -np.inf)
            np.minimum.at(mins, inverse, stat_rows(f, "min"))
            np.maximum.at(maxs, inverse, stat_rows(f, "max"))
            if base == "MIN":
                per_fn.append([float(v) for v in mins])
            elif base == "MAX":
                per_fn.append([float(v) for v in maxs])
            else:
                per_fn.append([(float(a), float(b))
                               for a, b in zip(mins, maxs)])
    blk.group_map = {
        tuple(_plain(vc[i]) for vc in value_cols):
            [per_fn[fi][i] for fi in range(len(functions))]
        for i in range(g)}


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
