"""PQL lexer.

Parity: token vocabulary of pinot-common/src/main/antlr4/.../PQL2.g4 —
identifiers (optionally back-quoted), string literals ('..' or ".."), integer
and float literals, comparison operators, parens/commas/star, and the PQL
keyword set (case-insensitive).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List


class TokType(enum.Enum):
    IDENT = "IDENT"
    STRING = "STRING"
    INT = "INT"
    FLOAT = "FLOAT"
    OP = "OP"          # = <> != < <= > >=
    LPAREN = "LPAREN"
    RPAREN = "RPAREN"
    LBRACKET = "LBRACKET"
    RBRACKET = "RBRACKET"
    COMMA = "COMMA"
    STAR = "STAR"
    KEYWORD = "KEYWORD"
    EOF = "EOF"


KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "TOP",
    "LIMIT", "OFFSET", "AND", "OR", "NOT", "IN", "BETWEEN", "IS", "NULL",
    "ASC", "DESC", "OPTION", "JOIN", "ON", "OVER", "PARTITION",
}


@dataclasses.dataclass
class Token:
    type: TokType
    value: str
    pos: int

    @property
    def upper(self) -> str:
        return self.value.upper()


class PqlSyntaxError(ValueError):
    pass


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c in "'\"":
            quote = c
            j = i + 1
            buf = []
            while j < n:
                if text[j] == quote:
                    if j + 1 < n and text[j + 1] == quote:  # escaped quote
                        buf.append(quote)
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            else:
                raise PqlSyntaxError(f"unterminated string at {i}")
            toks.append(Token(TokType.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise PqlSyntaxError(f"unterminated back-quote at {i}")
            toks.append(Token(TokType.IDENT, text[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c in "+-." and i + 1 < n and text[i + 1].isdigit()
                           and _numeric_context(toks)):
            j = i
            if text[j] in "+-":
                j += 1
            seen_dot = seen_exp = False
            while j < n and (text[j].isdigit() or text[j] in ".eE+-"):
                if text[j] == ".":
                    if seen_dot:
                        break
                    seen_dot = True
                elif text[j] in "eE":
                    if seen_exp:
                        break
                    seen_exp = True
                elif text[j] in "+-" and text[j - 1] not in "eE":
                    break
                j += 1
            lit = text[i:j]
            ttype = TokType.FLOAT if ("." in lit or "e" in lit or "E" in lit) \
                else TokType.INT
            toks.append(Token(ttype, lit, i))
            i = j
            continue
        if c.isalpha() or c in "_$":
            j = i
            while j < n and (text[j].isalnum() or text[j] in "_$."):
                j += 1
            word = text[i:j]
            ttype = TokType.KEYWORD if word.upper() in KEYWORDS else TokType.IDENT
            toks.append(Token(ttype, word, i))
            i = j
            continue
        if c == "(":
            toks.append(Token(TokType.LPAREN, c, i)); i += 1; continue
        if c == ")":
            toks.append(Token(TokType.RPAREN, c, i)); i += 1; continue
        if c == "[":
            toks.append(Token(TokType.LBRACKET, c, i)); i += 1; continue
        if c == "]":
            toks.append(Token(TokType.RBRACKET, c, i)); i += 1; continue
        if c == ",":
            toks.append(Token(TokType.COMMA, c, i)); i += 1; continue
        if c == "*":
            toks.append(Token(TokType.STAR, c, i)); i += 1; continue
        if c in "=<>!":
            for op in ("<>", "<=", ">=", "!=", "=", "<", ">"):
                if text.startswith(op, i):
                    toks.append(Token(TokType.OP, op, i))
                    i += len(op)
                    break
            else:
                raise PqlSyntaxError(f"bad operator at {i}: {text[i:i+2]!r}")
            continue
        raise PqlSyntaxError(f"unexpected character {c!r} at {i}")
    toks.append(Token(TokType.EOF, "", n))
    return toks


def _numeric_context(toks: List[Token]) -> bool:
    """A leading +/- starts a number only after an operator/paren/comma/keyword."""
    if not toks:
        return True
    return toks[-1].type in (TokType.OP, TokType.LPAREN, TokType.COMMA,
                             TokType.KEYWORD, TokType.LBRACKET)
