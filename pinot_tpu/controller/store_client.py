"""RemotePropertyStore: PropertyStore interface over the store server.

Parity: the ZooKeeper *client* role — every non-controller process in the
reference holds a ZK session for cluster state and watches.  This client
speaks the store_server frame protocol and exposes exactly the
PropertyStore interface, so ClusterCoordinator, ResourceManager,
BrokerClusterWatcher, minions etc. run unchanged over a remote store.

- update(fn) is a CAS retry loop (read → fn → compare-and-set), giving
  the same atomic read-modify-write the in-process store's lock provides.
- watch callbacks are dispatched on a single daemon thread in arrival
  order (ZK's single watcher-thread ordering guarantee).
- set(..., ephemeral=True) binds the path to this client's connection:
  the server removes it when the connection dies (ZK ephemeral znodes).
"""
from __future__ import annotations

import asyncio
import json
import queue
import threading
from typing import Callable, Dict, List, Optional

from pinot_tpu.transport.tcp import read_frame, write_frame

Watcher = Callable[[str, Optional[dict]], None]


class StoreClosedError(ConnectionError):
    pass


class RemotePropertyStore:
    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._loop = asyncio.new_event_loop()
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._watchers: List[tuple] = []        # (prefix, callback)
        self._watch_lock = threading.Lock()
        self._events: "queue.Queue" = queue.Queue()
        self._closed = False
        # per-client serialization of compose_view's read-compute-write
        # (state_machine.compose_view): without the attribute the
        # composer used to fall back to a throwaway lock, silently
        # disabling the serialization for remote-store coordinators
        self.compose_lock = threading.Lock()

        ready = threading.Event()
        boot: Dict[str, Optional[BaseException]] = {"err": None}

        def run() -> None:
            asyncio.set_event_loop(self._loop)
            try:
                self._reader, self._writer = self._loop.run_until_complete(
                    asyncio.open_connection(host, port))
            except BaseException as e:  # noqa: BLE001
                boot["err"] = e
                ready.set()
                return
            self._reader_task = self._loop.create_task(self._read_loop())
            ready.set()
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        ready.wait()
        if boot["err"] is not None:
            raise ConnectionError(
                f"cannot reach property store at {host}:{port}: "
                f"{boot['err']}")
        self._dispatcher = threading.Thread(target=self._dispatch_events,
                                            daemon=True)
        self._dispatcher.start()

    # -- wire --------------------------------------------------------------
    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self._reader)
                msg = json.loads(frame)
                if "event" in msg:
                    self._events.put(msg["event"])
                    continue
                fut = self._pending.pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(StoreClosedError("store disconnected"))
            self._pending.clear()
            self._events.put(None)

    def _call(self, **req) -> dict:
        if self._closed:
            raise StoreClosedError("store client is closed")
        with self._id_lock:
            self._next_id += 1
            req["id"] = self._next_id

        async def send_and_wait() -> dict:
            fut = self._loop.create_future()
            self._pending[req["id"]] = fut
            try:
                write_frame(self._writer, json.dumps(req).encode("utf-8"))
                await self._writer.drain()
                return await asyncio.wait_for(fut, self.timeout)
            finally:
                # timeout/cancel must not leak the entry: a hung server
                # would otherwise grow _pending per retry, and a late
                # response would resolve a future nobody awaits
                self._pending.pop(req["id"], None)

        resp = asyncio.run_coroutine_threadsafe(
            send_and_wait(), self._loop).result(self.timeout + 1)
        if not resp.get("ok"):
            raise RuntimeError(f"store op failed: {resp.get('error')}")
        return resp

    def _dispatch_events(self) -> None:
        while True:
            ev = self._events.get()
            if ev is None:
                return
            path, record = ev["path"], ev["record"]
            with self._watch_lock:
                cbs = [cb for p, cb in self._watchers
                       if path.startswith(p)]
            for cb in cbs:
                try:
                    cb(path, record)
                except Exception:  # noqa: BLE001 — watcher errors are theirs
                    import logging
                    logging.getLogger(__name__).exception(
                        "watch callback failed for %s", path)

    # -- PropertyStore interface ------------------------------------------
    def set(self, path: str, record: dict, ephemeral: bool = False) -> None:
        self._call(op="set", path=path, record=record, ephemeral=ephemeral)

    def get(self, path: str) -> Optional[dict]:
        return self._call(op="get", path=path)["record"]

    def cas(self, path: str, expected: Optional[dict],
            record: dict) -> bool:
        return self._call(op="cas", path=path, expected=expected,
                          record=record)["applied"]

    def update(self, path: str, fn: Callable[[Optional[dict]], dict]
               ) -> dict:
        while True:
            cur = self.get(path)
            rec = fn(cur)
            if self.cas(path, cur, rec):
                return rec

    def remove(self, path: str) -> bool:
        return self._call(op="remove", path=path)["existed"]

    def children(self, prefix: str) -> List[str]:
        return self._call(op="children", prefix=prefix)["result"]

    def list_paths(self, prefix: str) -> List[str]:
        return self._call(op="list", prefix=prefix)["result"]

    def watch(self, prefix: str, callback: Watcher) -> None:
        with self._watch_lock:
            self._watchers.append((prefix, callback))
        self._call(op="watch", prefix=prefix)

    def unwatch(self, callback: Watcher) -> None:
        # server-side prefixes stay registered (another callback may share
        # them); dropping the local route is what stops delivery
        with self._watch_lock:
            self._watchers = [(p, cb) for p, cb in self._watchers
                              if cb is not callback]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True

        async def shutdown() -> None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except BaseException:  # noqa: BLE001 — incl. our own cancel
                pass
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            # drain every in-flight send_and_wait so each pending
            # future's StoreClosedError is RETRIEVED by its awaiter —
            # stopping the loop first turned them into destroyed-pending
            # tasks and never-retrieved futures at GC
            tasks = [t for t in asyncio.all_tasks(self._loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            self._loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(shutdown(), self._loop)
            self._thread.join(timeout=5)
        except RuntimeError:
            pass
        self._events.put(None)
        dispatcher = getattr(self, "_dispatcher", None)
        if dispatcher is not None and \
                dispatcher is not threading.current_thread():
            dispatcher.join(timeout=5)
        if not self._loop.is_running() and not self._loop.is_closed():
            self._loop.close()
