"""Multiplexed data-plane tests.

The serving-plane contract this file pins down (reference parity:
ServerChannels.java requestId correlation + CombineOperator's parallel
per-segment plans):

- many requests share ONE broker→server connection and complete OUT OF
  ORDER — a slow query never head-of-line-blocks a fast one,
- a per-request timeout abandons only its own future; the connection and
  every other in-flight request stay live (late replies are discarded by
  correlation id, never misread as another query's reply),
- ≥8 in-flight requests on one connection round-trip correctly, and the
  fault-injection classes from common/faults.py still yield the
  correct-or-flagged-partial contract over the real TCP mux,
- the columnar (v2) DataTable wire format round-trips value-equal to the
  row (v1) path, and old v1 payloads still decode.

Determinism: ordering is driven by asyncio.Events, not sleeps.
"""
import asyncio
import concurrent.futures
import tempfile
import threading

import numpy as np
import pytest

from fixtures import build_segment
from oracle import Oracle

from pinot_tpu.broker import BrokerRequestHandler, RoutingManager
from pinot_tpu.broker.request_handler import TcpTransport
from pinot_tpu.broker.routing import RoutingTableBuilder
from pinot_tpu.common.cluster_state import ONLINE, TableView
from pinot_tpu.common.datatable import DataTable
from pinot_tpu.common.faults import (CORRUPT, DROP, LATENCY,
                                     MISSING_SEGMENTS,
                                     FaultInjectingTransport, FaultSpec)
from pinot_tpu.query.blocks import IntermediateResultsBlock
from pinot_tpu.pql.parser import compile_pql
from pinot_tpu.server import ServerInstance
from pinot_tpu.transport.tcp import QueryServer, ServerConnection

TABLE = "baseballStats_OFFLINE"


# ---------------------------------------------------------------------------
# transport-level: one connection, many in-flight requests
# ---------------------------------------------------------------------------

def _run(coro):
    return asyncio.run(coro)


def test_mux_out_of_order_completion_no_hol_blocking():
    """A delayed query and a fast query issued on the SAME connection:
    the fast one completes FIRST; the slow one finishes when released."""
    async def main():
        release = asyncio.Event()
        started = asyncio.Event()

        async def handler(payload: bytes) -> bytes:
            if payload == b"slow":
                started.set()
                await release.wait()
            return b"reply:" + payload

        server = QueryServer("127.0.0.1", 0, handler=None,
                             async_handler=handler)
        await server.start()
        conn = ServerConnection("127.0.0.1", server.port)
        try:
            slow = asyncio.ensure_future(conn.request(b"slow", timeout=30))
            await started.wait()          # slow frame is being handled
            fast = await conn.request(b"fast", timeout=30)
            assert fast == b"reply:fast"
            assert not slow.done()        # ...while slow is in flight
            release.set()
            assert await slow == b"reply:slow"
        finally:
            await conn.close()
            await server.stop()

    _run(main())


def test_mux_timeout_cancels_only_its_own_request():
    """A timed-out request abandons ONE future: the connection is not
    torn down, other in-flight requests survive, and the late reply to
    the dead request is discarded instead of desynchronizing the
    stream."""
    async def main():
        release = asyncio.Event()

        async def handler(payload: bytes) -> bytes:
            if payload.startswith(b"wait"):
                await release.wait()
            return b"ok:" + payload

        server = QueryServer("127.0.0.1", 0, handler=None,
                             async_handler=handler)
        await server.start()
        conn = ServerConnection("127.0.0.1", server.port)
        try:
            doomed = asyncio.ensure_future(
                conn.request(b"wait-doomed", timeout=0.2))
            survivor = asyncio.ensure_future(
                conn.request(b"wait-survivor", timeout=30))
            with pytest.raises(asyncio.TimeoutError):
                await doomed
            writer_before = conn._writer
            assert writer_before is not None       # connection kept
            # a fresh request on the same (untouched) connection works
            assert await conn.request(b"echo", timeout=30) == b"ok:echo"
            assert conn._writer is writer_before   # no reconnect
            # releasing produces the survivor's reply AND the doomed
            # request's late reply — which must be dropped by corr id
            release.set()
            assert await survivor == b"ok:wait-survivor"
            assert await conn.request(b"echo2", timeout=30) == b"ok:echo2"
            assert conn._writer is writer_before
            assert conn.num_pending == 0
        finally:
            await conn.close()
            await server.stop()

    _run(main())


def test_mux_many_in_flight_round_trip():
    """≥8 requests simultaneously in flight on ONE connection, each
    correlated back to its own payload. The handler refuses to answer
    until every request has ARRIVED, so completion proves true
    multiplexing, not pipelined turn-taking."""
    n = 12

    async def main():
        arrived = 0
        barrier = asyncio.Event()

        async def handler(payload: bytes) -> bytes:
            nonlocal arrived
            arrived += 1
            if arrived >= n:
                barrier.set()
            await barrier.wait()
            return b"echo:" + payload

        server = QueryServer("127.0.0.1", 0, handler=None,
                             async_handler=handler)
        await server.start()
        conn = ServerConnection("127.0.0.1", server.port)
        try:
            reqs = [asyncio.ensure_future(
                conn.request(b"req-%d" % i, timeout=30)) for i in range(n)]
            results = await asyncio.gather(*reqs)
            assert results == [b"echo:req-%d" % i for i in range(n)]
        finally:
            await conn.close()
            await server.stop()

    _run(main())


def test_mux_connection_loss_fails_all_pending():
    """A transport-level failure (server gone mid-flight) fails every
    pending request promptly so the broker can fail over — no hang."""
    async def main():
        gate = asyncio.Event()

        async def handler(payload: bytes) -> bytes:
            await gate.wait()
            return payload

        server = QueryServer("127.0.0.1", 0, handler=None,
                             async_handler=handler)
        await server.start()
        conn = ServerConnection("127.0.0.1", server.port)
        try:
            reqs = [asyncio.ensure_future(conn.request(b"x%d" % i,
                                                       timeout=30))
                    for i in range(4)]
            await asyncio.sleep(0)        # let the writes flush
            while conn.num_pending < 4:
                await asyncio.sleep(0.01)
            await server.stop()           # hard-closes the channel
            for r in reqs:
                with pytest.raises((ConnectionError, OSError,
                                    asyncio.IncompleteReadError)):
                    await r
            assert conn.num_pending == 0
        finally:
            await conn.close()
            await server.stop()

    _run(main())


# ---------------------------------------------------------------------------
# cluster-level: real TCP mux under fault injection
# ---------------------------------------------------------------------------

class _FixedRoutingBuilder(RoutingTableBuilder):
    def __init__(self, table):
        self.table = table

    def build(self, view, rng):
        return [{srv: list(segs) for srv, segs in self.table.items()}]


@pytest.fixture(scope="module")
def tcp_cluster():
    """2 TCP servers, 2 segments, replication 2 (both segments on both
    servers) — the QPS_r05 topology at test scale."""
    base = tempfile.mkdtemp()
    servers = {f"server_{i}": ServerInstance(f"server_{i}")
               for i in range(2)}
    view = TableView(TABLE, {})
    all_cols = []
    for i, name in enumerate(["seg_a", "seg_b"]):
        seg, cols = build_segment(f"{base}/seg{i}", n=600, seed=70 + i,
                                  name=name)
        all_cols.append(cols)
        for srv in servers.values():
            srv.data_manager.table(TABLE, create=True).add_segment(seg)
        view.segment_states[name] = {s: ONLINE for s in servers}
    endpoints = {name: ("127.0.0.1", srv.start(port=0))
                 for name, srv in servers.items()}
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    yield servers, endpoints, view, Oracle(merged)
    for s in servers.values():
        s.stop()


def _tcp_handler(endpoints, view, routing_table, seed=0):
    routing = RoutingManager(builder=_FixedRoutingBuilder(routing_table))
    routing.update_view(view)
    transport = FaultInjectingTransport(TcpTransport(endpoints), seed=seed)
    handler = BrokerRequestHandler(routing, transport,
                                   default_timeout_s=10.0)
    return handler, transport


def _correct_or_flagged(resp, oracle) -> bool:
    full = resp.aggregation_results and \
        resp.aggregation_results[0].value == \
        str(oracle.count(oracle.mask(lambda r: True)))
    flagged = resp.partial_response or bool(resp.exceptions)
    return bool(full or flagged)


def test_mux_tcp_concurrent_queries_under_fault_injection(tcp_cluster):
    """≥8 concurrent queries through the real TCP mux while the fault
    injector throws latency / drops / corrupt frames / missing segments:
    every response is the correct full answer or an honestly flagged
    partial — never a silent wrong answer, never a hang."""
    servers, endpoints, view, oracle = tcp_cluster
    handler, transport = _tcp_handler(
        endpoints, view,
        {"server_0": ["seg_a"], "server_1": ["seg_b"]}, seed=11)
    transport.inject("server_0", FaultSpec(LATENCY, latency_s=0.02,
                                           probability=0.5))
    transport.inject("server_0", FaultSpec(DROP, times=2))
    transport.inject("server_1", FaultSpec(CORRUPT, times=2))
    transport.inject("server_1", FaultSpec(
        MISSING_SEGMENTS, segments=("seg_b",), times=2))

    n = 10
    results = [None] * n

    def one(i):
        results[i] = handler.handle("SELECT COUNT(*) FROM baseballStats")

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert all(r is not None for r in results)
        for resp in results:
            assert _correct_or_flagged(resp, oracle), resp.to_json()
        # the faults actually fired
        assert transport.injected_count("server_0", DROP) == 2
        assert transport.injected_count("server_1", CORRUPT) == 2
    finally:
        handler.close()


def test_mux_tcp_shares_one_connection_per_server(tcp_cluster):
    """Concurrent queries reuse the per-server channel (the mux point of
    the whole exercise) instead of serializing on a connection lock."""
    servers, endpoints, view, oracle = tcp_cluster
    handler, transport = _tcp_handler(
        endpoints, view,
        {"server_0": ["seg_a", "seg_b"]}, seed=3)
    try:
        def one(_):
            return handler.handle("SELECT COUNT(*) FROM baseballStats")

        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            responses = list(pool.map(one, range(8)))
        for resp in responses:
            assert _correct_or_flagged(resp, oracle)
        inner = transport.inner
        assert len(inner._conns) == 1          # one channel, many queries
    finally:
        handler.close()


# ---------------------------------------------------------------------------
# parallel per-segment execution
# ---------------------------------------------------------------------------

def _build_engine_segments(n_segments=4, rows=400):
    base = tempfile.mkdtemp()
    segs, all_cols = [], []
    for i in range(n_segments):
        seg, cols = build_segment(f"{base}/s{i}", n=rows, seed=90 + i,
                                  name=f"ps_{i}")
        segs.append(seg)
        all_cols.append(cols)
    merged = {k: (np.concatenate([c[k] for c in all_cols])
                  if isinstance(all_cols[0][k], np.ndarray)
                  else sum((c[k] for c in all_cols), []))
              for k in all_cols[0]}
    return segs, Oracle(merged)


def test_parallel_segment_execution_matches_sequential():
    from pinot_tpu.query.executor import ServerQueryExecutor

    segs, oracle = _build_engine_segments()
    pool = concurrent.futures.ThreadPoolExecutor(4)
    try:
        seq = ServerQueryExecutor(use_device=False)
        par = ServerQueryExecutor(use_device=False, segment_executor=pool)
        for pql in (
                "SELECT COUNT(*), SUM(runs) FROM baseballStats "
                "WHERE yearID >= 2000",
                "SELECT SUM(hits) FROM baseballStats GROUP BY teamID "
                "TOP 500",
                "SELECT playerName, runs FROM baseballStats ORDER BY "
                "runs DESC LIMIT 13"):
            request = compile_pql(pql)
            b_seq = seq.execute(request, segs)
            b_par = par.execute(request, segs)
            assert b_par.exceptions == b_seq.exceptions == []
            assert b_par.stats.num_segments_processed == \
                b_seq.stats.num_segments_processed
            if b_seq.group_map is not None:
                assert b_par.group_map == b_seq.group_map
            elif b_seq.agg_intermediates is not None:
                assert b_par.agg_intermediates == b_seq.agg_intermediates
            if b_seq.selection_rows is not None:
                assert sorted(b_par.selection_rows) == \
                    sorted(b_seq.selection_rows)
    finally:
        pool.shutdown(wait=False)


def test_parallel_segment_execution_deadline_truncates():
    import time as _time
    from pinot_tpu.query.executor import ServerQueryExecutor

    segs, _ = _build_engine_segments()
    pool = concurrent.futures.ThreadPoolExecutor(4)
    try:
        par = ServerQueryExecutor(use_device=False, segment_executor=pool)
        request = compile_pql("SELECT COUNT(*) FROM baseballStats")
        blk = par.execute(request, segs,
                          deadline=_time.monotonic() - 0.001)
        assert any("DeadlineExceededError" in e for e in blk.exceptions)
        assert blk.stats.num_segments_processed < len(segs)
    finally:
        pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# DataTable wire-format compatibility
# ---------------------------------------------------------------------------

def _sample_tables():
    group_by = DataTable(
        kind=2, columns=["d1", "d2", "sum(m)", "avg(m)", "fasthll(x)"],
        num_group_cols=2,
        rows=[("x", 1, 10.0, (10.0, 2), None),
              ("y", 2, 5.5, (5.5, 1), True),
              ("z", -3, float("inf"), (0.0, 0), 2 ** 90)],
        metadata={"numDocsScanned": "3", "totalDocs": "10"},
        exceptions=["boom"])
    selection = DataTable(
        kind=3, columns=["name", "year", "score"],
        rows=[(f"p{i}", 1990 + i, i * 1.5) for i in range(64)],
        metadata={"selectionDisplayCols": "2"})
    aggregation = DataTable(
        kind=1, columns=["count(*)"], rows=[(123,)],
        metadata={"numDocsScanned": "123"})
    empty = DataTable()
    return [group_by, selection, aggregation, empty]


def test_datatable_v1_payloads_still_decode():
    """Old-version payloads (a version-skewed server mid-rollout) decode
    bit-for-bit equal to what the v1 reader produced."""
    for dt in _sample_tables():
        legacy = dt.to_bytes(version=1)
        rt = DataTable.from_bytes(legacy)
        assert rt.rows == dt.rows
        assert rt.columns == dt.columns
        assert rt.metadata == dt.metadata
        assert rt.exceptions == dt.exceptions
        assert rt.num_group_cols == dt.num_group_cols


def test_datatable_columnar_roundtrip_value_equal_to_row_path():
    """The v2 columnar encoding decodes value-equal to the v1 row path
    for every payload kind, including blocks rebuilt via to_block."""
    for dt in _sample_tables():
        via_v1 = DataTable.from_bytes(dt.to_bytes(version=1))
        via_v2 = DataTable.from_bytes(dt.to_bytes())
        assert via_v2.rows == via_v1.rows
        assert via_v2.columns == via_v1.columns
        assert via_v2.metadata == via_v1.metadata
        assert via_v2.exceptions == via_v1.exceptions
        b1, b2 = via_v1.to_block(), via_v2.to_block()
        assert b1.group_map == b2.group_map
        assert b1.agg_intermediates == b2.agg_intermediates
        assert b1.selection_rows == b2.selection_rows


def test_datatable_columnar_preserves_python_types():
    dt = DataTable(kind=3, columns=["i", "f", "s", "o"],
                   rows=[(np.int64(7), np.float64(2.5), "a", True),
                         (8, 3.5, "b", False)])
    rt = DataTable.from_bytes(dt.to_bytes())
    assert rt.rows == [(7, 2.5, "a", True), (8, 3.5, "b", False)]
    assert type(rt.rows[0][0]) is int
    assert type(rt.rows[0][1]) is float
    assert type(rt.rows[0][3]) is bool


def test_datatable_from_block_to_block_roundtrip():
    request = compile_pql(
        "SELECT SUM(m) FROM t GROUP BY d1, d2 TOP 10")
    blk = IntermediateResultsBlock()
    blk.group_map = {("a", 1): [2.0], ("b", 2): [3.0]}
    dt = DataTable.from_block(request, blk)
    rt = DataTable.from_bytes(dt.to_bytes())
    assert rt.to_block().group_map == blk.group_map
