"""Broker HTTP API: the /query endpoint clients talk to.

Parity: pinot-broker/.../api/resources/PinotClientRequest.java:67 (GET
/query?pql=...) and :95 (POST /query {"pql": ...}), plus the broker admin
app's /health and a /metrics view of the registry. Auth tokens arrive as
`Authorization: Bearer <token>` and become the RequesterIdentity the
access-control SPI sees.
"""
from __future__ import annotations

import asyncio
import math
import os
from typing import Optional

from pinot_tpu.broker.access_control import RequesterIdentity
from pinot_tpu.broker.request_handler import BrokerRequestHandler
from pinot_tpu.broker.routing import RoutingError
from pinot_tpu.common.table_name import (offline_table, raw_table,
                                         realtime_table, table_type)
from pinot_tpu.transport.http import (ApiServer, HttpRequest, HttpResponse,
                                      metrics_response)


def _retrying_response(resp, status: int, retry_s: float) -> HttpResponse:
    """429/503 share one Retry-After surface: whole seconds, floor 1."""
    return HttpResponse.of_json(
        resp.to_json(), status=status,
        headers={"Retry-After": str(max(1, math.ceil(retry_s)))})


class BrokerApiServer(ApiServer):
    """HTTP front door for one BrokerRequestHandler.

    `inline` (or PINOT_TPU_BROKER_INLINE=1): run the whole query
    pipeline — compile, route, scatter await, reduce — on the API's own
    event loop via `handle_async`, with NO executor hop and no second
    loop thread. On a single-core host every cross-thread wakeup is a
    self-pipe syscall plus GIL churn (~1ms measured under load), so the
    inline shape is what the serving-plane benchmarks run. Exclusive
    with the sync `handle()` facade: once inline, the TCP data-plane
    connections live on THIS loop, so queries must all enter through
    HTTP (the multi-process broker's only entry point anyway).
    """

    def __init__(self, handler: BrokerRequestHandler,
                 inline: Optional[bool] = None):
        super().__init__()
        self.handler = handler
        if inline is None:
            inline = os.environ.get("PINOT_TPU_BROKER_INLINE", "0") != "0"
        self.inline = bool(inline)
        self.router.add("GET", "/query", self._get_query)
        self.router.add("POST", "/query", self._post_query)
        self.router.add("GET", "/health", self._health)
        self.router.add("GET", "/metrics", self._metrics)
        # operator debug views (parity: the broker debug resources —
        # RoutingTables + TimeBoundary endpoints)
        self.router.add("GET", "/debug/routingTable/{table}",
                        self._debug_routing)
        self.router.add("GET", "/debug/timeBoundary/{table}",
                        self._debug_time_boundary)
        # rolling per-table operator stats (obs profiler) + slow-log
        # status
        self.router.add("GET", "/debug/tableStats", self._table_stats)
        self.router.add("GET", "/debug/tableStats/{table}",
                        self._table_stats)
        self.router.add("GET", "/debug/slowLog", self._slow_log)
        # ingress-control operator views: per-table/tenant token-bucket
        # state and the broker result cache
        self.router.add("GET", "/debug/quotas", self._quotas)
        self.router.add("GET", "/debug/resultCache", self._result_cache)
        # one-scrape leak-gate rollup for the soak harness / operators
        self.router.add("GET", "/debug/health", self._debug_health)
        # chaos plane: arm/clear/inspect transport fault windows when
        # the broker was started with a FaultInjectingTransport
        # (PINOT_TPU_BROKER_FAULTS=1)
        self.router.add("POST", "/debug/faults", self._inject_fault)
        self.router.add("DELETE", "/debug/faults", self._clear_faults)
        self.router.add("GET", "/debug/faults", self._fault_counts)

    def stop(self) -> None:
        if self.inline and self._loop is not None:
            # the data-plane connections live on THIS loop — close them
            # here (awaited, so reader tasks unwind) before the loop
            # dies, or their read loops are GC'd mid-coroutine
            try:
                self._loop.run(self.handler.router.transport.close())
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        super().stop()

    @staticmethod
    def _identity(request: HttpRequest) -> RequesterIdentity:
        parts = request.headers.get("authorization", "").split(None, 1)
        token = parts[1].strip() if len(parts) == 2 and \
            parts[0].lower() == "bearer" else None
        return RequesterIdentity(client_address=request.client,
                                 token=token or None)

    async def _run_query(self, pql: str, identity: RequesterIdentity,
                         force_trace: bool = False) -> HttpResponse:
        if self.inline:
            # single-loop serving: pipeline runs right here; the only
            # await inside is the scatter-gather network wait
            resp = await self.handler.handle_async(pql, identity,
                                                   force_trace)
        else:
            # the broker handler owns its own event loop (per-server
            # TCP connections live there); hop through its sync facade
            # off-thread
            loop = asyncio.get_running_loop()
            resp = await loop.run_in_executor(
                None, lambda: self.handler.handle(pql, identity,
                                                  force_trace))
        # quota rejections surface as real 429s with Retry-After derived
        # from the token bucket's refill time, so well-behaved clients
        # back off instead of hammering the retry loop
        if resp.exceptions and \
                resp.exceptions[0].get("errorCode") == 429:
            retry_s = getattr(resp, "retry_after_s", None) or \
                resp.exceptions[0].get("retryAfterSeconds") or 1.0
            return _retrying_response(resp, 429, retry_s)
        # a query FULLY lost to server-busy shedding (retry_after_s is
        # only set on that path in _finish) mirrors the 429 story as a
        # real HTTP 503 + Retry-After — clients keying backoff on the
        # status code must see overload, not a 200 that invites an
        # instant retry. Partial responses that recovered data stay 200.
        if getattr(resp, "retry_after_s", None) and \
                any(e.get("errorCode") == 503 for e in resp.exceptions):
            return _retrying_response(resp, 503, resp.retry_after_s)
        return HttpResponse.of_json(resp.to_json())

    async def _get_query(self, request: HttpRequest) -> HttpResponse:
        pql = request.query.get("pql") or request.query.get("sql")
        if not pql:
            return HttpResponse.error(400, "missing ?pql= parameter")
        return await self._run_query(pql, self._identity(request))

    async def _post_query(self, request: HttpRequest) -> HttpResponse:
        try:
            body = request.json() or {}
        except ValueError:
            return HttpResponse.error(400, "invalid JSON body")
        pql = body.get("pql") or body.get("sql")
        if not pql:
            return HttpResponse.error(400, 'missing "pql" in body')
        return await self._run_query(pql, self._identity(request),
                                     force_trace=bool(body.get("trace")))

    async def _health(self, request: HttpRequest) -> HttpResponse:
        return HttpResponse(200, b"OK", content_type="text/plain")

    async def _metrics(self, request: HttpRequest) -> HttpResponse:
        return metrics_response(self.handler.metrics, request)

    async def _table_stats(self, request: HttpRequest) -> HttpResponse:
        """Rolling operator stats honor the same ACL as the other
        debug views — per-table scan counts and recent query profiles
        are table metadata. The all-tables view filters to what the
        caller may see rather than denying outright."""
        table = request.path_params.get("table")
        if table is not None:
            denied = self._check_debug_access(request, table)
            if denied is not None:
                return denied
            return HttpResponse.of_json(
                self.handler.table_stats.snapshot(table))
        # filter by ACL FIRST, then copy only the visible tables —
        # snapshotting everything just to discard denied entries would
        # deep-copy their 64-profile rings for nothing
        stats = self.handler.table_stats
        allowed = {t: stats.snapshot(t)
                   for t in stats.table_names()
                   if self._check_debug_access(request, t) is None}
        return HttpResponse.of_json(allowed)

    async def _quotas(self, request: HttpRequest) -> HttpResponse:
        # per-table debug view: honor the same ACL as /debug/tableStats
        # (quota rates, token counts and tenant keys are table metadata)
        stats = self.handler.quota.stats()
        allowed = {t: s for t, s in stats.items()
                   if self._check_debug_access(request, t) is None}
        return HttpResponse.of_json(allowed)

    async def _result_cache(self, request: HttpRequest) -> HttpResponse:
        # aggregate counters only (entries/bytes/hits/misses) — no
        # table names or tenant keys, so no per-table ACL dimension
        return HttpResponse.of_json(self.handler.result_cache.stats())

    async def _debug_health(self, request: HttpRequest) -> HttpResponse:
        """One-scrape leak-gate rollup (obs/health.py): RSS, residency
        ledger, exchange held-bytes, plus the broker's result-cache
        counters — what the soak's flatness detectors poll."""
        from pinot_tpu.obs.health import health_rollup
        extra = {}
        try:
            extra = {f"resultCache.{k}": v
                     for k, v in self.handler.result_cache.stats().items()
                     if isinstance(v, (int, float))}
        except Exception:  # noqa: BLE001 — cache stats are best-effort
            pass
        return HttpResponse.of_json(
            health_rollup("broker", self.handler.metrics, extra=extra))

    # -- chaos plane: transport fault windows ------------------------------
    def _fault_transport(self):
        t = getattr(self.handler.router, "transport", None)
        return t if hasattr(t, "inject") and hasattr(t, "clear") else None

    async def _inject_fault(self, request: HttpRequest) -> HttpResponse:
        """Arm a transport fault window against one server — the HTTP
        face of FaultInjectingTransport.inject, so the chaos
        coordinator can open latency/drop windows inside a real broker
        process. 409 unless the broker runs the fault-wrapped transport
        (PINOT_TPU_BROKER_FAULTS=1)."""
        t = self._fault_transport()
        if t is None:
            return HttpResponse.error(
                409, "broker transport has no fault arm (start with "
                "PINOT_TPU_BROKER_FAULTS=1)")
        try:
            body = request.json() or {}
        except ValueError:
            return HttpResponse.error(400, "invalid JSON body")
        server, kind = body.get("server"), body.get("kind")
        if not server or not kind:
            return HttpResponse.error(400, '"server" and "kind" required')
        from pinot_tpu.common.faults import FaultSpec
        try:
            spec = FaultSpec(
                kind=kind,
                latency_s=float(body.get("latencyS", 0.0)),
                segments=tuple(body.get("segments", [])),
                probability=float(body.get("probability", 1.0)),
                times=body.get("times"))
        except (ValueError, TypeError) as e:
            return HttpResponse.error(400, str(e))
        t.inject(server, spec)
        return HttpResponse.of_json(
            {"status": "armed", "server": server, "kind": kind})

    async def _clear_faults(self, request: HttpRequest) -> HttpResponse:
        t = self._fault_transport()
        if t is None:
            return HttpResponse.error(
                409, "broker transport has no fault arm")
        server = request.query.get("server")
        t.clear(server or None)
        return HttpResponse.of_json(
            {"status": "cleared", "server": server or "*"})

    async def _fault_counts(self, request: HttpRequest) -> HttpResponse:
        t = self._fault_transport()
        if t is None:
            return HttpResponse.of_json({"enabled": False})
        return HttpResponse.of_json(
            {"enabled": True,
             "injected": {f"{s}:{k}": n
                          for (s, k), n in sorted(t.injected.items())}})

    async def _slow_log(self, request: HttpRequest) -> HttpResponse:
        sl = self.handler.slow_log
        if sl is None:
            return HttpResponse.of_json({"enabled": False})
        return HttpResponse.of_json({"enabled": True, **sl.stats()})

    def _check_debug_access(self, request: HttpRequest, table: str):
        """Debug views honor the same access-control SPI as /query —
        routing assignments are table metadata the ACL governs. The SPI
        takes a BrokerRequest; a minimal one carrying the table name is
        what table-scoped ACLs key on."""
        ac = getattr(self.handler, "access_control", None)
        if ac is None:
            return None
        from pinot_tpu.common.request import BrokerRequest
        probe = BrokerRequest(table_name=raw_table(table))
        if not ac.has_access(self._identity(request), probe):
            return HttpResponse.error(403, "access denied")
        return None

    async def _debug_routing(self, request: HttpRequest) -> HttpResponse:
        """One sampled routing table per physical variant of the table
        (parity: the broker's debug RoutingTables view)."""
        raw = request.path_params["table"]
        denied = self._check_debug_access(request, raw)
        if denied is not None:
            return denied
        names = [raw] if table_type(raw) != "NONE" else \
            [offline_table(raw), realtime_table(raw)]
        out = {}
        for name in names:
            try:
                out[name] = self.handler.routing.route(name)
            except RoutingError:
                continue
        if not out:
            return HttpResponse.error(404, f"no routing for {raw}")
        return HttpResponse.of_json(out)

    async def _debug_time_boundary(self, request: HttpRequest
                                   ) -> HttpResponse:
        """The boundary the TimeBoundaryService holds for the table's
        offline variant (parity: the TimeBoundary debug view).
        "appliedToQueries" says whether the broker actually attaches it
        — only hybrid tables (both variants routable) get the split."""
        raw = raw_table(request.path_params["table"])
        denied = self._check_debug_access(request, raw)
        if denied is not None:
            return denied
        tb = self.handler.time_boundary
        info = tb.get(offline_table(raw)) if tb is not None else None
        if info is None:
            return HttpResponse.error(404, f"no time boundary for {raw}")
        hybrid = self.handler.routing.has_table(offline_table(raw)) and \
            self.handler.routing.has_table(realtime_table(raw))
        return HttpResponse.of_json({
            "timeColumn": info.column, "timeValue": str(info.value),
            "appliedToQueries": hybrid})
