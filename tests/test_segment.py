"""Unit tests: dictionaries, bit-packing, inverted index, bloom, creator.

Mirrors the reference's per-index unit tier (core/src/test/.../index/,
.../io/) — round-trips + hand-computed goldens.
"""
import os
import tempfile

import numpy as np
import pytest

from pinot_tpu.common.datatype import DataType
from pinot_tpu.segment.bloom import BloomFilter
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.fwd import (bits_required, mv_to_padded, pack_bits,
                                   unpack_bits)
from pinot_tpu.segment.inverted import (InvertedIndexReader,
                                        InvertedIndexWriter, bitmap_to_mask)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(3)
    for num_bits in (1, 2, 3, 5, 7, 8, 13, 17, 24, 31):
        n = int(rng.integers(1, 5000))
        ids = rng.integers(0, 2**num_bits, n).astype(np.int32)
        words = pack_bits(ids, num_bits)
        assert words.dtype == np.uint32
        assert len(words) == (n * num_bits + 31) // 32
        out = unpack_bits(words, num_bits, n)
        np.testing.assert_array_equal(out, ids)


def test_bits_required():
    assert bits_required(1) == 1
    assert bits_required(2) == 1
    assert bits_required(3) == 2
    assert bits_required(256) == 8
    assert bits_required(257) == 9


def test_dictionary_numeric_lookups():
    d = Dictionary.build(DataType.INT, np.array([5, 3, 9, 3, 5], np.int32))
    assert d.cardinality == 3
    assert list(d.values) == [3, 5, 9]
    assert d.index_of(5) == 1
    assert d.index_of(4) == -1
    # ranges → half-open id intervals
    assert d.range_to_id_interval(3, 9, True, True) == (0, 3)
    assert d.range_to_id_interval(3, 9, False, False) == (1, 2)
    assert d.range_to_id_interval(None, 5, True, False) == (0, 1)
    assert d.range_to_id_interval(4, None, True, True) == (1, 3)
    # fractional bounds on int dictionary
    assert d.range_to_id_interval("3.5", None, True, True) == (1, 3)


def test_dictionary_string_roundtrip(tmp_path):
    vals = np.array(["b", "a", "c", "a", "ß-unicode"], dtype=object)
    d = Dictionary.build(DataType.STRING, vals)
    d.save(str(tmp_path), "col")
    d2 = Dictionary.load(str(tmp_path), "col", DataType.STRING)
    assert list(d2.values) == sorted(set(vals))
    assert d2.index_of("ß-unicode") >= 0
    ids = d2.encode(vals)
    np.testing.assert_array_equal(d2.decode(ids), vals)


def test_inverted_index_postings(tmp_path):
    ids = np.array([2, 0, 1, 2, 2, 0], dtype=np.int32)
    InvertedIndexWriter.write(str(tmp_path), "c", ids, 3)
    r = InvertedIndexReader.load(str(tmp_path), "c", len(ids))
    assert list(r.postings(0)) == [1, 5]
    assert list(r.postings(1)) == [2]
    assert list(r.postings(2)) == [0, 3, 4]
    assert r.count(2) == 3
    assert r.count_range(0, 2) == 3
    words = r.bitmap_words(np.array([0, 2]))
    mask = bitmap_to_mask(words, len(ids))
    np.testing.assert_array_equal(mask,
                                  [True, True, False, True, True, True])


def test_bloom_filter_roundtrip(tmp_path):
    bf = BloomFilter.with_capacity(100, 0.01)
    for v in ("alpha", "beta", 42):
        bf.add(v)
    bf.save(str(tmp_path), "c")
    bf2 = BloomFilter.load(str(tmp_path), "c")
    assert bf2.might_contain("alpha")
    assert bf2.might_contain(42)
    misses = sum(bf2.might_contain(f"absent-{i}") for i in range(200))
    assert misses <= 10  # fpp bound with slack


def test_mv_to_padded():
    flat = np.array([1, 2, 0, 3, 4, 5], dtype=np.int32)
    offsets = np.array([0, 2, 3, 6], dtype=np.int64)
    padded = mv_to_padded(flat, offsets, fill_value=9)
    np.testing.assert_array_equal(
        padded, [[1, 2, 9], [0, 9, 9], [3, 4, 5]])


def test_sorted_column_detected(tmp_path):
    from pinot_tpu.common.schema import Schema, dimension
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import ImmutableSegmentLoader
    schema = Schema("t", [dimension("s", DataType.INT),
                          dimension("u", DataType.INT)])
    cols = {"s": np.arange(100, dtype=np.int32) // 10,
            "u": np.arange(100, dtype=np.int32)[::-1] % 7}
    SegmentCreator(schema).build(cols, str(tmp_path))
    seg = ImmutableSegmentLoader.load(str(tmp_path))
    assert seg.metadata.columns["s"].sorted
    assert not seg.metadata.columns["u"].sorted
    ds = seg.data_source("s")
    assert ds.sorted_ranges is not None
    np.testing.assert_array_equal(ds.sorted_ranges[3], [30, 40])
